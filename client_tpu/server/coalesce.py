"""Decoupled-response coalescing shared by the stream frontends.

Per-message framing cost (protobuf + HTTP/2 write on gRPC, JSON + chunked
write on SSE) is the served token path's ceiling once decode waves outrun
the writer.  Requests that opt in via the ``response_coalesce`` parameter
let a frontend merge a stream's *backlogged* non-final responses into one
message whose outputs are the rows concatenated along axis 0 (a generation
stream's k ``[1]``-shaped TOKEN/INDEX rows become one ``[k]`` tensor).

Contract preserved: per-request response order (merging only ever combines
already-ordered consecutive rows of one request), finals/errors never merge,
and a dtype or trailing-shape drift starts a new message instead of blowing
up the concat.  Off backlog every response still ships alone, so latency is
unchanged; throughput rises exactly when the writer is behind.

Reference anchor: the decoupled bidi-stream contract this optimizes within
— many responses per request, ``triton_final_response`` terminating
(/root/reference/src/c++/library/grpc_client.h:99-312, consumed by
/root/reference/src/python/examples/simple_grpc_custom_repeat.py).  The
reference has no counterpart optimization (its servers are opaque); the
opt-in parameter keeps the wire behavior reference-compatible by default.
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.types import InferRequest, InferResponse

# Per-flush merge bound shared by every stream writer: caps one message's
# concat memory and wire size even when the pending limit is raised.
COALESCE_MAX = 512


def mergeable(req: InferRequest, resp: InferResponse) -> bool:
    """May this response join a coalesce run at all?"""
    return (resp.error is None and not resp.final
            and bool(req.parameters.get("response_coalesce"))
            and all(getattr(a, "ndim", 0) >= 1
                    for a in resp.outputs.values()))


def run_compatible(prev: InferResponse, resp: InferResponse) -> bool:
    """Do consecutive responses concatenate cleanly (same names, dtypes,
    trailing dims — axis 0 is the merge axis)?"""
    if set(prev.outputs) != set(resp.outputs):
        return False
    return all(prev.outputs[n].dtype == a.dtype
               and prev.outputs[n].shape[1:] == a.shape[1:]
               for n, a in resp.outputs.items())


def drain_run(first: InferResponse, get_nowait, req: InferRequest,
              cap: int = COALESCE_MAX):
    """Single-request run builder (the SSE writer's shape: one stream, one
    request): starting at ``first``, pull already-queued responses while
    they merge cleanly.  ``get_nowait()`` returns the next queued response
    or None when the queue is empty.  Returns ``(merged, leftover)`` where
    ``leftover`` is the first non-merging response pulled (caller emits it
    after ``merged``) or None.

    The gRPC stream writer keeps its own run builder: it interleaves many
    requests per RPC and must also thread error items and backlog
    accounting through the drain — the multi-request variant lives there.
    """
    run = [first]
    while len(run) < cap and mergeable(req, run[-1]):
        nxt = get_nowait()
        if nxt is None:
            break
        if mergeable(req, nxt) and run_compatible(run[-1], nxt):
            run.append(nxt)
            continue
        return merge(run), nxt
    return merge(run), None


def merge(resps: list[InferResponse]) -> InferResponse:
    """One response for a run: every output concatenated along axis 0."""
    if len(resps) == 1:
        return resps[0]
    last = resps[-1]
    return InferResponse(
        model_name=last.model_name,
        model_version=last.model_version,
        request_id=last.request_id,
        outputs={name: np.concatenate([r.outputs[name] for r in resps],
                                      axis=0)
                 for name in last.outputs},
        parameters=last.parameters,
        final=False,
        times=last.times,
    )
