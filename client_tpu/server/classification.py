"""Classification extension: top-K "score:index[:label]" string outputs.

The v2 classification extension lets a client request an output as top-K
classification strings instead of raw scores (reference client side:
``InferRequestedOutput`` class_count, common.h:359-431 and the image_client's
classification parse). Labels come from the model config's
``parameters["labels"][output_name]`` list.
"""

from __future__ import annotations

import numpy as np


def classify_output(scores: np.ndarray, count: int,
                    labels: list[str] | None) -> np.ndarray:
    """[batch, classes] scores -> [batch, count] BYTES of 'score:idx[:label]'."""
    if scores.ndim == 1:
        scores = scores[None, :]
    batch = scores.shape[0]
    flat = scores.reshape(batch, -1)
    k = min(count, flat.shape[1])
    top = np.argsort(-flat, axis=1)[:, :k]
    out = np.empty((batch, k), dtype=np.object_)
    for b in range(batch):
        for j in range(k):
            idx = int(top[b, j])
            entry = f"{flat[b, idx]:f}:{idx}"
            if labels and idx < len(labels):
                entry += f":{labels[idx]}"
            out[b, j] = entry.encode("utf-8")
    return out
