"""HTTP/REST frontend: the KServe v2 endpoint surface.

Routes mirror what the reference client calls (http_client.cc:1241-1245 for
infer, http_client.h:112-341 for the control plane): health, metadata,
config, stats, repository control, shared-memory registration, and
``POST /v2/models/<m>[/versions/<v>]/infer`` with the JSON + binary-tensor
body split by ``Inference-Header-Content-Length``. Request bodies may be
deflate/gzip compressed (the reference client can send both,
http_client.cc:122-198); responses compress when the client accepts it.

Implementation: stdlib ThreadingHTTPServer — each connection gets a thread;
actual device work is serialized by the engine's per-model schedulers, so the
frontend threads only do framing.
"""

from __future__ import annotations

import gzip
import json
import logging
from client_tpu import config as envcfg
import re
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

_log = logging.getLogger("client_tpu")

from client_tpu.engine.engine import TpuEngine
from client_tpu.engine.types import EngineError, InferRequest, OutputRequest
from client_tpu.faults import FaultInjected
from client_tpu.observability.tracing import (
    TraceContext,
    server_timing_header,
)
from client_tpu.protocol import rest
from client_tpu.protocol.loadreport import LOAD_HEADER, encode_header
from client_tpu.protocol.pushback import (
    RETRY_AFTER_HEADER,
    format_retry_after_s,
)
from client_tpu.server.classification import classify_output

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/v2/health/live$"), "health_live"),
    ("GET", re.compile(r"^/v2/health/ready$"), "health_ready"),
    ("GET", re.compile(r"^/v2(?:/)?$"), "server_metadata"),
    ("GET", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?/ready$"), "model_ready"),
    ("GET", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?/config$"), "model_config"),
    ("GET", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?/stats$"), "model_stats"),
    ("GET", re.compile(r"^/v2/models/stats$"), "all_stats"),
    ("GET", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?$"), "model_metadata"),
    ("POST", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?/infer$"), "infer"),
    ("POST", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?"
                        r"/generate$"), "generate"),
    ("POST", re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?"
                        r"/generate_stream$"), "generate_stream"),
    ("POST", re.compile(r"^/v2/repository/index$"), "repo_index"),
    ("POST", re.compile(r"^/v2/repository/models/([^/]+)/load$"), "repo_load"),
    ("POST", re.compile(r"^/v2/repository/models/([^/]+)/unload$"), "repo_unload"),
    ("GET", re.compile(r"^/v2/(systemsharedmemory|cudasharedmemory|tpusharedmemory)"
                       r"(?:/region/([^/]+))?/status$"), "shm_status"),
    ("POST", re.compile(r"^/v2/(systemsharedmemory|cudasharedmemory|tpusharedmemory)"
                        r"/region/([^/]+)/register$"), "shm_register"),
    ("POST", re.compile(r"^/v2/(systemsharedmemory|cudasharedmemory|tpusharedmemory)"
                        r"(?:/region/([^/]+))?/unregister$"), "shm_unregister"),
    ("GET", re.compile(r"^/v2/shm/ring(?:/([^/]+))?/status$"), "ring_status"),
    ("POST", re.compile(r"^/v2/shm/ring/([^/]+)/register$"), "ring_register"),
    ("POST", re.compile(r"^/v2/shm/ring(?:/([^/]+))?/unregister$"),
     "ring_unregister"),
    ("POST", re.compile(r"^/v2/shm/ring/([^/]+)/doorbell$"), "ring_doorbell"),
    ("GET", re.compile(r"^/v2/shm/dataset(?:/([^/]+))?/status$"),
     "dataset_status"),
    ("POST", re.compile(r"^/v2/shm/dataset/([^/]+)/register$"),
     "dataset_register"),
    ("POST", re.compile(r"^/v2/shm/dataset(?:/([^/]+))?/unregister$"),
     "dataset_unregister"),
    ("GET", re.compile(r"^/v2/trace/setting$"), "trace_setting"),
    ("POST", re.compile(r"^/v2/trace/setting$"), "trace_update"),
    ("GET", re.compile(r"^/v2/trace/requests$"), "trace_requests"),
    ("GET", re.compile(r"^/v2/events$"), "events"),
    ("GET", re.compile(r"^/v2/slo$"), "slo"),
    ("GET", re.compile(r"^/v2/profile$"), "profile"),
    ("GET", re.compile(r"^/v2/costs$"), "costs"),
    ("GET", re.compile(r"^/v2/qos$"), "qos"),
    ("GET", re.compile(r"^/v2/timeseries$"), "timeseries"),
    ("GET", re.compile(r"^/v2/memory$"), "memory"),
    ("GET", re.compile(r"^/v2/load$"), "load"),
    ("GET", re.compile(r"^/v2/debug/bundles$"), "debug_bundles"),
    ("GET", re.compile(r"^/v2/debug/bundles/([^/]+)$"), "debug_bundle"),
    ("POST", re.compile(r"^/v2/debug/capture$"), "debug_capture"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Nagle on the server side interacts with client delayed-ACK to add a
    # ~40ms stall per response (the C++ client sets TCP_NODELAY; the server
    # must too — measured 44ms -> <2ms round-trip on the perf harness).
    disable_nagle_algorithm = True
    # Buffer response writes so header+body leave in one segment.
    wbufsize = 64 * 1024

    def handle_expect_100(self):
        # With buffered wfile the interim '100 Continue' would sit in the
        # buffer while we block reading the body — flush it out explicitly.
        result = super().handle_expect_100()
        self.wfile.flush()
        return result
    engine: TpuEngine = None  # patched onto the subclass by HttpInferenceServer
    verbose = False

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003
        if self.verbose:
            super().log_message(fmt, *args)

    def _dispatch(self, method: str) -> None:
        try:
            # Chaos site: before any request byte past the headers is
            # consumed. A "drop" action closes the keep-alive socket with
            # no response — exactly the stale-socket/idle-timeout shape
            # the client-side replay and RetryPolicy must absorb.
            try:
                self.engine.faults.fire("http.pre_read")
            except FaultInjected as exc:
                if exc.kind == "drop":
                    self.close_connection = True
                    return
                # The injected error must still drain the request body —
                # the same keep-alive hazard the normal path documents
                # below: unread POST bytes would prefix the next request
                # line on this socket and desync the connection.
                try:
                    if method == "POST":
                        self.rfile.read(
                            int(self.headers.get("Content-Length", 0) or 0))
                except (OSError, ValueError):
                    self.close_connection = True
                self._send_error(exc.status or 503, str(exc))
                return
            # Drain the request body up front: handlers that ignore it (e.g.
            # repository index with an empty JSON body) must not leave bytes
            # in the keep-alive stream, or they would prefix the next
            # request line and desync the connection.
            self._raw_body = (self.rfile.read(
                int(self.headers.get("Content-Length", 0) or 0))
                if method == "POST" else b"")
            for m, pat, name in _ROUTES:
                if m != method:
                    continue
                match = pat.match(self.path.split("?")[0])
                if match:
                    getattr(self, "h_" + name)(*match.groups())
                    return
            self._send_error(404, f"no route for {method} {self.path}")
        except EngineError as exc:
            self._send_error(exc.status, str(exc),
                             retry_after_s=getattr(exc, "retry_after_s",
                                                   None))
        except (json.JSONDecodeError, ValueError, KeyError, zlib.error,
                gzip.BadGzipFile) as exc:
            self._send_error(400, f"malformed request: {exc!r}")
        except BrokenPipeError:
            pass
        except Exception as exc:  # noqa: BLE001
            self._send_error(500, f"internal error: {exc}")
        finally:
            # A draining server must shed its keep-alive sockets: the
            # accept loop is already stopped, so a pooled client (the L7
            # router, a probe loop) holding a live connection would keep
            # this "drained" frontend answering indefinitely. Closing
            # after the in-flight response is what lets the fleet
            # observe the replica as gone.
            try:
                if not self.engine.is_ready():
                    self.close_connection = True
            # tpulint: allow[swallowed-exception] health probe must not break the response already sent
            except Exception:  # noqa: BLE001 — health probe must not
                pass           # break the response already sent

    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def _read_body(self) -> bytes:
        body = self._raw_body
        encoding = (self.headers.get("Content-Encoding") or "").lower()
        if encoding == "deflate":
            body = zlib.decompress(body)
        elif encoding == "gzip":
            body = gzip.decompress(body)
        elif encoding:
            raise EngineError(f"unsupported Content-Encoding '{encoding}'", 415)
        return body

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              extra_headers: dict | None = None) -> None:
        accept = (self.headers.get("Accept-Encoding") or "").lower()
        headers = dict(extra_headers or {})
        if body and "gzip" in accept:
            body = gzip.compress(body, compresslevel=1)
            headers["Content-Encoding"] = "gzip"
        elif body and "deflate" in accept:
            body = zlib.compress(body, level=1)
            headers["Content-Encoding"] = "deflate"
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _send_json(self, obj, status: int = 200) -> None:
        self._send(status, json.dumps(obj).encode("utf-8"))

    def _send_error(self, status: int, msg: str,
                    retry_after_s: float | None = None) -> None:
        # Admission/drain sheds carry server pushback: Retry-After in
        # fractional seconds (our RetryPolicy parses floats; proxies that
        # only read integral seconds round down harmlessly). The shared
        # formatter keeps the text identical to the gRPC metadata form.
        headers = {}
        if retry_after_s is not None:
            headers[RETRY_AFTER_HEADER] = format_retry_after_s(retry_after_s)
        if status in (429, 503):
            # A shed/drain rejection names the health state it came from,
            # so an L7 router can tell a DRAINING replica (stop routing,
            # don't breaker it) from an overloaded or dead one.
            try:
                headers["X-Health-State"] = self.engine.health_state()
            # tpulint: allow[swallowed-exception] telemetry must not mask the error being reported
            except Exception:  # noqa: BLE001 — telemetry must not mask
                pass           # the error being reported
        try:
            self._send(status, json.dumps({"error": msg}).encode("utf-8"),
                       extra_headers=headers or None)
        # tpulint: allow[swallowed-exception] peer may have gone away
        except Exception:  # noqa: BLE001 — peer may have gone away
            pass

    # -- handlers -----------------------------------------------------------

    def h_health_live(self):
        self._send(200 if self.engine.is_live() else 400, b"")

    def h_health_ready(self):
        # Readiness with nuance: 200 while serving (READY or DEGRADED —
        # degraded still accepts work), 503 while DRAINING/down. The state
        # rides in both the JSON body and a header so HEAD-style probes
        # that ignore bodies can still read it.
        state = (self.engine.health_state()
                 if hasattr(self.engine, "health_state")
                 else ("READY" if self.engine.is_ready() else "DRAINING"))
        ready = self.engine.is_ready()
        self._send(200 if ready else 503,
                   json.dumps({"state": state}).encode("utf-8"),
                   extra_headers={"X-Health-State": state})

    def h_server_metadata(self):
        md = self.engine.server_metadata()
        # trace (/v2/trace/setting) and generate (/v2/models/<m>/generate*)
        # are HTTP-frontend routes, so only this frontend advertises them.
        md["extensions"] = list(md["extensions"]) + ["trace", "generate"]
        self._send_json(md)

    def h_model_ready(self, name, version=None):
        ready = self.engine.model_is_ready(name, version or "")
        self._send(200 if ready else 400, b"")

    def h_model_metadata(self, name, version=None):
        self._send_json(self.engine.model_metadata(name, version or ""))

    def h_model_config(self, name, version=None):
        self._send_json(self.engine.model_config(name, version or ""))

    def h_model_stats(self, name, version=None):
        self._send_json(self.engine.model_statistics(name, version or ""))

    def h_all_stats(self):
        self._send_json(self.engine.model_statistics())

    def h_repo_index(self):
        self._send_json(self.engine.repository_index())

    def h_repo_load(self, name):
        body = self._read_body()
        params = {}
        if body:
            try:
                params = json.loads(body).get("parameters", {}) or {}
            except (ValueError, AttributeError):
                raise EngineError("malformed load request body", 400)
        if params:
            # Same policy as the gRPC frontend: explicit config/file
            # overrides are not supported by the in-process repository —
            # reject rather than silently load the on-disk config.
            raise EngineError(
                "load parameters (config/file overrides) are not supported",
                400)
        self.engine.load_model(name)
        self._send_json({})

    def h_repo_unload(self, name):
        body = self._read_body()
        unload_dependents = False
        if body:
            try:
                params = json.loads(body).get("parameters", {}) or {}
            except (ValueError, AttributeError):
                raise EngineError("malformed unload request body", 400)
            unload_dependents = bool(params.get("unload_dependents", False))
        self.engine.unload_model(name, unload_dependents=unload_dependents)
        self._send_json({})

    # -- shared memory control plane ----------------------------------------

    def _shm_manager(self, kind: str):
        if kind == "systemsharedmemory":
            mgr = self.engine.system_shm
        else:  # cudasharedmemory is served by the TPU region manager
            mgr = self.engine.tpu_shm
        if mgr is None:
            raise EngineError(f"{kind} is not enabled on this server", 400)
        return mgr

    OPENMETRICS_CT = "application/openmetrics-text; version=1.0.0; " \
                     "charset=utf-8"

    def h_metrics(self):
        # Content negotiation mirrors prometheus/client_python: a scraper
        # that Accepts application/openmetrics-text gets OpenMetrics 1.0
        # (exemplars, # EOF); everyone else the classic 0.0.4 text format.
        accept = self.headers.get("Accept", "") or ""
        om = "application/openmetrics-text" in accept
        body = self.engine.prometheus_metrics(openmetrics=om)
        self._send(200, body.encode("utf-8"),
                   content_type=(self.OPENMETRICS_CT if om
                                 else "text/plain; version=0.0.4"))

    def h_events(self):
        """Operational event timeline (``/v2/events``). Filters:
        ``?model=`` exact, ``?severity=`` minimum (DEBUG..ERROR),
        ``?category=``, ``?since=<seq>`` exclusive cursor (use the
        previous response's ``next_seq``), ``?since_wall=``/
        ``?until_wall=`` an epoch-seconds window (exclusive lower,
        inclusive upper), ``?limit=<n>`` newest n."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)

        def one(key):
            return (q.get(key) or [None])[0]

        def num(key, cast):
            raw = one(key)
            if raw is None:
                return None
            try:
                return cast(raw)
            except ValueError:
                raise EngineError(f"malformed {key!r} parameter", 400)

        # ``since_wall``/``until_wall`` are the wall-window pair shared
        # with /v2/timeseries; ``since_ts`` predates them and stays as
        # an alias for the lower bound.
        since_wall = num("since_wall", float)
        if since_wall is None:
            since_wall = num("since_ts", float)
        try:
            self._send_json(self.engine.events_export(
                model=one("model"), severity=one("severity"),
                category=one("category"), since_seq=num("since", int),
                since_ts=since_wall,
                until_ts=num("until_wall", float),
                limit=num("limit", int)))
        except ValueError as exc:  # unknown severity name
            raise EngineError(str(exc), 400)

    def h_slo(self):
        """Per-model SLO burn-rate report (``/v2/slo``)."""
        self._send_json(self.engine.slo_snapshot())

    def h_profile(self):
        """Efficiency profiler cost table (``/v2/profile``): per-model/
        per-bucket fill ratios, padding-waste device-seconds, compile
        counts, duty cycle. ``?model=`` filters to one model."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        model = (q.get("model") or [None])[0]
        self._send_json(self.engine.profile_snapshot(model=model))

    def h_costs(self):
        """Per-tenant cost ledger (``/v2/costs``): device-seconds,
        HBM-byte-seconds, queue-seconds, and interference attribution,
        with reconciliation against the profiler and HBM census.
        ``?model=`` filters per-model rows to one model."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        model = (q.get("model") or [None])[0]
        self._send_json(self.engine.costs_snapshot(model=model))

    def h_qos(self):
        """Tenant QoS status (``/v2/qos``): the class table (weights,
        quotas, governor throttle ratios, inflight, shed/preemption
        tallies) plus per-model WFQ lane depths. ``?model=`` narrows
        the lane depths to one model."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        model = (q.get("model") or [None])[0]
        self._send_json(self.engine.qos_snapshot(model=model))

    def h_timeseries(self):
        """Flight-recorder export (``/v2/timeseries``): the 1 Hz signal
        ring. Filters: ``?signal=`` one signal family, ``?model=``
        narrows per-model maps, ``?since=<seq>`` exclusive cursor (use
        the previous response's ``next_seq``), ``?since_wall=``/
        ``?until_wall=`` an epoch-seconds window (exclusive lower,
        inclusive upper), ``?limit=<n>`` newest n."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)

        def one(key):
            return (q.get(key) or [None])[0]

        def num(key, cast):
            raw = one(key)
            if raw is None:
                return None
            try:
                return cast(raw)
            except ValueError:
                raise EngineError(f"malformed {key!r} parameter", 400)

        try:
            self._send_json(self.engine.timeseries_export(
                signal=one("signal"), model=one("model"),
                since_seq=num("since", int),
                since_wall=num("since_wall", float),
                until_wall=num("until_wall", float),
                limit=num("limit", int)))
        except ValueError as exc:  # unknown signal name
            raise EngineError(str(exc), 400)

    def h_memory(self):
        """HBM census report (``/v2/memory``): per-(model, component)
        live device bytes, plan-vs-actual drift, watermark, pressure."""
        self._send_json(self.engine.memory_census())

    def h_load(self):
        """Replica load report (``/v2/load``): the pull form of the
        ``X-Tpu-Load`` response piggyback — health state, in-flight,
        queue depth, EWMA wait estimate, SLO fast-burn, loaded models.
        Routers bootstrap from this and refresh via piggyback."""
        report = self.engine.load_report()
        self._send(200, json.dumps(report.to_json_dict()).encode("utf-8"),
                   extra_headers={LOAD_HEADER: encode_header(report)})

    def h_debug_bundles(self):
        """Incident-blackbox bundle index (``/v2/debug/bundles``):
        retained bundles newest first, retention caps, capture
        counters."""
        self._send_json(self.engine.blackbox_bundles())

    def h_debug_bundle(self, bundle_id):
        """One full incident bundle (``/v2/debug/bundles/{id}``):
        the JSON document ``tools/blackbox_report.py`` renders.
        404 unknown id; 400 malformed id or corrupt bundle — never
        500."""
        self._send_json(self.engine.blackbox_bundles(bundle_id))

    def h_debug_capture(self):
        """Manual incident capture (``POST /v2/debug/capture``). Body
        keys (all optional): ``trigger`` (default ``manual``; an
        automatic trigger name respects debounce/cooldown and may
        return ``{"deduped": true}``), ``incident`` (share one id
        across a fleet), ``note`` (free text stored in the bundle)."""
        body = json.loads(self._read_body() or b"{}")
        if not isinstance(body, dict):
            raise EngineError("request body must be a JSON object", 400)
        self._send_json(self.engine.blackbox_capture(
            str(body.get("trigger") or "manual"),
            incident=body.get("incident") or None,
            note=body.get("note") or None))

    def h_trace_setting(self):
        self._send_json(self.engine.trace_setting())

    def h_trace_update(self):
        body = json.loads(self._read_body() or b"{}")
        self._send_json(self.engine.update_trace_setting(body))

    def h_trace_requests(self):
        """Chrome trace-event JSON of recently traced requests; open the
        result in chrome://tracing or Perfetto. ``?trace_id=<32hex>``
        filters to one request's timeline."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        trace_id = (q.get("trace_id") or [None])[0]
        self._send_json(self.engine.request_trace_export(trace_id))

    def h_shm_status(self, kind, region=None):
        self._send_json(self._shm_manager(kind).status(region))

    def h_shm_register(self, kind, region):
        body = json.loads(self._read_body() or b"{}")
        self._shm_manager(kind).register_from_json(region, body)
        self._send_json({})

    def h_shm_unregister(self, kind, region=None):
        self._read_body()
        self._shm_manager(kind).unregister(region)
        self._send_json({})

    # -- shm slot ring (zero-copy data plane; engine.shmring) ---------------

    def h_ring_status(self, name=None):
        self._send_json(self.engine.ring_shm.status(name))

    def h_ring_register(self, name):
        body = json.loads(self._read_body() or b"{}")
        self.engine.ring_shm.register_from_json(name, body)
        self._send_json({})

    def h_ring_unregister(self, name=None):
        self._read_body()
        self.engine.ring_shm.unregister(name)
        self._send_json({})

    def h_ring_doorbell(self, name):
        """The batched doorbell: one POST admits a whole span of FILLED
        slots; completions land in shm, not in this response."""
        spec = json.loads(self._read_body() or b"{}")
        self._send_json(self.engine.ring_doorbell(name, spec))

    # -- staged datasets (many-producer fan-in; engine.staged) --------------

    def h_dataset_status(self, name=None):
        self._send_json(self.engine.staged_shm.status(name))

    def h_dataset_register(self, name):
        body = json.loads(self._read_body() or b"{}")
        self.engine.staged_shm.register_from_json(name, body)
        self._send_json({})

    def h_dataset_unregister(self, name=None):
        self._read_body()
        self.engine.staged_shm.unregister(name)
        self._send_json({})

    # -- inference ----------------------------------------------------------

    def h_infer(self, name, version=None):
        req = self._parse_infer_request(name, version)
        resp = self.engine.infer(req)
        self._send_infer_response(req, resp)

    # Stall guard for the generate endpoints: how long to wait for the
    # next response of an in-flight stream before cancelling it.
    GENERATE_STALL_TIMEOUT_S = 300.0

    def h_generate(self, name, version=None):
        """Non-streaming generate: run a (possibly decoupled) model and
        return every response as a JSON array. The streaming variant below
        is the live-token path; this one is the curl-friendly collector."""
        req = self._parse_generate_request(name, version)
        out = []
        for resp in self._stream_responses(req):
            if resp.error is not None:
                raise resp.error
            if resp.final and not resp.outputs:
                continue
            out.append(self._json_response_dict(resp))
        self._send_json({"model_name": name, "responses": out})

    def h_generate_stream(self, name, version=None):
        """Server-sent events: one `data: <v2 response JSON>` event per
        decoupled response, chunked transfer, terminated by the final-flag
        response. A dead client cancels the request (the generative
        scheduler then frees its KV arena slot)."""
        req = self._parse_generate_request(name, version)
        responses = self._stream_responses(req)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        self.wfile.flush()  # time-to-first-header, not time-to-first-token

        def chunk(payload: bytes) -> None:
            self.wfile.write(f"{len(payload):X}\r\n".encode() + payload +
                             b"\r\n")
            self.wfile.flush()

        # Headers are out: from here every outcome must stay inside the
        # chunked body (a second status line would desync the stream), and
        # an abandoned request must stop generating.
        try:
            for resp in responses:
                if resp.error is not None:
                    chunk(b"data: " + json.dumps(
                        {"error": str(resp.error)}).encode() + b"\n\n")
                    break
                if resp.outputs or not resp.final:
                    chunk(b"data: " + json.dumps(
                        self._json_response_dict(resp),
                        separators=(",", ":")).encode() + b"\n\n")
            chunk(b"")  # terminal chunk
        except (BrokenPipeError, ConnectionResetError):
            req.cancel()  # dead client: stop generating for it
        except Exception as exc:  # noqa: BLE001 — mid-stream failure
            req.cancel()
            try:
                chunk(b"data: " + json.dumps(
                    {"error": str(exc)}).encode() + b"\n\n")
                chunk(b"")
            except OSError:
                pass

    def _parse_generate_request(self, name, version) -> InferRequest:
        req = self._parse_infer_request(name, version)
        for o in req.outputs:
            if o.shm_region or o.classification_count > 0 or o.binary:
                raise EngineError(
                    "generate endpoints return JSON tensors only; output "
                    "parameters (shared memory, classification, "
                    "binary_data) are not supported", 400)
        return req

    # Slow-consumer bound for SSE streams: responses pending unread before
    # the request is cancelled (the generative scheduler then stops
    # producing at the next wave) — a stalled reader caps memory. One SSE
    # stream carries ONE request, so cancelling it is already per-request.
    STREAM_PENDING_LIMIT = 1024

    def _stream_pending_limit(self) -> int:
        """Read the env knob per stream (not at import) so it matches the
        gRPC servicer's construction-time semantics."""
        return max(1, envcfg.env_int("CLIENT_TPU_STREAM_PENDING_LIMIT"))

    def _stream_responses(self, req: InferRequest):
        """Submit and yield responses until the final one; a stall cancels
        the request and raises 504; a backlog past STREAM_PENDING_LIMIT
        cancels it too (logged)."""
        import queue as q

        out_q: q.Queue = q.Queue()
        choked = [False]
        limit = self._stream_pending_limit()
        # Progress-gated cancel, mirroring the gRPC servicer's choke: the
        # pipelined decoder legitimately delivers depth x chunk rows that
        # were already in flight when backpressure paused it, so crossing
        # the mark only ARMS the cancel; it fires when a later enqueue
        # finds the writer advanced NOTHING for the grace window (a
        # reader that stopped draining), or at the 8x hard mark (memory
        # bound if a producer ignores the probe).
        progress = [0]   # rows yielded to the SSE writer
        armed = [None]   # (progress, monotonic) at backlog crossing

        def enqueue(resp):
            out_q.put(resp)
            if choked[0]:
                return
            size = out_q.qsize()
            if size < limit:
                armed[0] = None
                return
            if size < 8 * limit:
                p = time.monotonic()
                if armed[0] is None or armed[0][0] != progress[0]:
                    armed[0] = (progress[0], p)
                    return
                if p - armed[0][1] < 0.25:
                    return
            choked[0] = True
            _log.warning(
                "generate stream backlog at %d pending responses "
                "(mark %d) with a stalled reader; cancelling request "
                "(slow consumer)", size, limit)
            req.cancel()

        # Transport flow control (same contract as the gRPC stream
        # writer): decode waves pause for this stream at HALF the cancel
        # mark, so a slow-but-alive SSE reader is writer-paced (TCP
        # backpressure propagates here through the blocking chunk write)
        # and never reaches the cancel; the choke above remains the
        # backstop for a stalled reader, and the generative scheduler's
        # BACKPRESSURE_TIMEOUT_S reclaims the arena slot of a stream
        # throttled past its bound.
        bp_mark = max(1, limit // 2)
        req.backpressure = lambda: out_q.qsize() >= bp_mark

        self.engine.async_infer(req, enqueue)
        # Same coalescing contract as the gRPC stream writer (an SSE event
        # also pays per-message framing): with `response_coalesce` set,
        # rows already backlogged behind a slow chunk write merge into one
        # [k]-row event; off backlog every response ships alone.
        from client_tpu.server.coalesce import drain_run

        def get_nowait():
            try:
                return out_q.get_nowait()
            except q.Empty:
                return None

        delay_s = envcfg.env_float(
            "CLIENT_TPU_STREAM_WRITER_DELAY_MS") / 1e3
        while True:
            try:
                resp = out_q.get(timeout=self.GENERATE_STALL_TIMEOUT_S)
            except q.Empty:
                req.cancel()
                raise EngineError("generation stalled", 504) from None
            merged, leftover = drain_run(resp, get_nowait, req)
            for resp in ((merged,) if leftover is None
                         else (merged, leftover)):
                yield resp
                progress[0] += 1  # reader took an event (choke gate)
                if delay_s:
                    time.sleep(delay_s)
                if resp.error is not None or resp.final:
                    return

    def _json_response_dict(self, resp) -> dict:
        """v2 response head with all tensors as JSON data (no binary tails
        — SSE events and collected arrays are text)."""
        from client_tpu.protocol.dtypes import np_to_wire_dtype

        head: dict = {"model_name": resp.model_name,
                      "model_version": str(resp.model_version)}
        if resp.request_id:
            head["id"] = resp.request_id
        if resp.parameters:
            head["parameters"] = dict(resp.parameters)
        head["outputs"] = [
            rest.build_tensor_json(out_name, arr,
                                   np_to_wire_dtype(arr.dtype), arr.shape,
                                   binary=False)[0]
            for out_name, arr in resp.outputs.items()
        ]
        return head

    def _parse_infer_request(self, name, version=None) -> InferRequest:
        body = self._read_body()
        header_len = self.headers.get(rest.HEADER_INFERENCE_CONTENT_LENGTH)
        head, tail = rest.split_body(
            body, int(header_len) if header_len is not None else None)

        inputs: dict[str, np.ndarray] = {}
        for wire in rest.parse_tensors(head.get("inputs", []), tail):
            shm_region = wire.parameters.get("shared_memory_region")
            if shm_region is not None:
                arr = self._read_shm_input(wire)
            else:
                arr = wire.to_numpy()
            inputs[wire.name] = arr

        outputs: list[OutputRequest] = []
        request_binary_all = bool(
            (head.get("parameters") or {}).get("binary_data_output", False))
        for o in head.get("outputs", []) or []:
            p = o.get("parameters", {}) or {}
            outputs.append(OutputRequest(
                name=o["name"],
                classification_count=int(p.get("classification", 0)),
                shm_region=p.get("shared_memory_region"),
                shm_offset=int(p.get("shared_memory_offset", 0)),
                shm_byte_size=int(p.get("shared_memory_byte_size", 0)),
                binary=bool(p.get("binary_data", request_binary_all)),
                parameters=p,
            ))

        params = head.get("parameters", {}) or {}
        req = InferRequest(
            model_name=name,
            model_version=version or "",
            request_id=head.get("id", ""),
            inputs=inputs,
            outputs=outputs,
            parameters=params,
            sequence_id=int(params.get("sequence_id", 0)),
            sequence_start=bool(params.get("sequence_start", False)),
            sequence_end=bool(params.get("sequence_end", False)),
            priority=int(params.get("priority", 0)),
            timeout_us=int(params.get("timeout", 0)),
            # Cost-ledger tenant: the `X-Tpu-Tenant` header (transport-
            # level, set by our client) or the `tenant` request parameter
            # (protocol-level, survives proxies that strip unknown
            # headers). Header wins, like timeout-ms below.
            tenant=str(self.headers.get("x-tpu-tenant")
                       or params.get("tenant", "") or ""),
            # Adopt the caller's W3C trace context (or start a new trace);
            # every HTTP inference is traced into the engine's ring buffer.
            trace=TraceContext.from_traceparent(
                self.headers.get("traceparent")),
        )
        # End-to-end deadline: the `timeout-ms` header (transport-level,
        # set by our HTTP client from its request budget) or the
        # `timeout_ms` request parameter (protocol-level, works through
        # proxies that strip unknown headers). Header wins — it reflects
        # the budget *remaining* at send time.
        timeout_ms = self.headers.get("timeout-ms") \
            or params.get("timeout_ms")
        if timeout_ms is not None:
            try:
                req.set_deadline_from_timeout_ms(float(timeout_ms))
            except (TypeError, ValueError):
                raise EngineError(
                    f"invalid timeout-ms value {timeout_ms!r}", 400) from None
        return req

    def _read_shm_input(self, wire) -> np.ndarray:
        return self.engine.read_shm_tensor(
            wire.parameters["shared_memory_region"],
            int(wire.parameters.get("shared_memory_offset", 0)),
            int(wire.parameters.get("shared_memory_byte_size", 0)),
            wire.datatype, wire.shape)

    def _send_infer_response(self, req: InferRequest, resp) -> None:
        entries = []
        cfg = None
        model = self.engine.repository.get(req.model_name)
        if model is not None:
            cfg = model.config
        out_req = {o.name: o for o in req.outputs}
        for out_name, arr in resp.outputs.items():
            o = out_req.get(out_name)
            # classification extension
            if o is not None and o.classification_count > 0:
                labels = None
                if cfg is not None:
                    labels = (cfg.parameters.get("labels") or {}).get(out_name)
                arr = classify_output(arr, o.classification_count, labels)
                entry, raw = rest.build_tensor_json(
                    out_name, arr, "BYTES", arr.shape,
                    binary=o.binary if o else False)
                entries.append((entry, raw))
                continue
            # shared-memory output placement
            if o is not None and o.shm_region:
                written = self._write_shm_output(o, arr)
                from client_tpu.protocol.dtypes import np_to_wire_dtype

                entry = {
                    "name": out_name,
                    "datatype": np_to_wire_dtype(arr.dtype),
                    "shape": list(arr.shape),
                    "parameters": {
                        "shared_memory_region": o.shm_region,
                        "shared_memory_offset": o.shm_offset,
                        "shared_memory_byte_size": written,
                    },
                }
                entries.append((entry, None))
                continue
            from client_tpu.protocol.dtypes import np_to_wire_dtype

            dt = np_to_wire_dtype(arr.dtype)
            # Binary encoding is opt-in (v2 binary-data extension default is
            # false): per-output binary_data param, or the request-wide
            # binary_data_output parameter for unlisted outputs.
            binary = o.binary if o is not None else bool(
                req.parameters.get("binary_data_output", False))
            entry, raw = rest.build_tensor_json(
                out_name, arr, dt, arr.shape, binary=binary)
            entries.append((entry, raw))

        body, jlen = rest.build_infer_response_body(
            entries, model_name=resp.model_name,
            model_version=resp.model_version, request_id=resp.request_id,
            parameters={k: v for k, v in resp.parameters.items()} or None)
        has_binary = any(raw is not None for _, raw in entries)
        headers = {}
        if has_binary:
            headers[rest.HEADER_INFERENCE_CONTENT_LENGTH] = str(jlen)
            ctype = "application/octet-stream"
        else:
            ctype = "application/json"
        # Round-trip the trace id (clients correlate against
        # /v2/trace/requests) and surface the server-side phase breakdown
        # as a standard Server-Timing header.
        if req.trace is not None:
            headers["traceparent"] = req.trace.to_traceparent()
        if resp.times is not None:
            headers["Server-Timing"] = server_timing_header(resp.times)
        # Load-report piggyback: every response refreshes the caller's
        # view of this replica's load, so steady-state L7 routing costs
        # zero extra RPCs (the report itself is cached engine-side).
        try:
            headers[LOAD_HEADER] = encode_header(self.engine.load_report())
        # tpulint: allow[swallowed-exception] telemetry must not fail a successful inference
        except Exception:  # noqa: BLE001 — telemetry must not fail a
            pass           # successful inference
        self._send(200, body, content_type=ctype, extra_headers=headers)

    def _write_shm_output(self, o: OutputRequest, arr: np.ndarray) -> int:
        return self.engine.write_shm_tensor(o.shm_region, o.shm_offset,
                                            o.shm_byte_size, arr)


class HttpInferenceServer:
    """Threaded v2 REST server over a TpuEngine."""

    def __init__(self, engine: TpuEngine, host: str = "127.0.0.1",
                 port: int = 8000, verbose: bool = False,
                 certfile: str | None = None, keyfile: str | None = None):
        handler = type("BoundHandler", (_Handler,),
                       {"engine": engine, "verbose": verbose})
        self.engine = engine
        # socketserver's default accept backlog (5) drops connections under
        # concurrent-client bursts — raise it before the socket listens.
        server_cls = type("_Httpd", (ThreadingHTTPServer,),
                          {"request_queue_size": 128})
        self.httpd = server_cls((host, port), handler)
        if certfile:
            # HTTPS endpoint (exercised by the native client's https://
            # support; the reference terminates TLS in libcurl).
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                                server_side=True)
        self.httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self.httpd.server_address[0]
        return f"{host}:{self.port}"

    def start(self) -> "HttpInferenceServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
