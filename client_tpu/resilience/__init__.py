"""Client-side resilience primitives: retry, circuit breaking, deadlines.

Production inference clients (the reference's C++ client behind Envoy/gRPC
service configs) never surface a single stale socket or transient 503 to
the caller; they retry with exponential backoff + full jitter, stop
hammering a host that is clearly down (circuit breaker), and bound the
*total* time spent across attempts by an end-to-end deadline budget.

These classes are transport-agnostic. Both ``client_tpu.http`` and
``client_tpu.grpc`` accept them as opt-in constructor arguments
(``retry_policy=`` / ``circuit_breaker=``) and funnel every call through
:func:`run_with_resilience`. Classification rules (what is retryable)
follow the usual contract:

* connection-level failures (refused, reset, stale keep-alive, timeout)
  are retryable;
* HTTP 502/503 and gRPC UNAVAILABLE are retryable;
* every other 4xx (INVALID_ARGUMENT, NOT_FOUND, ...) is NEVER retried —
  the request itself is wrong and replaying it cannot help.
"""

from __future__ import annotations

import random
import socket
from client_tpu.utils import lockdep
import time
from http.client import BadStatusLine

from client_tpu.utils import InferenceServerException

__all__ = [
    "RetryPolicy",
    "CircuitBreaker",
    "CircuitBreakerOpenError",
    "DeadlineExceededError",
    "retry_after_of",
    "run_with_resilience",
]

# Exceptions that indicate the connection (not the request) failed.
# BadStatusLine covers http.client.RemoteDisconnected (its subclass);
# ConnectionError covers reset/refused/aborted/broken-pipe.
CONNECTION_ERRORS = (ConnectionError, BadStatusLine, socket.timeout,
                     TimeoutError, socket.gaierror)

# HTTP statuses that signal transient server-side trouble.
RETRYABLE_HTTP_STATUSES = frozenset({502, 503})

# gRPC status codes (matched as substrings of the stringified code the
# clients store in InferenceServerException.status, e.g.
# "StatusCode.UNAVAILABLE").
RETRYABLE_GRPC_CODES = ("UNAVAILABLE",)


class DeadlineExceededError(InferenceServerException):
    """The end-to-end deadline budget ran out before a retry could run."""

    def __init__(self, msg, last_error=None):
        super().__init__(msg, status=504)
        self.last_error = last_error


class CircuitBreakerOpenError(InferenceServerException):
    """The per-host breaker is open: the call was rejected locally,
    without touching the network."""

    def __init__(self, host, cooldown_remaining_s):
        super().__init__(
            f"circuit breaker open for host '{host}' "
            f"(retry in {cooldown_remaining_s:.2f}s)", status=503)
        self.host = host
        self.cooldown_remaining_s = cooldown_remaining_s


def status_of(exc) -> int | str | None:
    """Best-effort status extraction across our error shapes:
    InferenceServerException.status() (int for HTTP, "StatusCode.X" str
    for gRPC) and EngineError.status (int attribute)."""
    status = getattr(exc, "status", None)
    if callable(status):
        status = status()
    return status


def retry_after_of(exc) -> float | None:
    """Server pushback attached to an error by the transports: the HTTP
    client parses a ``Retry-After`` header, the gRPC client the
    ``retry-after``/``retry-pushback-ms`` trailing metadata — both land
    on ``exc.retry_after_s``. None when the server sent no pushback."""
    value = getattr(exc, "retry_after_s", None)
    if value is None:
        return None
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if value >= 0 else None


class RetryPolicy:
    """Retry schedule + retryable-status classification.

    ``max_attempts`` counts the first try: ``max_attempts=4`` means up to
    three retries. Backoff is capped exponential with full jitter
    (delay ~ U(0, min(max_backoff, initial * multiplier^n)), the AWS
    architecture-blog scheme) — jitter decorrelates a thundering herd of
    clients all retrying the same blip. Pass ``seed`` for deterministic
    backoff draws in tests.
    """

    def __init__(self, max_attempts=3, initial_backoff_s=0.05,
                 max_backoff_s=2.0, backoff_multiplier=2.0, jitter=True,
                 retryable_statuses=RETRYABLE_HTTP_STATUSES,
                 retryable_grpc_codes=RETRYABLE_GRPC_CODES, seed=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.jitter = jitter
        self.retryable_statuses = frozenset(retryable_statuses)
        self.retryable_grpc_codes = tuple(retryable_grpc_codes)
        self._rng = random.Random(seed)
        self._rng_lock = lockdep.Lock("resilience.rng")

    def retryable(self, exc) -> bool:
        if isinstance(exc, CONNECTION_ERRORS):
            return True
        if retry_after_of(exc) is not None:
            # Explicit server pushback (429 + Retry-After / gRPC
            # retry-pushback): the server ASKED for a retry later —
            # retryable by definition, whatever the status code.
            return True
        status = status_of(exc)
        if status is None:
            # A wrapped connection failure (e.g. gRPC future timeout or an
            # InferenceServerException with no status from a dead socket)
            # is not classifiable; stay conservative and do not retry.
            return False
        if isinstance(status, int):
            return status in self.retryable_statuses
        text = str(status)
        if any(code in text for code in self.retryable_grpc_codes):
            return True
        return False

    def backoff_s(self, retry_index: int, remaining_s: float | None = None,
                  retry_after_s: float | None = None):
        """Delay before retry number ``retry_index`` (1-based). Never
        exceeds the remaining deadline budget when one is given.

        ``retry_after_s`` is server pushback (Retry-After / gRPC
        retry-pushback metadata): when present it REPLACES the jittered
        exponential draw — the server knows when capacity frees up;
        guessing earlier hammers it, guessing later wastes budget."""
        if retry_after_s is not None:
            delay = max(0.0, float(retry_after_s))
        else:
            cap = min(self.max_backoff_s,
                      self.initial_backoff_s
                      * self.backoff_multiplier ** max(0, retry_index - 1))
            if self.jitter:
                with self._rng_lock:
                    delay = self._rng.uniform(0.0, cap)
            else:
                delay = cap
        if remaining_s is not None:
            delay = min(delay, max(0.0, remaining_s))
        return delay


class CircuitBreaker:
    """Per-host three-state breaker: closed -> open after
    ``failure_threshold`` CONSECUTIVE failures -> half-open probe after
    ``cooldown_s`` -> closed on probe success (or back to open on probe
    failure). While open, calls fail locally with
    :class:`CircuitBreakerOpenError` instead of burning a network round
    trip on a host that is clearly down.

    One instance may be shared across clients; state is tracked per
    ``host`` key. ``open_seconds_total()`` reports cumulative time any
    host spent open — surfaced by bench.py as ``breaker_open_s``.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    class _HostState:
        __slots__ = ("state", "consecutive_failures", "opened_at",
                     "probe_in_flight", "probe_started_at", "open_accum_s")

        def __init__(self):
            self.state = CircuitBreaker.CLOSED
            self.consecutive_failures = 0
            self.opened_at = 0.0
            self.probe_in_flight = False
            self.probe_started_at = 0.0
            self.open_accum_s = 0.0

    def __init__(self, failure_threshold=5, cooldown_s=5.0,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = lockdep.Lock("resilience.breaker")
        self._hosts: dict[str, CircuitBreaker._HostState] = {}

    def _host(self, host: str) -> "_HostState":
        st = self._hosts.get(host)
        if st is None:
            st = self._hosts.setdefault(host, self._HostState())
        return st

    def state(self, host: str = "") -> str:
        with self._lock:
            return self._host(host).state

    def _emit(self, name: str, host: str, trace_id: str | None,
              severity: str = "INFO", **detail) -> None:
        """Journal a state transition (outside the breaker lock — the
        journal has its own lock and runs sinks)."""
        from client_tpu.observability.events import journal

        journal().emit("breaker", name, severity=severity,
                       trace_id=trace_id, host=host, **detail)

    def check(self, host: str = "", trace_id: str | None = None) -> None:
        """Gate one call attempt; raises CircuitBreakerOpenError when the
        host is open (or half-open with the single probe already taken)."""
        probing = False
        with self._lock:
            st = self._host(host)
            if st.state == self.CLOSED:
                return
            now = self._clock()
            elapsed = now - st.opened_at
            if st.state == self.OPEN:
                if elapsed < self.cooldown_s:
                    raise CircuitBreakerOpenError(
                        host, self.cooldown_s - elapsed)
                st.state = self.HALF_OPEN
                st.probe_in_flight = False
                probing = True
            # HALF_OPEN: exactly one probe at a time; concurrent callers
            # are rejected until the probe resolves. A probe older than
            # cooldown_s is treated as abandoned (its attempt died without
            # reporting success OR failure) and a fresh probe is admitted,
            # so the breaker can never wedge permanently in HALF_OPEN.
            if st.probe_in_flight:
                probe_age = now - st.probe_started_at
                if probe_age < self.cooldown_s:
                    raise CircuitBreakerOpenError(
                        host, self.cooldown_s - probe_age)
            st.probe_in_flight = True
            st.probe_started_at = now
        if probing:
            self._emit("half_open", host, trace_id)

    def record_success(self, host: str = "",
                       trace_id: str | None = None) -> None:
        with self._lock:
            st = self._host(host)
            closed = st.state != self.CLOSED
            if closed:
                st.open_accum_s += self._clock() - st.opened_at
            st.state = self.CLOSED
            st.consecutive_failures = 0
            st.probe_in_flight = False
        if closed:
            self._emit("closed", host, trace_id)

    def record_failure(self, host: str = "",
                       trace_id: str | None = None) -> None:
        opened = None
        with self._lock:
            st = self._host(host)
            now = self._clock()
            if st.state == self.HALF_OPEN:
                # Failed probe: re-open for a fresh cooldown, folding the
                # half-open interval into the cumulative open time.
                st.open_accum_s += now - st.opened_at
                st.state = self.OPEN
                st.opened_at = now
                st.probe_in_flight = False
                opened = {"probe_failed": True}
            else:
                st.consecutive_failures += 1
                if (st.state == self.CLOSED
                        and st.consecutive_failures
                        >= self.failure_threshold):
                    st.state = self.OPEN
                    st.opened_at = now
                    opened = {"failures": st.consecutive_failures}
        if opened is not None:
            self._emit("open", host, trace_id, severity="ERROR",
                       cooldown_s=self.cooldown_s, **opened)

    def open_seconds_total(self) -> float:
        with self._lock:
            now = self._clock()
            total = 0.0
            for st in self._hosts.values():
                total += st.open_accum_s
                if st.state != self.CLOSED:
                    total += now - st.opened_at
            return total


def counts_as_server_fault(exc) -> bool:
    """Whether a failure should trip the breaker: connection-level errors
    and 5xx/UNAVAILABLE/INTERNAL do; 4xx (the caller's fault) must not —
    a flood of bad requests does not mean the host is down."""
    if isinstance(exc, CONNECTION_ERRORS):
        return True
    status = status_of(exc)
    if isinstance(status, int):
        return status >= 500
    if status is not None:
        text = str(status)
        return any(code in text for code in
                   ("UNAVAILABLE", "INTERNAL", "UNKNOWN",
                    "DEADLINE_EXCEEDED"))
    return False


def run_with_resilience(attempt, *, policy=None, breaker=None,
                        deadline_s=None, host="", on_retry=None,
                        on_breaker_reject=None, sleep=time.sleep,
                        clock=time.monotonic, trace_id=None):
    """Run ``attempt(remaining_s)`` under retry/breaker/deadline control.

    ``attempt`` receives the remaining deadline budget in seconds (None
    when no budget is set) so it can cap its own per-attempt socket/RPC
    timeout; it returns the result or raises. ``on_retry(n, exc, delay)``
    fires before each backoff sleep (clients record it in InferStat).

    The deadline budget bounds TOTAL time across attempts: no retry is
    started — and no backoff slept — past the budget; on exhaustion the
    last transport error is re-raised (or DeadlineExceededError if the
    budget expired before the first attempt could run).
    """
    start = clock()
    max_attempts = policy.max_attempts if policy is not None else 1
    attempt_no = 0
    while True:
        attempt_no += 1
        remaining = None
        if deadline_s is not None:
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                raise DeadlineExceededError(
                    f"deadline budget of {deadline_s:.3f}s exhausted "
                    f"before attempt {attempt_no}")
        if breaker is not None:
            try:
                breaker.check(host, trace_id=trace_id)
            except CircuitBreakerOpenError:
                if on_breaker_reject is not None:
                    on_breaker_reject()
                raise
        try:
            result = attempt(remaining)
        except Exception as exc:  # noqa: BLE001 — classified below
            if breaker is not None:
                if counts_as_server_fault(exc):
                    breaker.record_failure(host, trace_id=trace_id)
                else:
                    # The host answered (4xx, RESOURCE_EXHAUSTED, a wrapped
                    # error with no status): the breaker must resolve any
                    # half-open probe as a SUCCESS — leaving it unresolved
                    # would reject every future call to this host forever.
                    breaker.record_success(host, trace_id=trace_id)
            if (policy is None or attempt_no >= max_attempts
                    or not policy.retryable(exc)):
                raise
            if deadline_s is not None:
                remaining = deadline_s - (clock() - start)
                if remaining <= 0:
                    raise
            delay = policy.backoff_s(attempt_no, remaining,
                                     retry_after_s=retry_after_of(exc))
            if on_retry is not None:
                on_retry(attempt_no, exc, delay)
            if delay > 0:
                sleep(delay)
            continue
        if breaker is not None:
            breaker.record_success(host, trace_id=trace_id)
        return result
