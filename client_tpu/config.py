"""Central registry for every ``CLIENT_TPU_*`` environment variable.

Before this module, ~20 env reads were scattered across the tree, each
with its own inline default and no single place that said what knobs
exist — so a typo'd variable name failed silently and docs drifted from
code. Every ``CLIENT_TPU_*`` read now goes through the accessors here
against a declared :class:`EnvVar` (name, default, parser, doc line),
which gives three properties at once:

* one source of truth the docs table is *generated* from
  (``python -m client_tpu.config --markdown`` → docs/CONFIG.md);
* tpulint (tools/analyze, check ``env-registry``) can statically verify
  that no code path reads ``os.environ["CLIENT_TPU_..."]`` directly and
  that every registered name is documented;
* reading an *unregistered* name raises at the call site instead of
  silently returning a default.

The accessors accept an ``environ`` mapping so config objects keep their
testable ``from_env(environ={...})`` signatures. Stdlib-only: safe to
import from anywhere (including ``client_tpu.utils.lockdep``) without
cycles.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "EnvVar",
    "register",
    "registered",
    "env_text",
    "env_str",
    "env_int",
    "env_float",
    "env_flag",
    "render_markdown_table",
]


@dataclass(frozen=True)
class EnvVar:
    """One declared environment knob."""

    name: str
    default: str       # raw default applied when unset ("" = unset/off)
    kind: str          # str | int | float | flag | json — documentation +
                       # which accessor the readers use
    doc: str           # one generated docs-table line
    subsystem: str     # docs-table grouping


_REGISTRY: dict[str, EnvVar] = {}


def register(name: str, default: str, kind: str, doc: str,
             subsystem: str) -> str:
    """Declare one variable; returns the name so modules can bind it to
    their legacy ``ENV_VAR`` constants."""
    if not name.startswith("CLIENT_TPU_"):
        raise ValueError(f"env registry only covers CLIENT_TPU_*: {name!r}")
    if name in _REGISTRY:
        raise ValueError(f"env var {name!r} registered twice")
    _REGISTRY[name] = EnvVar(name, default, kind, doc, subsystem)
    return name


def registered() -> dict[str, EnvVar]:
    return dict(_REGISTRY)


def _var(name: str) -> EnvVar:
    var = _REGISTRY.get(name)
    if var is None:
        raise KeyError(
            f"env var {name!r} is not registered in client_tpu.config — "
            "add a register(...) entry (and regenerate docs/CONFIG.md)")
    return var


def env_text(name: str, environ=None) -> str:
    """Raw stripped value; the registered default when unset. The JSON-ish
    knobs (``@file`` indirection, ``1``/``on`` grammars) parse this
    themselves — the registry owns the *name and default*, not the
    grammar."""
    var = _var(name)
    environ = os.environ if environ is None else environ
    raw = environ.get(name)
    if raw is None:
        return var.default
    return raw.strip()


def env_str(name: str, environ=None) -> str:
    text = env_text(name, environ)
    return text if text else _var(name).default


def env_int(name: str, environ=None) -> int:
    text = env_text(name, environ)
    try:
        return int(text if text else _var(name).default)
    except ValueError:
        raise ValueError(
            f"{name} expects an integer, got {text!r}") from None


def env_float(name: str, environ=None) -> float:
    text = env_text(name, environ)
    try:
        return float(text if text else _var(name).default)
    except ValueError:
        raise ValueError(
            f"{name} expects a number, got {text!r}") from None


def env_flag(name: str, environ=None) -> bool:
    """Boolean knob: unset, ``""``, ``0``, ``false``, ``off`` → False;
    anything else → True."""
    return env_text(name, environ).lower() not in ("", "0", "false", "off")


# ---------------------------------------------------------------------------
# The registry. Grouped by subsystem; kept alphabetical within a group so
# the generated docs table is stable across regenerations.
# ---------------------------------------------------------------------------

# -- engine ------------------------------------------------------------------
register(
    "CLIENT_TPU_ATTN_IMPL", "reference", "str",
    "Generative attention implementation: `reference` (XLA) or `fused` "
    "(Pallas decode-wave kernel); streams are token-identical either way.",
    "engine")
register(
    "CLIENT_TPU_AUTOTUNE", "", "json",
    "Bucket-ladder autotuner: unset/`0`/`off` disables (no thread, no "
    "arena); `1`/`on` takes defaults; else inline JSON or `@/path.json`.",
    "engine")
register(
    "CLIENT_TPU_SELFDRIVE", "", "json",
    "Self-drive closed loops (dispatch retune, SLO-burn admission "
    "tightening, drift re-placement): unset/`0`/`off` disables; `1`/`on` "
    "takes defaults; else inline JSON or `@/path.json` (interval_s, "
    "fill_low, wait_high_s, burn_factor, rebalance_cooldown_s, "
    "max_moves_per_window, ... — see docs/SELFDRIVING.md).",
    "engine")
register(
    "CLIENT_TPU_GEN_CHUNK", "1", "int",
    "Decode chunk K: one device dispatch advances every stream K tokens "
    "(divides per-wave host overhead by K; adds ≤K−1 waves of TTFT).",
    "engine")
register(
    "CLIENT_TPU_GEN_PIPELINE", "32", "int",
    "Generative dispatch-ahead depth in waves before the worker blocks "
    "on the oldest fetch.",
    "engine")
register(
    "CLIENT_TPU_PLATFORM", "", "str",
    "Force the JAX platform for the embedded engine (e.g. `cpu` for "
    "hermetic runs on machines without a TPU).",
    "engine")
register(
    "CLIENT_TPU_SEQ_PIPELINE", "2", "int",
    "Sequence-batcher dispatch-ahead depth (waves in flight before the "
    "worker blocks on the oldest fetch).",
    "engine")
register(
    "CLIENT_TPU_TRACE_BUFFER", "512", "int",
    "Engine request-trace span-store capacity (GET /v2/trace/requests).",
    "engine")
register(
    "CLIENT_TPU_WARMUP", "", "flag",
    "Pre-compile every batch bucket at model load in the embedded engine "
    "so no XLA compile lands inside a measurement window.",
    "engine")

# -- server frontends --------------------------------------------------------
register(
    "CLIENT_TPU_STREAM_PENDING_LIMIT", "1024", "int",
    "Per-stream pending-response backlog (HTTP generate_stream / gRPC "
    "stream) before the slow-consumer shed cancels the request.",
    "server")
register(
    "CLIENT_TPU_STREAM_WRITER_DELAY_MS", "0", "float",
    "Test knob: per-message stream-writer delay (ms) that forces a "
    "writer backlog so coalescing/shed paths are deterministically "
    "exercisable.",
    "server")

# -- admission / SLO ---------------------------------------------------------
register(
    "CLIENT_TPU_ADMISSION", "", "json",
    "Admission-controller limits: inline JSON or `@/path.json`; unset "
    "admits everything (in-flight accounting only).",
    "admission")
register(
    "CLIENT_TPU_SLO", "", "json",
    "SLO objectives (availability/latency burn tracking): inline JSON or "
    "`@/path.json`; unset disables tracking entirely.",
    "admission")
register(
    "CLIENT_TPU_QOS", "", "json",
    "Tenant QoS classes (inline JSON or `@/path.json`): named classes "
    "with WFQ weights, token-bucket quotas, inflight/queue caps, "
    "class→priority mapping, preempt/protect flags, plus the "
    "tenant→class table; unset disables QoS entirely (priority-heap "
    "scheduling, shared admission gates only). See docs/QOS.md.",
    "admission")

# -- observability -----------------------------------------------------------
register(
    "CLIENT_TPU_COSTS", "", "json",
    "Per-tenant cost ledger (GET /v2/costs, tpu_cost_* metrics): `0`/"
    "`off` disables; unset/`1`/`on` defaults; else inline JSON or "
    "`@/path.json` (window_s, max_tenants, tenants, top_talker_*).",
    "observability")
register(
    "CLIENT_TPU_EVENT_BUFFER", "1024", "int",
    "Capacity of the operational event-journal ring (GET /v2/events).",
    "observability")
register(
    "CLIENT_TPU_LOG", "", "str",
    "`json` attaches a JSON-lines handler to the `client_tpu` logger and "
    "mirrors journal events to the same stream.",
    "observability")
register(
    "CLIENT_TPU_LOGLEVEL", "INFO", "str",
    "Level of the `client_tpu.engine` logger's default stderr handler, "
    "applied when `engine.backend_init` is first imported.",
    "observability")
register(
    "CLIENT_TPU_MEMORY", "", "json",
    "HBM census / memory-pressure events: `0`/`off` disables pressure "
    "events; unset/`1`/`on` defaults; else inline JSON or `@/path.json`.",
    "observability")
register(
    "CLIENT_TPU_BLACKBOX", "", "json",
    "Incident blackbox (journal-triggered postmortem bundles on disk, "
    "GET /v2/debug/bundles): `0`/`off` disables; unset/`1`/`on` defaults "
    "(all triggers, ~48 MiB bundle ring under $TMPDIR); else inline JSON "
    "or `@/path.json` with `dir`, `triggers`, `window_s`, `debounce_s`, "
    "`cooldown_s`, `max_bundles`, `max_bundle_bytes`, `max_total_bytes`.",
    "observability")
register(
    "CLIENT_TPU_PROFILE_WINDOW_S", "60", "float",
    "Efficiency-profiler sliding-window length in seconds.",
    "observability")
register(
    "CLIENT_TPU_ROOFLINE", "", "json",
    "Roofline attribution (XLA cost-model capture + MFU/MBU peaks): "
    "`0`/`off` disables capture; unset/`1`/`on` defaults (detected "
    "device-kind peaks); else inline JSON or `@/path.json` with "
    "`peak_flops`, `peak_bytes_per_s`, `device_kinds`, `capture`.",
    "observability")
register(
    "CLIENT_TPU_TIMESERIES", "", "json",
    "Flight recorder (1 Hz signal ring, GET /v2/timeseries): `0`/`off` "
    "disables; unset/`1`/`on` defaults; else inline JSON or `@/path.json`.",
    "observability")

# -- shm data planes ---------------------------------------------------------
register(
    "CLIENT_TPU_REPLAY_PRIORITY", "8", "int",
    "InferRequest priority tools/replay.py stamps on shadow traffic; at "
    "or above the admission `shadow_priority` threshold the request is "
    "classed shadow and sheds first.",
    "shm")
register(
    "CLIENT_TPU_REPLAY_TENANT", "shadow", "str",
    "Cost-ledger tenant tag tools/replay.py stamps on its shm traffic "
    "(`--tenant` overrides) so shadow device/HBM spend is attributable.",
    "shm")
register(
    "CLIENT_TPU_REPLAY_SHAPE", "steady", "str",
    "Default load shape for tools/replay.py `--rate` pacing: `steady`, "
    "`diurnal` (raised cosine to `--peak-rate`), or `flash_crowd` "
    "(rectangular peak burst each `--shape-period`).",
    "shm")
register(
    "CLIENT_TPU_SHM_REAPER_INTERVAL_MS", "1.0", "float",
    "Idle sleep (ms) of the engine-side multi-ring reaper thread between "
    "sweeps that admitted nothing.",
    "shm")
register(
    "CLIENT_TPU_SHM_REAPER_SPAN", "32", "int",
    "Per-ring slot cap per reaper sweep — the fairness quantum that "
    "keeps one hot producer from starving the other reaped rings.",
    "shm")
register(
    "CLIENT_TPU_STAGED_BUDGET", "0", "int",
    "Total payload bytes of staged datasets the engine will hold "
    "attached at once; `0` means unlimited.",
    "shm")
register(
    "CLIENT_TPU_STAGED_PATH", "", "str",
    "Default staged-dataset shm key for tools/replay.py (`--dataset-key` "
    "overrides).",
    "shm")

# -- router / fleet ----------------------------------------------------------
register(
    "CLIENT_TPU_FLEET_MONITOR", "", "json",
    "Fleet drift monitor: unset/`0`/`off` disables; `1`/`on` defaults; "
    "else inline JSON or `@/path.json` (interval_s, threshold, "
    "min_replicas, window_s).",
    "router")
register(
    "CLIENT_TPU_ROUTER_TRACE_BUFFER", "512", "int",
    "Router span-store capacity (stitched traces on /v2/trace/requests).",
    "router")

# -- diagnostics -------------------------------------------------------------
register(
    "CLIENT_TPU_FAULTS", "", "json",
    "Deterministic fault-injection plan (inline JSON or `@/path.json`); "
    "unset injects nothing.",
    "diagnostics")
register(
    "CLIENT_TPU_LOCKDEP", "", "flag",
    "Enable runtime lock-order and blocking-under-lock checking "
    "(client_tpu.utils.lockdep). Test/CI harnesses only — named locks "
    "created while enabled record acquisition chains and raise on "
    "ordering cycles; zero-overhead plain threading primitives otherwise.",
    "diagnostics")


# ---------------------------------------------------------------------------
# Docs generation
# ---------------------------------------------------------------------------

def render_markdown_table() -> str:
    """The generated env-var table embedded in docs/CONFIG.md between the
    ``<!-- env-table:begin -->`` / ``<!-- env-table:end -->`` markers
    (tpulint's env-registry check verifies every registered name appears
    there)."""
    lines = [
        "| Variable | Subsystem | Kind | Default | Description |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(_REGISTRY):
        v = _REGISTRY[name]
        default = f"`{v.default}`" if v.default else "*(unset)*"
        lines.append(
            f"| `{v.name}` | {v.subsystem} | {v.kind} | {default} "
            f"| {v.doc} |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(render_markdown_table())
