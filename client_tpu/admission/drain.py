"""Graceful drain: coordinated SIGTERM shutdown with zero dropped work.

The lifecycle orchestrators (kubelet, systemd) signal SIGTERM and grant a
bounded grace period before SIGKILL. An abrupt exit drops every in-flight
request — the client sees a severed connection mid-inference. The drain
sequence here loses nothing that was already admitted:

1. ``engine.begin_drain()`` — readiness flips false (``/v2/health/ready``
   / ``ServerReady``) so load balancers stop routing here, and every NEW
   submission is rejected with 503 + ``Retry-After`` pushback.
2. Frontends stop accepting: the HTTP accept loop shuts down (in-flight
   handler threads keep running) and the gRPC server stops taking new
   RPCs with a grace window for active ones.
3. Poll until the engine is empty — admitted-but-unfinished requests
   (the admission controller's in-flight count) plus queued/batched work
   — or the drain deadline passes.
4. ``engine.shutdown()`` — scheduler workers drain their queues through
   the existing ``Scheduler.stop()`` machinery (heap order pops real
   requests ahead of the shutdown sentinels), then the process exits.

The wall time lands on the ``tpu_drain_duration_seconds`` gauge and in
the returned report. ``install_sigterm_handler`` wires the sequence to
SIGTERM for ``python -m client_tpu.server``.
"""

from __future__ import annotations

import logging
import signal
import threading
import time

_log = logging.getLogger("client_tpu")

DEFAULT_DRAIN_DEADLINE_S = 30.0


def _pending_work(engine) -> int:
    """Requests admitted but not yet finally responded, plus anything a
    scheduler still holds (covers in-process callers that bypass the
    engine's admission accounting)."""
    pending = 0
    admission = getattr(engine, "admission", None)
    if admission is not None:
        pending += admission.total_inflight()
    for sched in engine.schedulers():
        pending += sched.queue.qsize()
        pending += max(0, getattr(sched, "active_batches", 0))
    return pending


def drain(engine, http_servers=(), grpc_servers=(),
          deadline_s: float = DEFAULT_DRAIN_DEADLINE_S,
          poll_s: float = 0.02) -> dict:
    """Run the full drain sequence; returns a report dict:
    ``{"drain_s", "clean", "pending"}`` where ``clean`` means every
    admitted request finished inside the deadline (``pending`` is what
    remained when the deadline forced shutdown — those requests get 503
    responses from ``Scheduler.stop()``, not severed connections)."""
    from client_tpu.observability.events import journal

    jour = journal()
    start = time.monotonic()
    deadline = start + max(0.0, deadline_s)
    engine.begin_drain()
    jour.emit("drain", "begin", deadline_s=deadline_s,
              http_frontends=len(http_servers),
              grpc_frontends=len(grpc_servers))
    # Stop accepting new work. HTTP: the accept loop ends (threads serving
    # accepted connections run on; their new requests hit the drain gate).
    # gRPC: new RPCs are rejected immediately; in-flight ones get the
    # remaining grace. Neither wait happens here — draining the engine is
    # the clock that matters.
    for srv in http_servers:
        try:
            srv.httpd.shutdown()
        except Exception:  # noqa: BLE001 — a dead frontend must not stop
            _log.exception("http frontend shutdown failed during drain")
    grpc_stops = []
    for srv in grpc_servers:
        try:
            grpc_stops.append(
                (srv,
                 srv.server.stop(grace=max(0.0, deadline - time.monotonic()))))
        except Exception:  # noqa: BLE001
            _log.exception("grpc frontend stop failed during drain")
    pending = _pending_work(engine)
    while pending > 0 and time.monotonic() < deadline:
        time.sleep(poll_s)
        pending = _pending_work(engine)
    if pending:
        _log.warning(
            "drain deadline (%.1fs) passed with %d request(s) pending; "
            "they will be failed with 503 by scheduler shutdown",
            deadline_s, pending)
    engine.shutdown()
    # The first stop()'s grace is sized for in-flight RPCs, but its
    # termination event also waits out *idle* client connections — the
    # client library's channel cache keeps HTTP/2 connections open long
    # after their RPCs finish, so the event cannot fire until the grace
    # expires. Every admitted request has been responded to by now
    # (drained, or failed 503 by scheduler shutdown), so re-arm stop
    # with a short grace to force idle connections closed.
    for srv, evt in grpc_stops:
        if evt.wait(0.05):
            continue
        try:
            evt = srv.server.stop(grace=0.25)
        except Exception:  # noqa: BLE001
            _log.exception("grpc frontend final stop failed during drain")
        evt.wait(max(0.0, deadline - time.monotonic()))
    for srv in http_servers:
        try:
            srv.httpd.server_close()
        # tpulint: allow[swallowed-exception] reviewed fail-open
        except Exception:  # noqa: BLE001
            pass
    drain_s = time.monotonic() - start
    metrics = getattr(engine, "metrics", None)
    if metrics is not None:
        metrics.drain_duration.set(drain_s)
    _log.info("drain complete in %.3fs (clean=%s, pending=%d)",
              drain_s, pending == 0, pending)
    jour.emit("drain", "end",
              severity="INFO" if pending == 0 else "WARNING",
              drain_s=round(drain_s, 4), clean=pending == 0,
              pending=pending)
    return {"drain_s": drain_s, "clean": pending == 0, "pending": pending}


def install_sigterm_handler(engine, http_servers=(), grpc_servers=(),
                            deadline_s: float = DEFAULT_DRAIN_DEADLINE_S,
                            on_done=None) -> threading.Event:
    """Install a SIGTERM handler running :func:`drain` on a background
    thread (signal handlers must return promptly; the drain takes up to
    ``deadline_s``). Returns an Event set when the drain finishes — the
    server main loop waits on it and exits. ``on_done(report)`` runs
    after the drain, still on the drain thread. Must be called from the
    main thread (CPython signal API restriction)."""
    done = threading.Event()
    fired = threading.Event()

    def _run():
        report = drain(engine, http_servers, grpc_servers,
                       deadline_s=deadline_s)
        if on_done is not None:
            try:
                on_done(report)
            except Exception:  # noqa: BLE001
                _log.exception("drain on_done callback raised")
        done.set()

    def _handler(signum, frame):
        if fired.is_set():
            return  # double SIGTERM: first drain is already running
        fired.set()
        _log.info("SIGTERM received; draining (deadline %.1fs)", deadline_s)
        threading.Thread(target=_run, name="drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
    return done
