"""Tenant QoS: named classes, WFQ weights, quotas, preemption, governor.

PR 14's shadow class was one hard-coded shed-first tier; the cost
ledger (PR 16) then measured exactly how it leaks — shadow replay
inflating live p99 1.44x through queue wait and co-batch dilution.
This module generalizes the tier into a real QoS system:

* **Named classes** — ``CLIENT_TPU_QOS`` (inline JSON or ``@/path``)
  declares classes like ``interactive`` / ``batch`` / ``shadow``, each
  with a WFQ ``weight``, an optional token-bucket quota
  (``tokens_per_s`` + ``burst``), per-class ``max_inflight`` /
  ``max_queue_depth`` caps, and a ``priority_level`` mapping so a
  class implies a scheduler priority without every client stamping one.
* **Tenant → class mapping** — the ``tenants`` table routes the
  already-threaded tenant tag (``X-Tpu-Tenant``, gRPC param, shm slot
  field) onto a class; unmapped tenants fall back per-priority (a
  class may claim a ``min_priority`` band, generalizing
  ``shadow_priority``) and finally to ``default_class``.
* **Weighted fair queueing** — ``engine/scheduler.py`` swaps its pure
  priority heap for a deficit-round-robin queue over per-class lanes
  (``_WfqQueue``); this module only carries the weights.
* **Preemption** — classes with ``"preempt": true`` (interactive)
  split an in-assembly batch-lane batch on arrival rather than waiting
  behind a full wave; counted on ``tpu_qos_preemptions_total``.
* **Class-aware pushback** — a shed batch/shadow tenant gets a
  ``Retry-After`` derived from its own bucket's refill time, not the
  shared EWMA wait estimate: honest long pushback stops capped
  producers from synchronized retry-waves.
* **SLO-burn governor** — when the SLO tracker's fast-burn alarm
  (PR 4) fires, the governor tightens the *offending* class's bucket
  (the non-protected class with the highest cost-ledger occupancy over
  the last tick) instead of only flipping readiness; journaled as
  edge-triggered ``qos.throttle`` / ``qos.restore`` events and
  exported as ``tpu_qos_throttle_ratio{class}``.

Everything defaults to off: with ``CLIENT_TPU_QOS`` unset the
controller is disabled, schedulers keep their priority heap, and the
admission path is byte-for-byte the PR 14 behavior.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from client_tpu import config as envcfg
from client_tpu.admission import (
    MIN_RETRY_AFTER_S,
    AdmissionError,
    TokenBucket,
)
from client_tpu.utils import lockdep

__all__ = [
    "ENV_VAR",
    "DEFAULT_CLASS",
    "QosClassConfig",
    "QosConfig",
    "QosController",
]

ENV_VAR = "CLIENT_TPU_QOS"

# The implicit class for unmapped tenants when the config names none.
DEFAULT_CLASS = "default"

# Governor defaults: halve the offending class's rate per tighten step,
# never below this fraction of the configured rate, and restore (double
# back up) only after the burn alarm has stayed clear for a hold.
_THROTTLE_FACTOR = 0.5
_MIN_RATE_RATIO = 0.1
_RESTORE_HOLD_S = 5.0
_GOVERNOR_INTERVAL_S = 1.0


@dataclass
class QosClassConfig:
    """One named tenant class. Zeroed limits are disabled, like
    :class:`~client_tpu.admission.AdmissionConfig`."""

    name: str = ""
    # WFQ share: deficit-round-robin quantum is proportional to this.
    weight: float = 1.0
    # Scheduler priority stamped on requests that arrive with
    # priority <= 0 (0 keeps the model's default level).
    priority_level: int = 0
    # Requests with priority >= min_priority classify here when their
    # tenant is unmapped (generalizes shadow_priority; 0 = no band).
    min_priority: int = 0
    # Token-bucket quota (requests/s); burst defaults to the rate.
    tokens_per_s: float = 0.0
    burst: float = 0.0
    # Per-class concurrency / backlog caps.
    max_inflight: int = 0
    max_queue_depth: int = 0
    # Arrivals of this class split an in-assembly batch of other lanes.
    preempt: bool = False
    # The governor never throttles a protected class.
    protect: bool = False

    _FIELDS = ("weight", "priority_level", "min_priority", "tokens_per_s",
               "burst", "max_inflight", "max_queue_depth", "preempt",
               "protect")

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "QosClassConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(
                f"unknown qos class keys for '{name}': {sorted(unknown)}")
        out = cls(name=name, **d)
        if out.weight <= 0:
            raise ValueError(f"qos class '{name}': weight must be > 0")
        return out


@dataclass
class QosConfig:
    """The ``CLIENT_TPU_QOS`` grammar::

        {"classes": {"interactive": {"weight": 8, "preempt": true,
                                     "protect": true},
                     "batch":       {"weight": 2, "priority_level": 4},
                     "shadow":      {"weight": 1, "priority_level": 8,
                                     "min_priority": 8,
                                     "tokens_per_s": 50, "burst": 10,
                                     "max_inflight": 4,
                                     "max_queue_depth": 16}},
         "tenants": {"shadow": "shadow", "etl": "batch"},
         "default_class": "interactive"}

    Unknown keys fail fast (a typo must not silently disable a cap).
    """

    classes: dict[str, QosClassConfig] = field(default_factory=dict)
    tenants: dict[str, str] = field(default_factory=dict)
    default_class: str = ""
    throttle_factor: float = _THROTTLE_FACTOR
    min_rate_ratio: float = _MIN_RATE_RATIO
    restore_hold_s: float = _RESTORE_HOLD_S
    governor_interval_s: float = _GOVERNOR_INTERVAL_S

    _FIELDS = ("classes", "tenants", "default_class", "throttle_factor",
               "min_rate_ratio", "restore_hold_s", "governor_interval_s")

    @property
    def enabled(self) -> bool:
        return bool(self.classes)

    @classmethod
    def from_dict(cls, d: dict) -> "QosConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(f"unknown qos config keys: {sorted(unknown)}")
        classes = {
            str(name): QosClassConfig.from_dict(str(name), spec)
            for name, spec in (d.pop("classes", {}) or {}).items()
        }
        tenants = {str(t): str(c)
                   for t, c in (d.pop("tenants", {}) or {}).items()}
        cfg = cls(classes=classes, tenants=tenants, **d)
        for tenant, cname in cfg.tenants.items():
            if cname not in cfg.classes:
                raise ValueError(
                    f"qos tenant '{tenant}' maps to undeclared class "
                    f"'{cname}'")
        if cfg.default_class and cfg.default_class not in cfg.classes:
            raise ValueError(
                f"qos default_class '{cfg.default_class}' is not declared")
        if not cfg.default_class and cfg.classes:
            # Deterministic fallback: a declared class named "default",
            # else the highest-weight class (ties break by name).
            if DEFAULT_CLASS in cfg.classes:
                cfg.default_class = DEFAULT_CLASS
            else:
                cfg.default_class = max(
                    cfg.classes,
                    key=lambda n: (cfg.classes[n].weight, n))
        return cfg

    @classmethod
    def from_env(cls, environ=os.environ) -> "QosConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:], "r", encoding="utf-8") as f:
                raw = f.read()
        return cls.from_dict(json.loads(raw))


class _ClassState:
    """Runtime state for one class: quota bucket, in-flight count,
    governor throttle ratio, shed/preempt tallies."""

    __slots__ = ("cfg", "bucket", "inflight", "throttle_ratio",
                 "sheds", "preemptions", "throttles")

    def __init__(self, cfg: QosClassConfig, clock):
        self.cfg = cfg
        self.bucket = None
        if cfg.tokens_per_s > 0:
            self.bucket = TokenBucket(
                cfg.tokens_per_s, cfg.burst or cfg.tokens_per_s,
                clock=clock)
        self.inflight = 0
        self.throttle_ratio = 1.0
        self.sheds = 0
        self.preemptions = 0
        self.throttles = 0


class QosController:
    """Classify, gate, and govern tenant classes for one engine.

    The engine stamps ``req.qos_class`` via :meth:`classify`, the
    admission controller calls :meth:`admit` ahead of its shared gates,
    the scheduler's WFQ queue reads :meth:`weight` / :meth:`is_preempt`
    and reports batch splits through :meth:`note_preemption`, and the
    governor thread (:meth:`start_governor`) closes the SLO-burn →
    token-bucket feedback loop.
    """

    def __init__(self, config: QosConfig | None = None, metrics=None,
                 clock=time.monotonic):
        self.config = config or QosConfig()
        self._metrics = metrics  # EngineMetrics | None
        self._clock = clock
        self._lock = lockdep.Lock("qos.controller")
        self._classes: dict[str, _ClassState] = {
            name: _ClassState(cfg, clock)
            for name, cfg in self.config.classes.items()
        }
        # Governor state: last burn sighting, last occupancy totals.
        self._governor: threading.Thread | None = None
        self._governor_stop = threading.Event()
        self._last_burn_ts = 0.0
        self._last_occupancy: dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    @classmethod
    def from_env(cls, metrics=None, environ=os.environ) -> "QosController":
        return cls(QosConfig.from_env(environ), metrics=metrics)

    # -- classification -------------------------------------------------------

    def classify(self, tenant: str = "", priority: int = 0) -> str:
        """Tenant table first, then the widest matching ``min_priority``
        band, then ``default_class``."""
        if not self.enabled:
            return ""
        cname = self.config.tenants.get(tenant or "")
        if cname:
            return cname
        if priority > 0:
            banded = [c for c in self.config.classes.values()
                      if 0 < c.min_priority <= priority]
            if banded:
                # The tightest band wins: highest min_priority at/below
                # the request's priority.
                return max(banded, key=lambda c: c.min_priority).name
        return self.config.default_class

    def priority_level(self, cls_name: str) -> int:
        cfg = self.config.classes.get(cls_name)
        return cfg.priority_level if cfg is not None else 0

    def weight(self, cls_name: str) -> float:
        cfg = self.config.classes.get(cls_name)
        return cfg.weight if cfg is not None else 1.0

    def is_preempt(self, cls_name: str) -> bool:
        cfg = self.config.classes.get(cls_name)
        return bool(cfg is not None and cfg.preempt)

    def class_names(self) -> list[str]:
        return list(self.config.classes)

    # -- admission gates ------------------------------------------------------

    def admit(self, model: str, cls_name: str, *,
              class_queue_depth: int = 0) -> None:
        """Per-class gates ahead of the shared admission checks; raises
        :class:`AdmissionError` (reason ``qos_inflight`` / ``qos_queue``
        / ``qos_throttled``) on shed. Pushback is **class-aware**: when
        the class carries a bucket, every shed advertises that bucket's
        refill time — honest long pushback for rate-capped batch/shadow
        tenants instead of the shared EWMA estimate."""
        state = self._classes.get(cls_name)
        if state is None:
            return
        cfg = state.cfg
        if cfg.max_inflight > 0 and state.inflight >= cfg.max_inflight:
            self._shed(model, state, "qos_inflight", AdmissionError(
                f"qos class '{cls_name}' is at its concurrency cap "
                f"({state.inflight}/{cfg.max_inflight} in flight)",
                retry_after_s=self._class_pushback(state),
                reason="qos_inflight"))
        if cfg.max_queue_depth > 0 \
                and class_queue_depth >= cfg.max_queue_depth:
            self._shed(model, state, "qos_queue", AdmissionError(
                f"qos class '{cls_name}' queue depth {class_queue_depth} "
                f"is at its cap ({cfg.max_queue_depth})",
                retry_after_s=self._class_pushback(state),
                reason="qos_queue"))
        if state.bucket is not None and not state.bucket.try_acquire():
            self._shed(model, state, "qos_throttled", AdmissionError(
                f"qos class '{cls_name}' request rate exceeds "
                f"{state.bucket.rate:g}/s (burst {state.bucket.burst:g}"
                f"{', throttled' if state.throttle_ratio < 1.0 else ''})",
                retry_after_s=state.bucket.retry_after_s(),
                reason="qos_throttled"))

    @staticmethod
    def _class_pushback(state: _ClassState) -> float:
        """Class-aware Retry-After: the class bucket's refill time when
        one is configured (a capped tenant cannot usefully retry before
        a token exists), else the floor."""
        if state.bucket is not None:
            return state.bucket.retry_after_s()
        return MIN_RETRY_AFTER_S

    def _shed(self, model: str, state: _ClassState, reason: str,
              exc: AdmissionError):
        with self._lock:
            state.sheds += 1
        if self._metrics is not None:
            self._metrics.qos_sheds.inc(
                qos_class=state.cfg.name, reason=reason)
        raise exc

    # -- lifetime accounting --------------------------------------------------

    def on_request_start(self, cls_name: str) -> None:
        state = self._classes.get(cls_name)
        if state is None:
            return
        with self._lock:
            state.inflight += 1
            inflight = state.inflight
        if self._metrics is not None:
            self._metrics.qos_inflight.set(inflight, qos_class=cls_name)

    def on_request_end(self, cls_name: str) -> None:
        state = self._classes.get(cls_name)
        if state is None:
            return
        with self._lock:
            state.inflight = max(0, state.inflight - 1)
            inflight = state.inflight
        if self._metrics is not None:
            self._metrics.qos_inflight.set(inflight, qos_class=cls_name)

    def note_preemption(self, model: str, cls_name: str) -> None:
        """A WFQ batch split in ``cls_name``'s favor (scheduler hook)."""
        state = self._classes.get(cls_name)
        if state is not None:
            with self._lock:
                state.preemptions += 1
        if self._metrics is not None:
            self._metrics.qos_preemptions.inc(model=model)

    # -- the SLO-burn governor ------------------------------------------------

    def throttle(self, cls_name: str, reason: str = "slo_burn") -> bool:
        """Tighten one class's bucket by ``throttle_factor`` (floored at
        ``min_rate_ratio`` x configured rate). Returns True when the
        rate actually moved. The unthrottled→throttled edge lands in
        the journal as ``qos.throttle``."""
        state = self._classes.get(cls_name)
        if state is None or state.bucket is None or state.cfg.protect:
            return False
        with self._lock:
            new_ratio = max(self.config.min_rate_ratio,
                            state.throttle_ratio
                            * self.config.throttle_factor)
            if new_ratio >= state.throttle_ratio:
                return False
            entered = state.throttle_ratio >= 1.0
            state.throttle_ratio = new_ratio
            state.bucket.set_rate(state.cfg.tokens_per_s * new_ratio)
            state.throttles += 1
        self._export_ratio(cls_name, new_ratio)
        if entered:
            self._journal().emit(
                "qos", "throttle", severity="WARNING",
                qos_class=cls_name, reason=reason,
                ratio=round(new_ratio, 4),
                rate=round(state.cfg.tokens_per_s * new_ratio, 3))
        return True

    def restore(self, cls_name: str) -> bool:
        """Walk one class's bucket back up one step (inverse of
        :meth:`throttle`); the throttled→restored edge (ratio back at
        1.0) journals as ``qos.restore``."""
        state = self._classes.get(cls_name)
        if state is None or state.bucket is None:
            return False
        with self._lock:
            if state.throttle_ratio >= 1.0:
                return False
            new_ratio = min(1.0, state.throttle_ratio
                            / self.config.throttle_factor)
            state.throttle_ratio = new_ratio
            state.bucket.set_rate(state.cfg.tokens_per_s * new_ratio)
            restored = new_ratio >= 1.0
        self._export_ratio(cls_name, new_ratio)
        if restored:
            self._journal().emit(
                "qos", "restore", qos_class=cls_name,
                rate=round(state.cfg.tokens_per_s, 3))
        return True

    def _export_ratio(self, cls_name: str, ratio: float) -> None:
        if self._metrics is not None:
            self._metrics.qos_throttle_ratio.set(ratio, qos_class=cls_name)

    def throttled_classes(self) -> list[str]:
        with self._lock:
            return [n for n, s in self._classes.items()
                    if s.throttle_ratio < 1.0]

    def start_governor(self, slo, costs,
                       interval_s: float | None = None) -> None:
        """Close the feedback loop: while ``slo.fast_burn()`` reports
        burning models, tighten the non-protected class with the
        highest cost-ledger occupancy growth (device + host seconds per
        tick, tenants mapped through :meth:`classify`); once the alarm
        stays clear for ``restore_hold_s``, walk rates back up."""
        if not self.enabled or self._governor is not None:
            return
        if not any(s.bucket is not None and not s.cfg.protect
                   for s in self._classes.values()):
            return  # nothing the governor could actuate
        interval = interval_s or self.config.governor_interval_s
        self._governor_stop.clear()

        def _loop():
            while not self._governor_stop.wait(interval):
                try:
                    self.governor_tick(slo, costs)
                # tpulint: allow[swallowed-exception] the governor is advisory — a bad tick must not kill the feedback thread
                except Exception:  # noqa: BLE001
                    pass

        self._governor = threading.Thread(
            target=_loop, name="qos-governor", daemon=True)
        self._governor.start()

    def stop_governor(self) -> None:
        self._governor_stop.set()
        t = self._governor
        if t is not None:
            t.join(timeout=2.0)
        self._governor = None

    def governor_tick(self, slo, costs) -> str | None:
        """One feedback step (exposed for fake-clock tests). Returns the
        class throttled/restored this tick, if any."""
        burning = slo.fast_burn() if slo is not None else []
        now = self._clock()
        if burning:
            self._last_burn_ts = now
            victim = self._pick_victim(costs)
            if victim is not None and self.throttle(victim):
                return victim
            return None
        if self._last_burn_ts and \
                now - self._last_burn_ts >= self.config.restore_hold_s:
            for name in self.throttled_classes():
                if self.restore(name):
                    return name
        return None

    def _pick_victim(self, costs) -> str | None:
        """The non-protected, bucket-carrying class with the largest
        occupancy growth (device + host seconds) since the last tick."""
        occupancy: dict[str, float] = {}
        if costs is not None:
            try:
                snap = costs.snapshot()
            # tpulint: allow[swallowed-exception] occupancy is only a victim-selection hint
            except Exception:  # noqa: BLE001
                snap = {}
            for tenant, entry in (snap.get("tenants") or {}).items():
                cname = self.classify(tenant)
                occupancy[cname] = occupancy.get(cname, 0.0) + \
                    float(entry.get("device_s", 0.0)) + \
                    float(entry.get("host_s", 0.0)) + \
                    float(entry.get("padding_s", 0.0))
        deltas = {n: occupancy.get(n, 0.0) - self._last_occupancy.get(n, 0.0)
                  for n in self._classes}
        self._last_occupancy = occupancy
        candidates = [
            (deltas.get(n, 0.0), occupancy.get(n, 0.0), n)
            for n, s in self._classes.items()
            if s.bucket is not None and not s.cfg.protect
        ]
        if not candidates:
            return None
        # Highest growth wins; cumulative occupancy then name break ties
        # (a flat tick still needs a deterministic victim).
        candidates.sort(reverse=True)
        return candidates[0][2]

    # -- report ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """The controller half of ``GET /v2/qos`` (the engine layers
        per-model class queue depths on top)."""
        classes = {}
        with self._lock:
            for name, s in self._classes.items():
                cfg = s.cfg
                classes[name] = {
                    "weight": cfg.weight,
                    "priority_level": cfg.priority_level,
                    "min_priority": cfg.min_priority,
                    "preempt": cfg.preempt,
                    "protect": cfg.protect,
                    "tokens_per_s": cfg.tokens_per_s,
                    "burst": cfg.burst or cfg.tokens_per_s,
                    "throttle_ratio": round(s.throttle_ratio, 4),
                    "effective_rate": round(
                        cfg.tokens_per_s * s.throttle_ratio, 3),
                    "max_inflight": cfg.max_inflight,
                    "max_queue_depth": cfg.max_queue_depth,
                    "inflight": s.inflight,
                    "sheds": s.sheds,
                    "preemptions": s.preemptions,
                    "throttles": s.throttles,
                    "tenants": sorted(
                        t for t, c in self.config.tenants.items()
                        if c == name),
                }
        return {
            "enabled": self.enabled,
            "default_class": self.config.default_class,
            "governor": {
                "running": self._governor is not None,
                "throttle_factor": self.config.throttle_factor,
                "min_rate_ratio": self.config.min_rate_ratio,
                "restore_hold_s": self.config.restore_hold_s,
                "throttled": self.throttled_classes(),
            },
            "classes": classes,
        }

    def _journal(self):
        from client_tpu.observability.events import journal

        return journal()
