"""Server-side admission control: load shedding, throttling, pushback.

PR 2 made the *client* resilient; this module is the server-side
complement. Under sustained overload an unprotected queue grows until
every request times out — the classic metastable failure. The standard
SRE remedy is to shed early and tell the client when to come back:

* **Load shedder** — reject when the scheduler queue is deeper than
  ``max_queue_depth``, or when the *estimated wait* (queue depth x EWMA
  service time / instances) exceeds ``max_estimated_wait_s``. A request
  that would wait longer than its caller will is dead on arrival; failing
  it in microseconds preserves capacity for requests that can still
  succeed.
* **Per-model token buckets** — ``tokens_per_s`` + ``burst`` rate caps
  and a ``max_inflight`` concurrency cap, so one model cannot starve the
  rest of the repository.
* **Retry-After pushback** — every rejection is an
  :class:`AdmissionError` (HTTP 429 / gRPC RESOURCE_EXHAUSTED) carrying
  ``retry_after_s``: the frontends surface it as a ``Retry-After`` header
  / retry-pushback trailing metadata, and the client ``RetryPolicy``
  honors it instead of guessing with blind exponential backoff.
* **Shadow admission class** — requests whose priority is at or above
  ``shadow_priority`` (Triton convention: higher number = less urgent)
  are classed *shadow* — replayed/offline traffic fed through the shm
  fan-in plane by ``tools/replay.py``. Shadow traffic gets its own,
  stricter gates (``shadow_max_inflight``, ``shadow_max_queue_depth``)
  evaluated *before* the shared ones, so replay sheds first and live
  p99 stays intact while the engine soaks spare capacity.
* **DEGRADED health** — while the controller is actively shedding,
  ``TpuEngine.health_state()`` reports DEGRADED (surfaced via
  ``/v2/health/ready``) so load balancers can steer traffic away before
  the instance falls over.

Configuration is programmatic (``AdmissionController(AdmissionConfig(...))``)
or via the ``CLIENT_TPU_ADMISSION`` environment variable holding JSON::

    CLIENT_TPU_ADMISSION='{"max_queue_depth": 256,
        "max_estimated_wait_s": 2.0,
        "models": {"bert_base": {"tokens_per_s": 100, "burst": 20,
                                 "max_inflight": 64}}}'

Every limit defaults to off (0), so an unconfigured engine admits
everything — the controller then only provides in-flight accounting for
the drain coordinator (:mod:`client_tpu.admission.drain`).

Rejections are exported as ``tpu_admission_rejections_total{model,
version,reason}`` on the engine's metric registry.
"""

from __future__ import annotations

import json
import os
from client_tpu.utils import lockdep
from client_tpu import config as envcfg
import time
from dataclasses import dataclass, field

from client_tpu.engine.types import EngineError

__all__ = [
    "ENV_VAR",
    "AdmissionConfig",
    "AdmissionError",
    "AdmissionController",
    "TokenBucket",
]

ENV_VAR = "CLIENT_TPU_ADMISSION"

# Pushback floor: never tell a client to come back in less than this
# (a 0-second Retry-After degenerates into a tight retry loop).
MIN_RETRY_AFTER_S = 0.01
# Pushback ceiling: under pathological estimates, cap the advertised wait
# so clients re-probe within a bounded window.
MAX_RETRY_AFTER_S = 30.0

# EWMA smoothing for the per-model service-time estimate: ~86% of the
# weight within the last 12 observations — reactive enough to follow a
# load shift, smooth enough to ignore one slow compile.
_EWMA_ALPHA = 0.15


class AdmissionError(EngineError):
    """A request shed at admission. ``retry_after_s`` is the server's
    pushback: how long the client should wait before retrying (surfaced
    as the HTTP ``Retry-After`` header / gRPC retry-pushback trailing
    metadata). ``reason`` matches the metric label."""

    def __init__(self, message: str, retry_after_s: float,
                 reason: str = "shed", status: int = 429):
        super().__init__(message, status)
        self.retry_after_s = max(MIN_RETRY_AFTER_S,
                                 min(float(retry_after_s), MAX_RETRY_AFTER_S))
        self.reason = reason


def _clip_retry_after(s: float) -> float:
    return max(MIN_RETRY_AFTER_S, min(float(s), MAX_RETRY_AFTER_S))


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity. ``try_acquire`` never blocks; a failed acquire pairs with
    :meth:`retry_after_s` — the refill time until the request would fit —
    which becomes the rejection's pushback."""

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError("TokenBucket rate must be > 0")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._stamp = clock()
        self._lock = lockdep.Lock("admission.bucket")

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._stamp) * self.rate)
        self._stamp = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        with self._lock:
            self._refill_locked()
            deficit = n - self._tokens
        if deficit <= 0:
            return MIN_RETRY_AFTER_S
        return deficit / self.rate

    def available(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens

    def set_rate(self, rate: float) -> None:
        """Retarget the refill rate (the QoS governor's actuator).
        Tokens accrued at the old rate are banked first, so a rate cut
        never claws back credit already earned."""
        with self._lock:
            self._refill_locked()
            self.rate = max(1e-9, float(rate))


@dataclass
class AdmissionConfig:
    """Per-model (or default) admission limits; 0 disables each check."""

    # Shed when the model's scheduler queue is at/over this depth.
    max_queue_depth: int = 0
    # Shed when queue_depth x EWMA service time / instances exceeds this.
    max_estimated_wait_s: float = 0.0
    # Token-bucket rate cap (requests/s); burst defaults to the rate.
    tokens_per_s: float = 0.0
    burst: float = 0.0
    # Concurrency cap: requests admitted but not yet finally responded.
    max_inflight: int = 0
    # How long after the last shed the engine stays DEGRADED.
    degraded_hold_s: float = 5.0
    # Shadow class: requests with priority >= shadow_priority (0 = no
    # shadow class) pass these stricter gates before the shared ones.
    shadow_priority: int = 0
    shadow_max_inflight: int = 0
    shadow_max_queue_depth: int = 0
    # Per-model overrides, keyed by model name (dicts of the fields above).
    models: dict[str, dict] = field(default_factory=dict)

    _FIELDS = ("max_queue_depth", "max_estimated_wait_s", "tokens_per_s",
               "burst", "max_inflight", "degraded_hold_s",
               "shadow_priority", "shadow_max_inflight",
               "shadow_max_queue_depth")

    @classmethod
    def from_dict(cls, d: dict) -> "AdmissionConfig":
        d = dict(d or {})
        models = d.pop("models", {}) or {}
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(
                f"unknown admission config keys: {sorted(unknown)}")
        for name, override in models.items():
            bad = set(override) - set(cls._FIELDS)
            if bad:
                raise ValueError(
                    f"unknown admission config keys for model "
                    f"'{name}': {sorted(bad)}")
        return cls(models=models, **d)

    @classmethod
    def from_env(cls, environ=os.environ) -> "AdmissionConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:], "r", encoding="utf-8") as f:
                raw = f.read()
        return cls.from_dict(json.loads(raw))

    def for_model(self, name: str) -> "AdmissionConfig":
        """Effective limits for one model: defaults + per-model override."""
        override = self.models.get(name)
        if not override:
            return self
        merged = {f: getattr(self, f) for f in self._FIELDS}
        merged.update(override)
        return AdmissionConfig(**merged)


class _ModelGate:
    """Per-model admission state: bucket, in-flight count, service EWMA."""

    __slots__ = ("cfg", "bucket", "inflight", "shadow_inflight",
                 "ewma_service_s", "rate_ratio", "dyn_bucket",
                 "dyn_base_rate", "dyn_max_inflight")

    def __init__(self, cfg: AdmissionConfig):
        self.cfg = cfg
        self.bucket = None
        if cfg.tokens_per_s > 0:
            self.bucket = TokenBucket(
                cfg.tokens_per_s, cfg.burst or cfg.tokens_per_s)
        self.inflight = 0
        self.shadow_inflight = 0
        self.ewma_service_s = 0.0
        # Self-drive actuator state (tighten_model / set_concurrency_cap):
        # the fraction of the configured rate currently admitted, a
        # synthesized bucket for models with no configured rate cap, and
        # a dynamic concurrency cap (0 = none). All of these only ever
        # *tighten* relative to cfg.
        self.rate_ratio = 1.0
        self.dyn_bucket = None
        self.dyn_base_rate = 0.0
        self.dyn_max_inflight = 0


class AdmissionController:
    """Admission decisions + in-flight accounting for one engine.

    The engine calls :meth:`admit` before every scheduler submit and the
    start/end hooks around each request's lifetime; the drain coordinator
    reads :meth:`total_inflight` to know when the server is empty.
    Thread-safe; the hot path is one lock acquisition.
    """

    def __init__(self, config: AdmissionConfig | None = None,
                 metrics=None, clock=time.monotonic):
        self.config = config or AdmissionConfig()
        self._metrics = metrics  # EngineMetrics | None
        self._clock = clock
        self._lock = lockdep.Lock("admission.controller")
        self._gates: dict[str, _ModelGate] = {}
        # Optional QoS controller (client_tpu.admission.qos): per-class
        # gates evaluated ahead of the shared ones when attached.
        self.qos = None
        self._last_shed = 0.0
        # True between the first shed and the hold-window expiry observed
        # by degraded(); drives degraded_enter/degraded_exit events.
        self._degraded_state = False
        self.rejection_count = 0

    @classmethod
    def from_env(cls, metrics=None, environ=os.environ
                 ) -> "AdmissionController":
        return cls(AdmissionConfig.from_env(environ), metrics=metrics)

    def _gate(self, model: str) -> _ModelGate:
        gate = self._gates.get(model)
        if gate is None:
            with self._lock:
                gate = self._gates.setdefault(
                    model, _ModelGate(self.config.for_model(model)))
        return gate

    # -- the admission decision ---------------------------------------------

    def is_shadow(self, model: str, priority: int = 0) -> bool:
        """True when ``priority`` puts the request in the model's shadow
        class (``shadow_priority`` configured and priority at/above it)."""
        cfg = self._gate(model).cfg
        return cfg.shadow_priority > 0 and priority >= cfg.shadow_priority

    def attach_qos(self, qos) -> None:
        """Bind a :class:`~client_tpu.admission.qos.QosController`; its
        per-class gates run first in :meth:`admit` and its sheds land on
        the same rejection counter/journal/ledger as the shared ones."""
        self.qos = qos if qos is not None and \
            getattr(qos, "enabled", False) else None

    def admit(self, model: str, version: str = "",
              queue_depth: int = 0, instances: int = 1,
              trace_id: str | None = None, priority: int = 0,
              tenant: str = "", qos_class: str = "",
              class_queue_depth: int = 0) -> None:
        """Admit or shed one request; raises :class:`AdmissionError` on
        shed. ``queue_depth`` is the model's current scheduler backlog and
        ``instances`` its worker count (for the estimated-wait check).
        ``trace_id`` correlates a shed with the rejected request's trace
        in the event journal. ``priority`` selects the admission class:
        at/above ``shadow_priority`` the stricter shadow gates apply
        first, so replay traffic sheds before it can queue behind live.
        ``tenant`` attributes a shed on the metrics/ledger side. With a
        QoS controller attached, ``qos_class`` / ``class_queue_depth``
        drive the per-class gates (quota, class inflight/queue caps) —
        their pushback is the class bucket's refill time, not the shared
        EWMA estimate."""
        gate = self._gate(model)
        cfg = gate.cfg
        if self.qos is not None and qos_class:
            try:
                self.qos.admit(model, qos_class,
                               class_queue_depth=class_queue_depth)
            except AdmissionError as exc:
                self._count_shed(model, version, exc.reason,
                                 retry_after_s=exc.retry_after_s,
                                 trace_id=trace_id, tenant=tenant)
                raise
        if cfg.shadow_priority > 0 and priority >= cfg.shadow_priority:
            if cfg.shadow_max_inflight > 0 \
                    and gate.shadow_inflight >= cfg.shadow_max_inflight:
                self._reject(model, version, "shadow", AdmissionError(
                    f"model '{model}' shadow class is at its concurrency "
                    f"cap ({gate.shadow_inflight}/"
                    f"{cfg.shadow_max_inflight} in flight)",
                    retry_after_s=gate.ewma_service_s or MIN_RETRY_AFTER_S,
                    reason="shadow"), trace_id=trace_id, tenant=tenant)
            if cfg.shadow_max_queue_depth > 0 \
                    and queue_depth >= cfg.shadow_max_queue_depth:
                est = self._estimated_wait_s(gate, queue_depth, instances)
                self._reject(model, version, "shadow", AdmissionError(
                    f"model '{model}' queue depth {queue_depth} is at "
                    f"the shadow shed limit "
                    f"({cfg.shadow_max_queue_depth})",
                    retry_after_s=est, reason="shadow"),
                    trace_id=trace_id, tenant=tenant)
        # Effective concurrency cap: the configured one, tightened (never
        # relaxed) by the self-drive governor's dynamic cap.
        inflight_cap = cfg.max_inflight
        if gate.dyn_max_inflight > 0:
            inflight_cap = min(inflight_cap, gate.dyn_max_inflight) \
                if inflight_cap > 0 else gate.dyn_max_inflight
        if inflight_cap > 0 and gate.inflight >= inflight_cap:
            # Pushback ~ one service interval: a slot frees when the
            # oldest in-flight request completes.
            self._reject(model, version, "concurrency", AdmissionError(
                f"model '{model}' is at its concurrency cap "
                f"({gate.inflight}/{inflight_cap} in flight)",
                retry_after_s=gate.ewma_service_s or MIN_RETRY_AFTER_S,
                reason="concurrency"), trace_id=trace_id, tenant=tenant)
        if gate.bucket is not None and not gate.bucket.try_acquire():
            self._reject(model, version, "throttled", AdmissionError(
                f"model '{model}' request rate exceeds "
                f"{cfg.tokens_per_s:g}/s (burst {gate.bucket.burst:g})",
                retry_after_s=gate.bucket.retry_after_s(),
                reason="throttled"), trace_id=trace_id, tenant=tenant)
        if gate.dyn_bucket is not None \
                and not gate.dyn_bucket.try_acquire():
            # A model with no configured rate cap that the governor
            # tightened under SLO burn: shed on the synthesized bucket.
            self._reject(model, version, "tightened", AdmissionError(
                f"model '{model}' admission tightened to "
                f"{gate.rate_ratio:g}x of observed capacity under SLO "
                "burn", retry_after_s=gate.dyn_bucket.retry_after_s(),
                reason="tightened"), trace_id=trace_id, tenant=tenant)
        if cfg.max_queue_depth > 0 and queue_depth >= cfg.max_queue_depth:
            est = self._estimated_wait_s(gate, queue_depth, instances)
            self._reject(model, version, "queue_depth", AdmissionError(
                f"model '{model}' queue depth {queue_depth} is at the "
                f"shed limit ({cfg.max_queue_depth}); estimated wait "
                f"{est:.3f}s", retry_after_s=est, reason="queue_depth"),
                trace_id=trace_id, tenant=tenant)
        if cfg.max_estimated_wait_s > 0:
            est = self._estimated_wait_s(gate, queue_depth, instances)
            if est > cfg.max_estimated_wait_s:
                self._reject(model, version, "estimated_wait",
                             AdmissionError(
                                 f"model '{model}' estimated queue wait "
                                 f"{est:.3f}s exceeds the shed limit "
                                 f"({cfg.max_estimated_wait_s:g}s)",
                                 retry_after_s=est - cfg.max_estimated_wait_s
                                 + MIN_RETRY_AFTER_S,
                                 reason="estimated_wait"),
                             trace_id=trace_id, tenant=tenant)

    @staticmethod
    def _estimated_wait_s(gate: _ModelGate, queue_depth: int,
                          instances: int) -> float:
        service = gate.ewma_service_s or MIN_RETRY_AFTER_S
        return queue_depth * service / max(1, instances)

    def _reject(self, model: str, version: str, reason: str,
                exc: AdmissionError, trace_id: str | None = None,
                tenant: str = ""):
        self._count_shed(model, version, reason,
                         retry_after_s=exc.retry_after_s,
                         trace_id=trace_id, tenant=tenant)
        raise exc

    def record_rejection(self, model: str, version: str = "",
                         reason: str = "draining",
                         trace_id: str | None = None,
                         tenant: str = "") -> None:
        """Count a shed decided outside :meth:`admit` (e.g. the engine's
        drain gate) on the same counter and DEGRADED clock."""
        self._count_shed(model, version, reason, trace_id=trace_id,
                         tenant=tenant)

    def _count_shed(self, model: str, version: str, reason: str,
                    retry_after_s: float | None = None,
                    trace_id: str | None = None,
                    tenant: str = "") -> None:
        with self._lock:
            self.rejection_count += 1
            self._last_shed = self._clock()
            entered = not self._degraded_state
            self._degraded_state = True
        tenant = tenant or "default"
        if self._metrics is not None:
            self._metrics.admission_rejections.inc(
                model=model, version=str(version or "latest"),
                reason=reason, tenant=tenant, exemplar=trace_id)
        # Lazy, like _journal(): count the shed on the cost ledger's
        # interference taxonomy (the `admission` leg).
        from client_tpu.observability.costs import ledger

        ledger().note_shed(model, version or "latest", tenant, reason)
        jour = self._journal()
        if jour is not None:
            detail = {"reason": reason, "tenant": tenant}
            if retry_after_s is not None:
                detail["retry_after_s"] = round(retry_after_s, 4)
            jour.emit("admission", "shed", severity="WARNING",
                      model=model, version=version or None,
                      trace_id=trace_id, **detail)
            if entered:
                jour.emit("admission", "degraded_enter",
                          severity="WARNING", model=model,
                          version=version or None, trace_id=trace_id,
                          hold_s=self.config.degraded_hold_s)

    def _journal(self):
        """The process-global event journal (lazy: admission is imported
        by engine.types consumers that never serve)."""
        from client_tpu.observability.events import journal

        return journal()

    # -- lifetime accounting -------------------------------------------------

    def on_request_start(self, model: str, shadow: bool = False) -> None:
        gate = self._gate(model)
        with self._lock:
            gate.inflight += 1
            if shadow:
                gate.shadow_inflight += 1

    def on_request_end(self, model: str, service_s: float | None = None,
                       shadow: bool = False) -> None:
        gate = self._gate(model)
        with self._lock:
            gate.inflight = max(0, gate.inflight - 1)
            if shadow:
                gate.shadow_inflight = max(0, gate.shadow_inflight - 1)
            if service_s is not None and service_s > 0:
                if gate.ewma_service_s <= 0:
                    gate.ewma_service_s = service_s
                else:
                    gate.ewma_service_s += _EWMA_ALPHA * (
                        service_s - gate.ewma_service_s)

    def inflight(self, model: str) -> int:
        gate = self._gates.get(model)
        return gate.inflight if gate is not None else 0

    def total_inflight(self) -> int:
        with self._lock:
            return sum(g.inflight for g in self._gates.values())

    def estimated_service_s(self, model: str) -> float:
        gate = self._gates.get(model)
        return gate.ewma_service_s if gate is not None else 0.0

    def load_snapshot(self) -> dict[str, dict]:
        """Per-model load inputs for the replica load report
        (:meth:`TpuEngine.load_report`): the in-flight count and the
        service EWMA that the estimated-wait shed check already uses —
        one lock acquisition for the whole table."""
        with self._lock:
            return {m: {"inflight": g.inflight,
                        "shadow_inflight": g.shadow_inflight,
                        "ewma_service_s": g.ewma_service_s}
                    for m, g in self._gates.items()}

    # -- self-drive actuators (SLO-burn tightening, concurrency nudges) ------

    def tighten_model(self, model: str, version: str = "", *,
                      factor: float = 0.5, min_ratio: float = 0.1,
                      reason: str = "slo_burn") -> bool:
        """Progressively lower the model's admitted rate (the SLO-burn
        loop's actuator). Each call multiplies the current rate ratio by
        ``factor``, floored at ``min_ratio``. With a configured token
        bucket the cut retargets its refill rate; without one a bucket is
        synthesized from the observed service capacity (1/EWMA), so even
        an uncapped model can be shed under burn. Returns True when the
        ratio actually moved. Journals ``admission.tighten`` only on the
        untightened->tightened edge — the QoS governor's hysteresis
        idiom — so a sustained burn logs one edge, not one per tick."""
        gate = self._gate(model)
        cfg = gate.cfg
        with self._lock:
            old = gate.rate_ratio
            new = max(min_ratio, old * factor)
            if new >= old:
                return False
            gate.rate_ratio = new
            entered = old >= 1.0
            if gate.bucket is None and gate.dyn_bucket is None:
                # Capacity estimate for the synthesized cap; 1ms floor
                # keeps a cold EWMA from minting an absurd rate.
                gate.dyn_base_rate = 1.0 / max(gate.ewma_service_s, 1e-3)
        if gate.bucket is not None:
            gate.bucket.set_rate(cfg.tokens_per_s * new)
        elif gate.dyn_bucket is None:
            rate = max(1e-9, gate.dyn_base_rate * new)
            gate.dyn_bucket = TokenBucket(rate, max(1.0, rate),
                                          clock=self._clock)
        else:
            gate.dyn_bucket.set_rate(gate.dyn_base_rate * new)
        if entered:
            jour = self._journal()
            if jour is not None:
                jour.emit("admission", "tighten", severity="WARNING",
                          model=model, version=version or None,
                          ratio=round(new, 4), reason=reason)
        return True

    def restore_model(self, model: str, version: str = "", *,
                      step: float = 2.0) -> bool:
        """Walk one tightened model's rate ratio back up by ``step``
        (multiplicative, capped at 1.0) — one step per quiet window, the
        governor's restore idiom. Journals ``admission.restore`` only
        when the ratio reaches 1.0 (the cleared edge). Returns True when
        the ratio moved."""
        gate = self._gate(model)
        cfg = gate.cfg
        with self._lock:
            old = gate.rate_ratio
            if old >= 1.0:
                return False
            new = min(1.0, old * max(1.0 + 1e-9, step))
            gate.rate_ratio = new
            cleared = new >= 1.0
        if gate.bucket is not None:
            gate.bucket.set_rate(cfg.tokens_per_s * new)
        elif gate.dyn_bucket is not None:
            if cleared:
                gate.dyn_bucket = None
            else:
                gate.dyn_bucket.set_rate(gate.dyn_base_rate * new)
        if cleared:
            jour = self._journal()
            if jour is not None:
                jour.emit("admission", "restore", model=model,
                          version=version or None, ratio=1.0)
        return True

    def tightened_models(self) -> dict[str, float]:
        """{model: rate_ratio} for every model currently below 1.0."""
        with self._lock:
            return {m: g.rate_ratio for m, g in self._gates.items()
                    if g.rate_ratio < 1.0}

    def set_concurrency_cap(self, model: str, cap: int | None) -> int:
        """Set (or with None, clear) the model's dynamic concurrency cap
        — the dispatch tuner's admission-side nudge. The effective cap in
        :meth:`admit` is min(configured, dynamic), so a nudge can only
        tighten. Returns the dynamic cap now in force (0 = none)."""
        gate = self._gate(model)
        with self._lock:
            gate.dyn_max_inflight = 0 if cap is None else max(1, int(cap))
            return gate.dyn_max_inflight

    def concurrency_cap(self, model: str) -> int:
        """The effective concurrency cap for ``model`` (0 = uncapped)."""
        gate = self._gate(model)
        with self._lock:
            cfg_cap, dyn = gate.cfg.max_inflight, gate.dyn_max_inflight
        if dyn > 0:
            return min(cfg_cap, dyn) if cfg_cap > 0 else dyn
        return cfg_cap

    def actuator_snapshot(self) -> dict[str, dict]:
        """Per-model self-drive actuator state for observability
        surfaces: only models with an active tighten or dynamic cap."""
        with self._lock:
            return {m: {"rate_ratio": round(g.rate_ratio, 4),
                        "dyn_max_inflight": g.dyn_max_inflight}
                    for m, g in self._gates.items()
                    if g.rate_ratio < 1.0 or g.dyn_max_inflight > 0}

    # -- health --------------------------------------------------------------

    def degraded(self) -> bool:
        """True while the controller shed recently (within
        ``degraded_hold_s``): the engine reports DEGRADED so balancers
        deprioritize the instance while it is actively overloaded. The
        enter/exit edges land in the event journal as
        ``admission.degraded_enter`` / ``admission.degraded_exit``."""
        with self._lock:
            last = self._last_shed
            now_degraded = bool(last) and (
                self._clock() - last < self.config.degraded_hold_s)
            exited = self._degraded_state and not now_degraded
            self._degraded_state = now_degraded
        if exited:
            jour = self._journal()
            if jour is not None:
                jour.emit("admission", "degraded_exit",
                          hold_s=self.config.degraded_hold_s)
        return now_degraded
