"""The `simple*` conformance-model family.

Behavioral oracles for the whole client stack, matching the models the
reference's examples assert against (add/sub INT32[16]:
/root/reference/src/c++/examples/simple_grpc_infer_client.cc:337; string,
identity, sequence and repeat variants exercised by the simple_* example
pairs, SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np

from client_tpu.engine.config import (
    DynamicBatchingConfig,
    ModelConfig,
    SequenceBatchingConfig,
    TensorConfig,
)
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model


class AddSubBackend(ModelBackend):
    """INT32[16] -> OUTPUT0=sum, OUTPUT1=diff. The canonical `simple` model."""

    def __init__(self, name: str = "simple", n: int = 16,
                 max_batch_size: int = 64, datatype: str = "INT32"):
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=max_batch_size,
            input=[
                TensorConfig("INPUT0", datatype, [n]),
                TensorConfig("INPUT1", datatype, [n]),
            ],
            output=[
                TensorConfig("OUTPUT0", datatype, [n]),
                TensorConfig("OUTPUT1", datatype, [n]),
            ],
            dynamic_batching=DynamicBatchingConfig(
                preferred_batch_size=sorted(
                    {min(8, max_batch_size), max_batch_size}),
                max_queue_delay_microseconds=100,
            ),
            # A deep batching ceiling matters more than compute here: each
            # device round trip has fixed transport latency (tens of ms when
            # the chip sits behind a network tunnel), so throughput scales
            # with how many requests ride one dispatch.  Small bucket set
            # (clamped to the configured ceiling) keeps warmup compiles cheap.
            batch_buckets=sorted(
                {b for b in (1, 8, 64) if b <= max_batch_size}
                | {max_batch_size}),
            # Several executor instances keep multiple batches in flight so
            # device round-trips overlap (the device transport pipelines
            # concurrent dispatch+fetch; serialized batches leave it idle).
            instance_count=4,
        )

    def make_apply(self):
        def apply(inputs):
            a, b = inputs["INPUT0"], inputs["INPUT1"]
            return {"OUTPUT0": a + b, "OUTPUT1": a - b}
        return apply


class StringAddSubBackend(ModelBackend):
    """BYTES decimal-string add/sub — exercises the BYTES codec end to end.

    Host-side compute (object arrays can't enter XLA), like the reference's
    simple_string model served by a CPU backend.
    """

    jittable = False

    def __init__(self, name: str = "simple_string", n: int = 16):
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=8,
            input=[
                TensorConfig("INPUT0", "BYTES", [n]),
                TensorConfig("INPUT1", "BYTES", [n]),
            ],
            output=[
                TensorConfig("OUTPUT0", "BYTES", [n]),
                TensorConfig("OUTPUT1", "BYTES", [n]),
            ],
        )

    def make_apply(self):
        def apply(inputs):
            a = np.vectorize(int)(inputs["INPUT0"]).astype(np.int64)
            b = np.vectorize(int)(inputs["INPUT1"]).astype(np.int64)
            enc = np.vectorize(lambda v: str(v).encode())
            return {
                "OUTPUT0": enc(a + b).astype(np.object_),
                "OUTPUT1": enc(a - b).astype(np.object_),
            }
        return apply


class IdentityBackend(ModelBackend):
    """BYTES passthrough (`simple_identity`) — string round-trip oracle."""

    jittable = False

    def __init__(self, name: str = "simple_identity"):
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=8,
            input=[TensorConfig("INPUT0", "BYTES", [-1])],
            output=[TensorConfig("OUTPUT0", "BYTES", [-1])],
        )

    def make_apply(self):
        def apply(inputs):
            return {"OUTPUT0": inputs["INPUT0"]}
        return apply


class SequenceAccumulateBackend(ModelBackend):
    """Stateful accumulator (`simple_sequence` semantics): OUTPUT = running
    sum of INPUT across the sequence. State = INT32[1] pytree in HBM.

    ``strategy="oldest"`` serves the same model through the arena-batched
    oldest-sequence scheduler (steps of distinct sequences share one XLA
    execution; see engine/sequence.py OldestSequenceScheduler)."""

    def __init__(self, name: str = "simple_sequence",
                 strategy: str = "direct",
                 max_candidate_sequences: int = 64):
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=0,  # sequence requests are shape [1]
            input=[TensorConfig("INPUT", "INT32", [1])],
            output=[TensorConfig("OUTPUT", "INT32", [1])],
            sequence_batching=SequenceBatchingConfig(
                strategy=strategy,
                max_candidate_sequences=max_candidate_sequences),
        )

    def initial_state(self):
        return np.zeros((1,), dtype=np.int32)

    def make_apply(self):
        def apply(state, inputs):
            acc = state + inputs["INPUT"]
            return acc, {"OUTPUT": acc}
        return apply


class RepeatBackend(ModelBackend):
    """Decoupled model (`repeat_int32` semantics): emits IN's elements one
    response at a time, with DELAY microseconds between responses."""

    jittable = False

    def __init__(self, name: str = "simple_repeat"):
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=0,
            input=[
                TensorConfig("IN", "INT32", [-1]),
                TensorConfig("DELAY", "UINT32", [-1], optional=True),
            ],
            output=[
                TensorConfig("OUT", "INT32", [1]),
                TensorConfig("IDX", "UINT32", [1]),
            ],
            decoupled=True,
        )

    def make_apply(self):
        def apply(inputs):  # non-streaming fallback: first element only
            return {
                "OUT": inputs["IN"][:1],
                "IDX": np.zeros((1,), dtype=np.uint32),
            }
        return apply

    def generate(self, inputs: dict[str, np.ndarray],
                 parameters: dict[str, Any]) -> Iterator[dict[str, np.ndarray]]:
        import time

        data = np.ravel(inputs["IN"]).astype(np.int32)
        delays = np.ravel(inputs.get("DELAY", np.zeros(0, np.uint32)))
        for i, v in enumerate(data):
            if i < len(delays) and delays[i]:
                time.sleep(int(delays[i]) / 1e6)
            yield {
                "OUT": np.array([v], dtype=np.int32),
                "IDX": np.array([i], dtype=np.uint32),
            }


register_model("simple")(AddSubBackend)
register_model("simple_string")(StringAddSubBackend)
register_model("simple_identity")(IdentityBackend)
register_model("simple_sequence")(SequenceAccumulateBackend)
register_model("simple_sequence_oldest")(
    lambda: SequenceAccumulateBackend(name="simple_sequence_oldest",
                                      strategy="oldest"))
# INT8 add/sub variant (reference simple_int8 model, exercised by the
# explicit-content raw-stub clients).
register_model("simple_int8")(
    lambda: AddSubBackend(name="simple_int8", max_batch_size=8,
                          datatype="INT8"))
register_model("simple_repeat")(RepeatBackend)
