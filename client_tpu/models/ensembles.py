"""Ensemble pipelines and their composing pre/post-process models.

BASELINE.md config 5 names the flagship pipeline: preprocess → BERT-base →
postprocess with string I/O, served like the reference serves ensembles
(composing steps declared via input_map/output_map, executed by the engine's
EnsembleScheduler with per-composing-model statistics — the reference's perf
harness rolls these up in inference_profiler.cc:910-960).

Composing host-side models (jittable=False — BYTES object arrays cannot
enter XLA; this mirrors Triton's Python/DALI preprocess backends):

- ``bert_preprocess``   BYTES text [1] -> input_ids/attention_mask INT32[S]
  (deterministic hash wordpiece stand-in — no vocab files ship with the
  reference either)
- ``bert_postprocess``  logits FP32[num_labels] -> BYTES label + FP32 score
- ``image_preprocess``  UINT8 HWC (any size) -> FP32 [224,224,3] resized and
  normalized (the reference's image_client does this client-side with
  OpenCV, image_client.cc:26-120; ensemble_image_client pushes it into an
  ensemble, which is what this models)

Ensembles:

- ``ensemble_bert``  TEXT -> LABEL, SCORE        (preprocess→bert_base→post)
- ``ensemble_image`` RAW_IMAGE -> CLASS_LOGITS   (image_preprocess→resnet50)
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.config import EnsembleStep, ModelConfig, TensorConfig
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model
from client_tpu.models.bert import BertBackend

SEQ_LEN = 128
CLS_ID = 101
SEP_ID = 102


def _hash_token(tok: bytes) -> int:
    """Stable token-id hash into the BERT vocab range (1000..30521)."""
    h = 2166136261
    for c in tok:
        h = ((h ^ c) * 16777619) & 0xFFFFFFFF
    return 1000 + h % (30522 - 1000)


class BertPreprocessBackend(ModelBackend):
    jittable = False

    def __init__(self, name: str = "bert_preprocess", seq_len: int = SEQ_LEN):
        self.seq_len = seq_len
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=8,
            input=[TensorConfig("TEXT", "BYTES", [1])],
            output=[
                TensorConfig("input_ids", "INT32", [seq_len]),
                TensorConfig("attention_mask", "INT32", [seq_len]),
            ],
        )

    def make_apply(self):
        seq_len = self.seq_len

        def apply(inputs):
            texts = inputs["TEXT"]
            batch = texts.shape[0]
            ids = np.zeros((batch, seq_len), np.int32)
            mask = np.zeros((batch, seq_len), np.int32)
            for i in range(batch):
                raw = texts[i, 0]
                if isinstance(raw, str):
                    raw = raw.encode()
                toks = [_hash_token(t) for t in bytes(raw).lower().split()]
                toks = [CLS_ID] + toks[: seq_len - 2] + [SEP_ID]
                ids[i, : len(toks)] = toks
                mask[i, : len(toks)] = 1
            return {"input_ids": ids, "attention_mask": mask}

        return apply


class BertPostprocessBackend(ModelBackend):
    jittable = False

    LABELS = (b"negative", b"positive")

    def __init__(self, name: str = "bert_postprocess", num_labels: int = 2):
        self.num_labels = num_labels
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=8,
            input=[TensorConfig("logits", "FP32", [num_labels])],
            output=[
                TensorConfig("LABEL", "BYTES", [1]),
                TensorConfig("SCORE", "FP32", [1]),
            ],
        )

    def make_apply(self):
        def apply(inputs):
            logits = np.asarray(inputs["logits"], np.float32)
            exp = np.exp(logits - logits.max(axis=-1, keepdims=True))
            probs = exp / exp.sum(axis=-1, keepdims=True)
            best = probs.argmax(axis=-1)
            labels = np.array(
                [[self.LABELS[min(b, len(self.LABELS) - 1)]] for b in best],
                dtype=np.object_)
            scores = probs.max(axis=-1, keepdims=True).astype(np.float32)
            return {"LABEL": labels, "SCORE": scores}

        return apply


class ImagePreprocessBackend(ModelBackend):
    """UINT8 [H,W,3] (any size) -> FP32 [224,224,3], mean/std normalized."""

    jittable = False

    MEAN = np.array([123.675, 116.28, 103.53], np.float32)
    STD = np.array([58.395, 57.12, 57.375], np.float32)

    def __init__(self, name: str = "image_preprocess", size: int = 224):
        self.size = size
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=8,
            input=[TensorConfig("RAW_IMAGE", "UINT8", [-1, -1, 3])],
            output=[TensorConfig("IMAGE", "FP32", [size, size, 3])],
        )

    def make_apply(self):
        size = self.size

        def apply(inputs):
            imgs = inputs["RAW_IMAGE"]
            batch = imgs.shape[0]
            out = np.empty((batch, size, size, 3), np.float32)
            for i in range(batch):
                img = imgs[i]
                h, w = img.shape[0], img.shape[1]
                # nearest-neighbor resize (host-side; no OpenCV in-tree)
                ys = (np.arange(size) * h // size).clip(0, h - 1)
                xs = (np.arange(size) * w // size).clip(0, w - 1)
                resized = img[ys][:, xs].astype(np.float32)
                out[i] = (resized - self.MEAN) / self.STD
            return {"IMAGE": out}

        return apply


class EnsembleBertBackend(ModelBackend):
    """preprocess → bert_base → postprocess, string I/O end to end."""

    def __init__(self, name: str = "ensemble_bert"):
        self.config = ModelConfig(
            name=name,
            platform="ensemble",
            max_batch_size=8,
            input=[TensorConfig("TEXT", "BYTES", [1])],
            output=[
                TensorConfig("LABEL", "BYTES", [1]),
                TensorConfig("SCORE", "FP32", [1]),
            ],
            ensemble_scheduling=[
                EnsembleStep(
                    model_name="bert_preprocess",
                    input_map={"TEXT": "TEXT"},
                    output_map={"input_ids": "_ids",
                                "attention_mask": "_mask"},
                ),
                EnsembleStep(
                    model_name="bert_base",
                    input_map={"input_ids": "_ids",
                               "attention_mask": "_mask"},
                    output_map={"logits": "_logits"},
                ),
                EnsembleStep(
                    model_name="bert_postprocess",
                    input_map={"logits": "_logits"},
                    output_map={"LABEL": "LABEL", "SCORE": "SCORE"},
                ),
            ],
        )


class EnsembleImageBackend(ModelBackend):
    """image_preprocess → resnet50 (the reference's ensemble_image_client
    pipeline shape, /root/reference/src/c++/examples/ensemble_image_client.cc)."""

    def __init__(self, name: str = "ensemble_image"):
        self.config = ModelConfig(
            name=name,
            platform="ensemble",
            max_batch_size=8,
            input=[TensorConfig("RAW_IMAGE", "UINT8", [-1, -1, 3])],
            output=[TensorConfig("CLASS_LOGITS", "FP32", [1000])],
            ensemble_scheduling=[
                EnsembleStep(
                    model_name="image_preprocess",
                    input_map={"RAW_IMAGE": "RAW_IMAGE"},
                    output_map={"IMAGE": "_image"},
                ),
                EnsembleStep(
                    model_name="resnet50",
                    input_map={"INPUT": "_image"},
                    output_map={"OUTPUT": "CLASS_LOGITS"},
                ),
            ],
        )


register_model("bert_preprocess")(BertPreprocessBackend)
register_model("bert_postprocess")(BertPostprocessBackend)
register_model("image_preprocess")(ImagePreprocessBackend)
register_model("ensemble_bert")(EnsembleBertBackend)
register_model("ensemble_image")(EnsembleImageBackend)

# keep an explicit reference so linters see BertBackend as used (the ensemble
# depends on `bert_base` being registered by client_tpu.models.bert)
_ = BertBackend
