"""Model zoo for the TPU serving engine.

Conformance models (the reference's examples assert exact values against the
server's `simple*` family — e.g. add/sub INT32[16] checks in
/root/reference/src/c++/examples/simple_grpc_infer_client.cc:337):

- ``simple``            — INT32[16] add/sub (batched, dynamic batching)
- ``simple_string``     — BYTES decimal add/sub
- ``simple_identity``   — BYTES passthrough
- ``simple_sequence``   — stateful accumulator (sequence batching)
- ``simple_repeat``     — decoupled/streaming repeat
- ``simple_dyna_sequence`` — sequence + additive correlation-id semantics

Flagship models (BASELINE.md configs): ``resnet50``, ``densenet_onnx``
(DenseNet-121), ``bert_base``, ``ssd_mobilenet_v2_coco_quantized``, plus the
``ensemble_bert`` preprocess→BERT→postprocess pipeline.

All are JAX/flax, bfloat16 on the MXU where it matters.
"""

from __future__ import annotations

from typing import Callable

from client_tpu.engine.model import ModelBackend
from client_tpu.engine.repository import ModelRepository

_REGISTRY: dict[str, Callable[[], ModelBackend]] = {}
_NON_DEFAULT: set[str] = set()  # listed/loadable by name, excluded from "all"


def register_model(name: str, default: bool = True):
    def deco(builder: Callable[[], ModelBackend]):
        _REGISTRY[name] = builder
        if not default:
            _NON_DEFAULT.add(name)
        return builder
    return deco


def model_names() -> list[str]:
    _import_all()
    return sorted(_REGISTRY)


def build_repository(names: list[str] | None = None,
                     jit: bool = True) -> ModelRepository:
    """Repository with the requested zoo models registered (all by default)."""
    _import_all()
    repo = ModelRepository(jit=jit)
    for name, builder in _REGISTRY.items():
        if names is None:
            if name in _NON_DEFAULT:
                continue
        elif name not in names:
            continue
        repo.register(name, builder)
    return repo


def _import_all() -> None:
    from client_tpu.models import simple  # noqa: F401

    for mod in ("vision", "bert", "ssd", "ensembles", "generate", "dlrm"):
        try:
            __import__(f"client_tpu.models.{mod}")
        except ImportError:
            pass
    # Multi-chip serving models live with the parallelism code.
    try:
        __import__("client_tpu.parallel.serving")
    except ImportError:
        pass
