"""Autoregressive decoder LM (`tiny_gpt`) for generative serving.

No reference counterpart exists (the reference's only streaming model is the
repeat/decoupled demo, src/python/examples/simple_grpc_custom_repeat.py);
this is the framework's generative workload: a decoder-only transformer
served token-by-token through the decoupled response protocol, with
**iteration-level (continuous) batching** — concurrent generation streams
share each decode step via a KV-cache arena in HBM
(client_tpu/engine/generative.py).

TPU-first shapes: the KV cache is one pytree with leading dims
``[n_layers, capacity+1, max_seq_len, heads, head_dim]`` (the +1 row absorbs
padded decode lanes); prefill writes a whole row, each decode wave scatters
one position per active stream and computes masked attention over the static
``max_seq_len`` axis — no dynamic shapes anywhere, so XLA compiles one
executable per (prompt bucket | wave bucket).

Weights are random (seeded) — generation is deterministic nonsense, which is
exactly what the correctness tests need: batched decode must produce
bit-identical token streams to solo decode.
"""

from __future__ import annotations

import math
from client_tpu import config as envcfg

import numpy as np

from client_tpu.engine.config import ModelConfig, TensorConfig
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model


def _ln(x, g, b, eps=1e-5):
    import jax.numpy as jnp

    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) / jnp.sqrt(v + eps) * g + b


def _sample_token(logits, seed, ctx_len, temp, top_k, top_p):
    """Per-stream token choice, fully jit-traceable (vmap over streams).

    - ``temp <= 0`` → greedy argmax (the default; bit-identical to the
      pre-sampling engine).
    - Otherwise: temperature-scaled logits, top-k rank cut (``top_k == 0``
      keeps all), nucleus top-p cumulative cut (first token always kept),
      then a categorical draw.

    Determinism contract: the PRNG key is ``fold_in(PRNGKey(seed),
    ctx_len)`` where ``ctx_len`` is the context length at sampling time —
    a pure function of (request seed, position), NOT of batch composition,
    so batched decode stays bit-identical to solo decode under sampling.
    """
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits).astype(jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), ctx_len)
    scaled = logits / jnp.maximum(temp, 1e-6)
    order = jnp.argsort(-scaled)
    sl = scaled[order]
    probs = jax.nn.softmax(sl)
    cum = jnp.cumsum(probs)
    idx = jnp.arange(sl.shape[0])
    keep = ((cum - probs) < top_p) & jnp.where(top_k > 0, idx < top_k, True)
    keep = keep.at[0].set(True)
    choice = jax.random.categorical(key, jnp.where(keep, sl, -jnp.inf))
    sampled = order[choice].astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, sampled)


class TinyGptBackend(ModelBackend):
    """Decoder-only LM: INPUT_IDS [-1] -> streamed (TOKEN, INDEX) responses.

    ``max_tokens`` request parameter bounds generation (default 16); the
    stream terminates with an empty ``triton_final_response`` like every
    decoupled model here.
    """

    generative = True

    def __init__(self, name: str = "tiny_gpt", n_layers: int = 4,
                 d_model: int = 256, n_heads: int = 4, d_ff: int = 1024,
                 vocab: int = 512, max_seq_len: int = 128,
                 max_streams: int = 64, seed: int = 0,
                 attention_impl: str = "einsum",
                 attn_impl: str | None = None, kv_shards: int = 1):
        # "einsum": XLA-scheduled O(S^2) prefill scores — right for short
        # prompts.  "flash": the Pallas kernel (causal) for prefill and
        # the full-context forward — the long-context generation path
        # (`tiny_gpt_long`: max_seq 2048); decode waves are single-query
        # and always use the masked dense read over the KV arena.
        if attention_impl not in ("einsum", "flash"):
            # Silent fallback would serve the quadratic path at 2048+ —
            # the exact cliff the option exists to avoid.
            raise ValueError(
                f"attention_impl must be 'einsum' or 'flash', got "
                f"{attention_impl!r}")
        self.attention_impl = attention_impl
        # Flash tile caps (block_q, block_k): 512/1024 measured fastest at
        # s=2048 on v5e (bert.py's sweep); tests shrink them to drive the
        # multi-block grid at short sequence.
        self.flash_blocks = (512, 1024)
        # Decode-wave implementation: "reference" is the stacked-XLA path
        # above; "fused" runs the one-pass Pallas kernel
        # (ops/decode_kernel.py) — same math, same `_sample_token`
        # sequence, so streams are token-identical either way. The env
        # flips the fleet without touching model registration.
        if attn_impl is None:
            attn_impl = envcfg.env_str("CLIENT_TPU_ATTN_IMPL")
        if attn_impl not in ("reference", "fused"):
            raise ValueError(
                f"attn_impl must be 'reference' or 'fused', got "
                f"{attn_impl!r}")
        self.attn_impl = attn_impl
        # KV arena shards over a "kv" mesh axis (parallel/kv_shard.py);
        # 1 = single-chip arena (the +1-dummy-row layout). >1 requires the
        # fused decode path — the row-sharded layout and the shard_map'd
        # kernel go together.
        self.kv_shards = int(kv_shards)
        if self.kv_shards < 1:
            raise ValueError(f"kv_shards must be >= 1, got {kv_shards}")
        if self.kv_shards > 1:
            if self.attn_impl != "fused":
                raise ValueError(
                    "kv_shards > 1 requires attn_impl='fused' (the "
                    "sharded arena is served by the shard_map'd kernel)")
            if max_streams % self.kv_shards:
                raise ValueError(
                    f"max_streams ({max_streams}) must be divisible by "
                    f"kv_shards ({self.kv_shards})")
        # Fused-kernel knobs: key-block tile (None = auto divisor of
        # max_seq_len) and the cross-shard combine ("ring" remote-DMA
        # kernel | "psum" XLA collective).
        self.decode_block_s: int | None = None
        self.kv_combine = "ring"
        self._kv_mesh = None
        self.n_layers, self.d_model = n_layers, d_model
        self.n_heads, self.d_ff = n_heads, d_ff
        self.head_dim = d_model // n_heads
        self.vocab, self.max_seq_len = vocab, max_seq_len
        self.max_streams = max_streams
        self.default_max_tokens = 16
        self._seed = seed
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=0,
            input=[TensorConfig("INPUT_IDS", "INT32", [-1])],
            output=[
                TensorConfig("TOKEN", "INT32", [1]),
                TensorConfig("INDEX", "UINT32", [1]),
            ],
            decoupled=True,
        )

    # -- params --------------------------------------------------------------

    def _init_params(self):
        rng = np.random.default_rng(self._seed)
        d, f, v = self.d_model, self.d_ff, self.vocab

        def w(*shape, scale=None):
            scale = scale or 1.0 / math.sqrt(shape[0])
            return (rng.standard_normal(shape) * scale).astype(np.float32)

        layers = []
        for _ in range(self.n_layers):
            layers.append({
                "ln1g": np.ones(d, np.float32), "ln1b": np.zeros(d, np.float32),
                "wq": w(d, d), "wk": w(d, d), "wv": w(d, d), "wo": w(d, d),
                "ln2g": np.ones(d, np.float32), "ln2b": np.zeros(d, np.float32),
                "w1": w(d, f), "w2": w(f, d),
            })
        return {
            "embed": w(v, d, scale=0.02), "pos": w(self.max_seq_len, d, scale=0.02),
            "layers": layers,
            "lnfg": np.ones(d, np.float32), "lnfb": np.zeros(d, np.float32),
            "head": w(d, v),
        }

    def place_params(self, params):
        """Device placement hook; sharded variants override with
        per-tensor NamedShardings (parallel/serving.py)."""
        import jax

        return jax.device_put(params)

    def make_apply_params(self):
        """Full-context forward (no cache): logits for every position.
        Model-level entry for warmup/diagnostics; serving goes through
        prefill/decode below."""
        params = self.place_params(self.load_or_init_params(self._init_params))

        def apply(p, inputs):
            ids = inputs["INPUT_IDS"].astype("int32")
            x, _ = self._embed_positions(p, ids, 0)
            x = self._stack(p, x, causal=True)
            logits = _ln(x, p["lnfg"], p["lnfb"]) @ p["head"]
            return {"logits": logits}

        return apply, params

    # -- shared blocks --------------------------------------------------------

    def _ffn(self, lp, h):
        """Position-wise FFN on [T, d] rows; the MoE generative family
        (parallel/serving.py MoeGptBackend) overrides this with routed
        experts — attention, KV arena, and the prefill/decode programs are
        shared unchanged."""
        import jax

        return jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]

    def _embed_positions(self, p, ids, start):
        import jax.numpy as jnp

        n = ids.shape[0]
        pos = jnp.arange(n) + start
        return p["embed"][ids] + p["pos"][pos], pos

    def _stack(self, p, x, causal, on_kv=None):
        """Full-context transformer stack (no cache reads). ``on_kv(li, k,
        v)`` observes each layer's K/V at trace time — the prefill path
        uses it to populate the KV arena with the same math the plain
        forward runs."""
        import jax
        import jax.numpy as jnp

        n = x.shape[0]
        h_, d_ = self.n_heads, self.head_dim
        pos = jnp.arange(n)
        mask = pos[None, :] <= pos[:, None] if causal else None
        use_flash = self.attention_impl == "flash" and causal

        def attend(q, k, v):
            if use_flash:
                from client_tpu.ops.flash_attention import flash_attention

                def pick_block(s_len, cap):
                    best = None
                    for cand in range(8, min(cap, s_len) + 1, 8):
                        if s_len % cand == 0:
                            best = cand
                    return best if best is not None else s_len

                cap_q, cap_k = self.flash_blocks
                return flash_attention(
                    q[None], k[None], v[None], causal=True,
                    block_q=pick_block(n, cap_q),
                    block_k=pick_block(n, cap_k),
                    interpret=jax.default_backend() != "tpu")[0]
            s = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(d_)
            if mask is not None:
                s = jnp.where(mask[None], s, -1e30)
            return jnp.einsum("hqk,khd->qhd", jax.nn.softmax(s), v)

        for li, lp in enumerate(p["layers"]):
            h = _ln(x, lp["ln1g"], lp["ln1b"])
            q = (h @ lp["wq"]).reshape(n, h_, d_)
            k = (h @ lp["wk"]).reshape(n, h_, d_)
            v = (h @ lp["wv"]).reshape(n, h_, d_)
            if on_kv is not None:
                on_kv(li, k, v)
            o = attend(q, k, v)
            x = x + o.reshape(n, self.d_model) @ lp["wo"]
            h2 = _ln(x, lp["ln2g"], lp["ln2b"])
            x = x + self._ffn(lp, h2)
        return x

    # -- generative interface (used by GenerativeScheduler) -------------------

    def arena_rows(self, capacity: int | None = None):
        """(free_rows, dummy_row) of the arena this backend builds: which
        rows the scheduler may hand to streams, and the junk row padded
        lanes point at.  Single-chip: rows 0..cap-1 plus the trailing
        dummy; sharded: one junk row per shard (parallel/kv_shard.py), so
        the free list is non-contiguous and the scheduler must not assume
        ``row == lane`` arithmetic."""
        cap = self.max_streams if capacity is None else int(capacity)
        from client_tpu.parallel.kv_shard import arena_row_layout

        _total, free, dummy = arena_row_layout(cap, self.kv_shards)
        return free, dummy

    def _mesh(self):
        if self._kv_mesh is None:
            from client_tpu.parallel.kv_shard import kv_mesh

            self._kv_mesh = kv_mesh(self.kv_shards)
        return self._kv_mesh

    def init_arena(self, capacity: int):
        """KV arena pytree: k/v of shape [L, R, S, H, D] plus ``tok`` [R] —
        each row's latest token, kept ON DEVICE so decode waves chain
        without a host round trip per step (the scheduler pipelines waves
        and fetches emitted tokens asynchronously).  Unsharded, R is
        ``capacity + 1`` (the +1 dummy row absorbs padded decode lanes);
        with ``kv_shards > 1`` the rows carry a junk row per shard and the
        k/v leaves are placed row-sharded over the "kv" mesh
        (``NamedSharding``) — capacity beyond one chip's HBM."""
        import jax.numpy as jnp

        from client_tpu.parallel.kv_shard import (arena_row_layout,
                                                  shard_arena)

        total, _free, _dummy = arena_row_layout(capacity, self.kv_shards)
        shape = (self.n_layers, total, self.max_seq_len,
                 self.n_heads, self.head_dim)
        arena = {"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32),
                 "tok": jnp.zeros(total, jnp.int32)}
        if self.kv_shards > 1:
            arena = shard_arena(arena, self._mesh())
        return arena

    def prefill_fn(self):
        """(params, arena, rows[B], ids[B, S_pad], lens[B], seeds[B],
        temps[B], top_ks[B], top_ps[B]) -> (arena, first_tokens[B]).

        BATCHED prefill: writes each prompt's K/V into its arena row and
        samples the first token after each prompt's last real position —
        B admits cost ONE device round trip instead of B (round-2's
        per-admit prefill stalled every live decode stream for each admit).
        Causal masking makes the padded tail invisible to every valid
        query; padded LANES (rows pointing at the dummy row) are absorbed
        the same way decode waves absorb them.
        """
        import jax

        def prefill(p, arena, rows, ids, lens, seeds, temps, top_ks, top_ps,
                    sample=True):
            n = ids.shape[1]

            def one(ids_row):
                x, _pos = self._embed_positions(p, ids_row, 0)
                ks, vs = [], []
                x = self._stack(p, x, causal=True,
                                on_kv=lambda li, k, v:
                                (ks.append(k), vs.append(v)))
                import jax.numpy as jnp

                return x, jnp.stack(ks), jnp.stack(vs)  # [S,d],[L,S,H,D]x2

            xB, kB, vB = jax.vmap(one)(ids)              # [B,...]
            import jax.numpy as jnp

            b = rows.shape[0]
            xf = _ln(xB[jnp.arange(b), lens - 1], p["lnfg"], p["lnfb"])
            logits = xf @ p["head"]                      # [B, vocab]
            # `sample` is a STATIC arg: the all-greedy variant (the default
            # workload) compiles without the sort/cumsum/PRNG pipeline —
            # jnp.where alone would keep both branches in the executable.
            if sample:
                tokens = jax.vmap(_sample_token)(
                    logits, seeds, lens, temps, top_ks, top_ps)
            else:
                tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # Scatter whole prompt rows: [B,L,S,H,D] -> arena [L,rows,:n];
            # the first token lands in the device-side token slot so the
            # first decode wave can start without the host fetch.
            arena = {
                **arena,
                "k": arena["k"].at[:, rows, :n].set(
                    kB.transpose(1, 0, 2, 3, 4)),
                "v": arena["v"].at[:, rows, :n].set(
                    vB.transpose(1, 0, 2, 3, 4)),
                "tok": arena["tok"].at[rows].set(tokens),
            }
            return arena, tokens

        return prefill

    def decode_chunk_fn(self):
        """(params, arena, rows[B], lens[B], seeds[B], temps[B], top_ks[B],
        top_ps[B], sample, k) -> (arena, tokens[k, B]).

        K decode waves in ONE device execution via ``lax.scan`` over the
        single-wave body: each scanned step gathers its inputs from the
        arena token slots the previous step wrote, so the whole chunk
        chains on device.  One dispatch (and one transport command round)
        then advances every live stream K tokens — on a high-latency
        transport this divides the scheduler's dispatch-side overhead by
        K.  ``k`` is static (one executable per (wave bucket, K)); the
        per-step math is the decode_fn body unchanged, so sampling's
        fold_in(seed, ctx_len) sequence is identical to K separate waves.
        """
        import jax

        decode = self.decode_fn()

        def decode_chunk(p, arena, rows, lens, seeds, temps, top_ks,
                         top_ps, sample=True, k=2):
            def body(carry, _):
                arena_c, lens_c = carry
                arena_c, nxt = decode(p, arena_c, rows, lens_c, seeds,
                                      temps, top_ks, top_ps, sample)
                return (arena_c, lens_c + 1), nxt

            (arena, _), toks = jax.lax.scan(body, (arena, lens), None,
                                            length=k)
            return arena, toks  # [k, B]

        return decode_chunk

    def decode_fn(self):
        """(params, arena, rows[B], lens[B], seeds[B], temps[B],
        top_ks[B], top_ps[B]) -> (arena, next[B]).

        One batched decode step: each stream's input token is GATHERED from
        the arena's device-side token slots (written by prefill / the
        previous wave), so consecutive waves chain on device with no host
        round trip between them — the scheduler dispatches waves ahead and
        fetches emitted tokens asynchronously. Scatter each stream's new
        K/V at its current position, masked attention over the static
        max_seq_len axis, per-stream sampled (or greedy) next token.

        ``attn_impl="fused"`` swaps the per-layer scatter/gather/attend
        stack for the one-pass Pallas kernel (``_fused_decode_fn``); this
        body stays as the reference path and the parity oracle.
        """
        if self.attn_impl == "fused":
            return self._fused_decode_fn()
        import jax
        import jax.numpy as jnp

        h_, d_ = self.n_heads, self.head_dim

        def decode(p, arena, rows, lens, seeds, temps, top_ks,
                   top_ps, sample=True):
            b = rows.shape[0]
            tokens = arena["tok"][rows]                      # [B]
            x = p["embed"][tokens] + p["pos"][lens]          # [B, d]
            for li, lp in enumerate(p["layers"]):
                h = _ln(x, lp["ln1g"], lp["ln1b"])
                q = (h @ lp["wq"]).reshape(b, h_, d_)
                k = (h @ lp["wk"]).reshape(b, h_, d_)
                v = (h @ lp["wv"]).reshape(b, h_, d_)
                arena = {
                    **arena,
                    "k": arena["k"].at[li, rows, lens].set(k),
                    "v": arena["v"].at[li, rows, lens].set(v),
                }
                ck = arena["k"][li, rows]                    # [B, S, H, D]
                cv = arena["v"][li, rows]
                s = jnp.einsum("bhd,bshd->bhs", q, ck) / math.sqrt(d_)
                mask = jnp.arange(self.max_seq_len)[None, :] <= lens[:, None]
                s = jnp.where(mask[:, None, :], s, -1e30)
                o = jnp.einsum("bhs,bshd->bhd", jax.nn.softmax(s), cv)
                x = x + o.reshape(b, self.d_model) @ lp["wo"]
                h2 = _ln(x, lp["ln2g"], lp["ln2b"])
                x = x + self._ffn(lp, h2)
            xf = _ln(x, p["lnfg"], p["lnfb"])
            logits = xf @ p["head"]                          # [B, vocab]
            # ctx at sampling = lens + 1 (the token just written occupies
            # position lens) — continues the prefill fold sequence exactly.
            # `sample` static: all-greedy waves skip the sampling pipeline.
            if sample:
                nxt = jax.vmap(_sample_token)(
                    logits, seeds, lens + 1, temps, top_ks, top_ps)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            arena = dict(arena)
            arena["tok"] = arena["tok"].at[rows].set(nxt)
            return arena, nxt

        return decode

    def _fused_decode_fn(self):
        """The ``attn_impl="fused"`` decode step: same signature, same
        sampling sequence, but each layer's scatter + masked attention is
        ONE Pallas grid (ops/decode_kernel.py) — the arena row streams
        through VMEM once instead of materializing a [B, S, H, D] gather
        per layer.  With ``kv_shards > 1`` the per-layer call is the
        shard_map-wrapped variant over the row-sharded arena
        (parallel/kv_shard.py).  ``decode_chunk_fn`` scans this body
        unchanged, so chunked decode inherits the kernel for free."""
        import jax
        import jax.numpy as jnp

        h_, d_ = self.n_heads, self.head_dim
        interpret = jax.default_backend() != "tpu"
        block_s = self.decode_block_s

        if self.kv_shards > 1:
            from client_tpu.parallel.kv_shard import \
                sharded_decode_attention

            mesh, combine = self._mesh(), self.kv_combine

            def attend(k_a, v_a, q, k, v, rows, lens, layer):
                return sharded_decode_attention(
                    mesh, k_a, v_a, q, k, v, rows, lens, layer=layer,
                    block_s=block_s, interpret=interpret, combine=combine)
        else:
            from client_tpu.ops.decode_kernel import decode_wave_attention

            def attend(k_a, v_a, q, k, v, rows, lens, layer):
                return decode_wave_attention(
                    k_a, v_a, q, k, v, rows, lens, layer=layer,
                    block_s=block_s, interpret=interpret)

        def decode(p, arena, rows, lens, seeds, temps, top_ks,
                   top_ps, sample=True):
            b = rows.shape[0]
            tokens = arena["tok"][rows]                      # [B]
            x = p["embed"][tokens] + p["pos"][lens]          # [B, d]
            k_a, v_a = arena["k"], arena["v"]
            for li, lp in enumerate(p["layers"]):
                h = _ln(x, lp["ln1g"], lp["ln1b"])
                q = (h @ lp["wq"]).reshape(b, h_, d_)
                k = (h @ lp["wk"]).reshape(b, h_, d_)
                v = (h @ lp["wv"]).reshape(b, h_, d_)
                k_a, v_a, o = attend(k_a, v_a, q, k, v, rows, lens, li)
                x = x + o.reshape(b, self.d_model) @ lp["wo"]
                h2 = _ln(x, lp["ln2g"], lp["ln2b"])
                x = x + self._ffn(lp, h2)
            xf = _ln(x, p["lnfg"], p["lnfb"])
            logits = xf @ p["head"]                          # [B, vocab]
            # Same ctx/sample semantics as the reference body — sampling
            # is bit-identical across impls by construction.
            if sample:
                nxt = jax.vmap(_sample_token)(
                    logits, seeds, lens + 1, temps, top_ks, top_ps)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            arena = {**arena, "k": k_a, "v": v_a,
                     "tok": arena["tok"].at[rows].set(nxt)}
            return arena, nxt

        return decode


register_model("tiny_gpt")(TinyGptBackend)
# Long-context generation: seq 2048 with flash-attention prefill (the
# O(S^2) einsum scores would dominate prompt admission at this length);
# opt-in — a default load-all server shouldn't pay the 2048-wide arena.
register_model("tiny_gpt_long", default=False)(
    lambda: TinyGptBackend(name="tiny_gpt_long", max_seq_len=2048,
                           max_streams=16, attention_impl="flash"))
