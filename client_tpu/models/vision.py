"""Image-classification flagship models: ResNet-50 and DenseNet-121.

These are the serving-side counterparts of the models the reference's image
clients drive (/root/reference/src/c++/examples/image_client.cc:26-120
preprocesses for "resnet"-style models; BASELINE.md configs 3-4 name
`resnet50` and `densenet_onnx`). The reference repo carries no model code —
models live behind the server boundary — so these are TPU-first designs, not
translations:

- NHWC layout end to end (TPU conv layout; the MXU consumes HWIO kernels),
- bfloat16 weights and activations, float32 batch-norm statistics and final
  logits,
- inference-mode batch norm folded to a scale/bias affine (no running-stat
  bookkeeping inside the jitted step),
- one pure ``apply`` over a params pytree, jitted once per batch bucket by
  the engine (engine/model.py).

Weights are deterministic random (He-style fans) — the reference ships no
weights either (models/ has config.pbtxt only); benchmark realism comes from
architecture/FLOPs, not weight values.
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.config import (
    DynamicBatchingConfig,
    ModelConfig,
    TensorConfig,
)
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model


def _conv_init(key, kh, kw, cin, cout, dtype):
    import jax

    fan_in = kh * kw * cin
    std = np.sqrt(2.0 / fan_in)
    return (jax.random.normal(key, (kh, kw, cin, cout)) * std).astype(dtype)


def _dense_init(key, cin, cout, dtype):
    import jax

    std = np.sqrt(1.0 / cin)
    return (jax.random.normal(key, (cin, cout)) * std).astype(dtype)


def _conv(x, w, stride=1, padding="SAME", feature_group_count=1):
    import jax

    return jax.lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=feature_group_count,
    )


def _bn_params(key, c, dtype):
    """Inference-mode batch norm folded to affine: y = x*scale + bias."""
    import jax

    scale = 1.0 + 0.1 * jax.random.normal(key, (c,))
    return {"scale": scale.astype(dtype), "bias": np.zeros((c,), dtype)}


def _bn(x, p):
    return x * p["scale"] + p["bias"]


def _max_pool(x, window, stride, padding="SAME"):
    import jax

    return jax.lax.reduce_window(
        x, -np.inf, jax.lax.max,
        (1, window, window, 1), (1, stride, stride, 1), padding)


def _avg_pool_global(x):
    import jax.numpy as jnp

    return jnp.mean(x.astype(jnp.float32), axis=(1, 2))


# ---------------------------------------------------------------------------
# ResNet-50
# ---------------------------------------------------------------------------

_RESNET50_STAGES = ((3, 64), (4, 128), (6, 256), (3, 512))
_EXPANSION = 4


class ResNet50Backend(ModelBackend):
    """ResNet-50 classifier: FP32 NHWC [224,224,3] -> FP32 [1000] logits."""

    def __init__(self, name: str = "resnet50", num_classes: int = 1000,
                 image_size: int = 224, stages=_RESNET50_STAGES,
                 max_batch_size: int = 32):
        self._num_classes = num_classes
        self._stages = stages
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=max_batch_size,
            input=[TensorConfig("INPUT", "FP32", [image_size, image_size, 3])],
            output=[TensorConfig("OUTPUT", "FP32", [num_classes])],
            dynamic_batching=DynamicBatchingConfig(
                preferred_batch_size=[max(1, max_batch_size // 2),
                                      max_batch_size],
                max_queue_delay_microseconds=500,
            ),
            instance_count=2,
        )

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        dt = jnp.bfloat16
        key = jax.random.PRNGKey(50)

        def nk():
            nonlocal key
            key, sub = jax.random.split(key)
            return sub

        params = {
            "stem": {"w": _conv_init(nk(), 7, 7, 3, 64, dt),
                     "bn": _bn_params(nk(), 64, dt)},
            "stages": [],
        }
        cin = 64
        for n_blocks, width in self._stages:
            blocks = []
            for b in range(n_blocks):
                cout = width * _EXPANSION
                blk = {
                    "w1": _conv_init(nk(), 1, 1, cin, width, dt),
                    "bn1": _bn_params(nk(), width, dt),
                    "w2": _conv_init(nk(), 3, 3, width, width, dt),
                    "bn2": _bn_params(nk(), width, dt),
                    "w3": _conv_init(nk(), 1, 1, width, cout, dt),
                    "bn3": _bn_params(nk(), cout, dt),
                }
                if b == 0:
                    blk["wproj"] = _conv_init(nk(), 1, 1, cin, cout, dt)
                    blk["bnproj"] = _bn_params(nk(), cout, dt)
                blocks.append(blk)
                cin = cout
            params["stages"].append(blocks)
        params["fc"] = {
            "w": _dense_init(nk(), cin, self._num_classes, dt),
            "b": np.zeros((self._num_classes,), np.float32),
        }
        return params

    def make_apply_params(self):
        import jax

        def bottleneck(x, blk, stride):
            y = jax.nn.relu(_bn(_conv(x, blk["w1"]), blk["bn1"]))
            y = jax.nn.relu(_bn(_conv(y, blk["w2"], stride=stride), blk["bn2"]))
            y = _bn(_conv(y, blk["w3"]), blk["bn3"])
            if "wproj" in blk:
                x = _bn(_conv(x, blk["wproj"], stride=stride), blk["bnproj"])
            return jax.nn.relu(x + y)

        def apply(params, inputs):
            import jax
            import jax.numpy as jnp

            x = inputs["INPUT"].astype(jnp.bfloat16)
            x = jax.nn.relu(_bn(_conv(x, params["stem"]["w"], stride=2),
                                params["stem"]["bn"]))
            x = _max_pool(x, 3, 2)
            for si, blocks in enumerate(params["stages"]):
                for bi, blk in enumerate(blocks):
                    stride = 2 if (si > 0 and bi == 0) else 1
                    x = bottleneck(x, blk, stride)
            pooled = _avg_pool_global(x)  # fp32 [B, C]
            fc = params["fc"]
            logits = pooled @ fc["w"].astype(jnp.float32) + fc["b"]
            return {"OUTPUT": logits}

        return apply, jax.device_put(self.load_or_init_params(self._init_params))


# ---------------------------------------------------------------------------
# DenseNet-121
# ---------------------------------------------------------------------------

_DENSENET121_BLOCKS = (6, 12, 24, 16)


class DenseNet121Backend(ModelBackend):
    """DenseNet-121 classifier (`densenet_onnx` parity name lives in the
    registry): FP32 NHWC [224,224,3] -> FP32 [1000] logits."""

    def __init__(self, name: str = "densenet_onnx", num_classes: int = 1000,
                 image_size: int = 224, blocks=_DENSENET121_BLOCKS,
                 growth: int = 32, max_batch_size: int = 16):
        self._num_classes = num_classes
        self._blocks = blocks
        self._growth = growth
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=max_batch_size,
            input=[TensorConfig("INPUT", "FP32", [image_size, image_size, 3])],
            output=[TensorConfig("OUTPUT", "FP32", [num_classes])],
            dynamic_batching=DynamicBatchingConfig(
                preferred_batch_size=[max(1, max_batch_size // 2),
                                      max_batch_size],
                max_queue_delay_microseconds=500,
            ),
        )

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        dt = jnp.bfloat16
        g = self._growth
        key = jax.random.PRNGKey(121)

        def nk():
            nonlocal key
            key, sub = jax.random.split(key)
            return sub

        params = {
            "stem": {"w": _conv_init(nk(), 7, 7, 3, 2 * g, dt),
                     "bn": _bn_params(nk(), 2 * g, dt)},
            "blocks": [],
            "transitions": [],
        }
        c = 2 * g
        for i, n_layers in enumerate(self._blocks):
            layers = []
            for _ in range(n_layers):
                layers.append({
                    "bn1": _bn_params(nk(), c, dt),
                    "w1": _conv_init(nk(), 1, 1, c, 4 * g, dt),
                    "bn2": _bn_params(nk(), 4 * g, dt),
                    "w2": _conv_init(nk(), 3, 3, 4 * g, g, dt),
                })
                c += g
            params["blocks"].append(layers)
            if i < len(self._blocks) - 1:
                cout = c // 2
                params["transitions"].append({
                    "bn": _bn_params(nk(), c, dt),
                    "w": _conv_init(nk(), 1, 1, c, cout, dt),
                })
                c = cout
        params["final_bn"] = _bn_params(nk(), c, dt)
        params["fc"] = {
            "w": _dense_init(nk(), c, self._num_classes, dt),
            "b": np.zeros((self._num_classes,), np.float32),
        }
        return params

    def make_apply_params(self):
        import jax

        def dense_layer(x, lyr):
            y = _conv(jax.nn.relu(_bn(x, lyr["bn1"])), lyr["w1"])
            y = _conv(jax.nn.relu(_bn(y, lyr["bn2"])), lyr["w2"])
            return y

        def apply(params, inputs):
            import jax
            import jax.numpy as jnp

            x = inputs["INPUT"].astype(jnp.bfloat16)
            x = jax.nn.relu(_bn(_conv(x, params["stem"]["w"], stride=2),
                                params["stem"]["bn"]))
            x = _max_pool(x, 3, 2)
            for i, layers in enumerate(params["blocks"]):
                for lyr in layers:
                    y = dense_layer(x, lyr)
                    x = jnp.concatenate([x, y], axis=-1)
                if i < len(params["blocks"]) - 1:
                    tr = params["transitions"][i]
                    x = _conv(jax.nn.relu(_bn(x, tr["bn"])), tr["w"])
                    x = _avg_pool2(x)
            x = jax.nn.relu(_bn(x, params["final_bn"]))
            pooled = _avg_pool_global(x)
            fc = params["fc"]
            logits = pooled @ fc["w"].astype(jnp.float32) + fc["b"]
            return {"OUTPUT": logits}

        return apply, jax.device_put(self.load_or_init_params(self._init_params))


def _avg_pool2(x):
    import jax

    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    return summed * 0.25


register_model("resnet50")(ResNet50Backend)
register_model("densenet_onnx")(DenseNet121Backend)
