"""SSD-MobileNet-v2 COCO detector (`ssd_mobilenet_v2_coco_quantized`).

Wire-level parity with the reference's in-tree model config
(/root/reference/models/ssd_mobilenet_v2_coco_quantized/config.pbtxt:1-36):
UINT8 NHWC [300,300,3] input named ``normalized_input_image_tensor``; four
FP32 outputs named ``TFLite_Detection_PostProcess[:1|:2|:3]`` with dims
[1,10,4] boxes, [1,10] classes, [1,10] scores, [1] count; max_batch_size 1.

The implementation is TPU-first, not TFLite: the backbone is a MobileNetV2
inverted-residual stack (depthwise separable convs in bfloat16 on the MXU),
SSD box/class heads run over six feature-map scales, and the detection
postprocess (box decode + top-K NMS) runs **in-graph** with static shapes —
``lax.fori_loop`` greedy NMS over the top-scoring candidates instead of the
reference's CPU TFLite_Detection_PostProcess op. "quantized" parity: the
wire input stays UINT8 (dequantized on device); matmul precision is bf16.

A batched variant ``ssd_mobilenet_v2_tpu`` (max_batch_size 16, dynamic
batching) is also registered — that's the BASELINE.md north-star bench
target, where batch>1 keeps the MXU fed.
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.config import (
    DynamicBatchingConfig,
    ModelConfig,
    TensorConfig,
)
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model
from client_tpu.models.vision import _bn, _bn_params, _conv, _conv_init

NUM_CLASSES = 91          # COCO label map (91 ids incl. background gaps)
MAX_DETECTIONS = 10       # reference config output dims [1, 10, 4]
IOU_THRESHOLD = 0.5
SCORE_THRESHOLD = 0.05

# MobileNetV2 inverted-residual spec: (expansion, out_channels, n, stride)
_MBV2_SPEC = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)

# SSD feature-map sizes for a 300x300 input and anchors per cell.
_FEATURE_MAPS = ((19, 3), (10, 6), (5, 6), (3, 6), (2, 6), (1, 6))
_SCALES = (0.2, 0.35, 0.5, 0.65, 0.8, 0.95)


def _mbv2_blocks():
    """Flattened per-block structure of ``_MBV2_SPEC``:
    (cin, cout, expansion, stride, residual) — the single source both the
    weight init and the traced apply iterate, so the params list and the
    static stride/residual flags can't drift out of lockstep."""
    out = []
    cin = 32
    for expansion, cout, n, stride in _MBV2_SPEC:
        for i in range(n):
            out.append((cin, cout, expansion,
                        stride if i == 0 else 1,
                        (i > 0 or stride == 1) and cin == cout))
            cin = cout
    return out


def _make_anchors():
    """Static [N,4] anchor boxes (cy, cx, h, w) in normalized coords."""
    all_anchors = []
    for (fm, n_anchors), scale in zip(_FEATURE_MAPS, _SCALES):
        ratios = (1.0, 2.0, 0.5, 3.0, 1.0 / 3.0, 1.0)[:n_anchors]
        for y in range(fm):
            for x in range(fm):
                cy, cx = (y + 0.5) / fm, (x + 0.5) / fm
                for i, r in enumerate(ratios):
                    s = scale * (1.25 if (i == n_anchors - 1 and n_anchors > 1)
                                 else 1.0)
                    all_anchors.append(
                        [cy, cx, s / np.sqrt(r), s * np.sqrt(r)])
    return np.asarray(all_anchors, np.float32)


class SsdMobileNetV2Backend(ModelBackend):
    def __init__(self, name: str = "ssd_mobilenet_v2_coco_quantized",
                 max_batch_size: int = 1, image_size: int = 300):
        self._image_size = image_size
        batched = max_batch_size > 1
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=max_batch_size,
            input=[TensorConfig("normalized_input_image_tensor", "UINT8",
                                [image_size, image_size, 3])],
            output=[
                TensorConfig("TFLite_Detection_PostProcess", "FP32",
                             [1, MAX_DETECTIONS, 4]),
                TensorConfig("TFLite_Detection_PostProcess:1", "FP32",
                             [1, MAX_DETECTIONS]),
                TensorConfig("TFLite_Detection_PostProcess:2", "FP32",
                             [1, MAX_DETECTIONS]),
                TensorConfig("TFLite_Detection_PostProcess:3", "FP32", [1]),
            ],
            dynamic_batching=DynamicBatchingConfig(
                preferred_batch_size=[max_batch_size],
                max_queue_delay_microseconds=300,
            ) if batched else None,
            instance_count=2,
        )
        self._anchors = _make_anchors()

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        dt = jnp.bfloat16
        key = jax.random.PRNGKey(300)

        def nk():
            nonlocal key
            key, sub = jax.random.split(key)
            return sub

        params = {"stem": {"w": _conv_init(nk(), 3, 3, 3, 32, dt),
                           "bn": _bn_params(nk(), 32, dt)},
                  "blocks": [], "heads": [], "extras": []}
        cin = 32
        for cin, cout, expansion, _stride, _residual in _mbv2_blocks():
            mid = cin * expansion
            blk = {
                "bn1": _bn_params(nk(), mid, dt),
                "wd": _conv_init(nk(), 3, 3, 1, mid, dt),  # depthwise HWI(1)O
                "bn2": _bn_params(nk(), mid, dt),
                "wp": _conv_init(nk(), 1, 1, mid, cout, dt),
                "bn3": _bn_params(nk(), cout, dt),
            }
            if expansion != 1:
                blk["we"] = _conv_init(nk(), 1, 1, cin, mid, dt)
            params["blocks"].append(blk)
        cin = _mbv2_blocks()[-1][1]
        # extra feature layers down to 1x1 (channels cin -> 256 each)
        for _ in range(len(_FEATURE_MAPS) - 2):
            params["extras"].append({
                "w1": _conv_init(nk(), 1, 1, cin, 128, dt),
                "bn1": _bn_params(nk(), 128, dt),
                "w2": _conv_init(nk(), 3, 3, 128, 256, dt),
                "bn2": _bn_params(nk(), 256, dt),
            })
            cin = 256
        # heads: one box + one class conv per feature map
        head_cins = [576, 320] + [256] * (len(_FEATURE_MAPS) - 2)
        for (fm, n_anchors), hc in zip(_FEATURE_MAPS, head_cins):
            params["heads"].append({
                "box": _conv_init(nk(), 3, 3, hc, n_anchors * 4, dt),
                "cls": _conv_init(nk(), 3, 3, hc, n_anchors * NUM_CLASSES, dt),
            })
        return params

    def make_apply_params(self):
        import jax

        anchors = self._anchors
        n_anchors_total = anchors.shape[0]
        # Per-block static structure (conv strides, residual flags) stays
        # host-side: it parameterizes the traced program and must not ride in
        # the params argument, where leaves become traced arrays.
        statics = [(stride, residual)
                   for _cin, _cout, _exp, stride, residual in _mbv2_blocks()]

        def backbone_feats(params, x):
            feats = []
            y = jax.nn.relu6(_bn(_conv(x, params["stem"]["w"], stride=2),
                                 params["stem"]["bn"]))
            for bi, (blk, (stride, residual)) in enumerate(
                    zip(params["blocks"], statics)):
                inp = y
                if "we" in blk:
                    expanded = jax.nn.relu6(
                        _bn(_conv(y, blk["we"]), blk["bn1"]))
                else:
                    expanded = y
                mid = expanded.shape[-1]
                y = jax.nn.relu6(_bn(
                    _conv(expanded, blk["wd"], stride=stride,
                          feature_group_count=mid), blk["bn2"]))
                y = _bn(_conv(y, blk["wp"]), blk["bn3"])
                if residual:
                    y = y + inp
                if bi == 13 and "we" in blk:
                    # 19x19 tap: expansion conv of the first 160-stage block
                    feats.append(expanded)
            feats.append(y)  # 10x10, 320 channels
            for ex in params["extras"]:
                y = jax.nn.relu6(_bn(_conv(y, ex["w1"]), ex["bn1"]))
                y = jax.nn.relu6(_bn(_conv(y, ex["w2"], stride=2),
                                     ex["bn2"]))
                feats.append(y)
            return feats

        def decode_and_nms(boxes_enc, scores_all):
            """boxes_enc [N,4] fp32, scores_all [N,C] fp32 -> top-10 dets."""
            import jax.numpy as jnp

            cy = anchors[:, 0] + 0.1 * boxes_enc[:, 0] * anchors[:, 2]
            cx = anchors[:, 1] + 0.1 * boxes_enc[:, 1] * anchors[:, 3]
            h = anchors[:, 2] * jnp.exp(0.2 * boxes_enc[:, 2])
            w = anchors[:, 3] * jnp.exp(0.2 * boxes_enc[:, 3])
            ymin, xmin = cy - h / 2, cx - w / 2
            ymax, xmax = cy + h / 2, cx + w / 2
            boxes = jnp.stack([ymin, xmin, ymax, xmax], axis=1)

            cls_scores = scores_all[:, 1:]  # drop background column 0
            best_cls = jnp.argmax(cls_scores, axis=1).astype(jnp.float32)
            best_score = jnp.max(cls_scores, axis=1)
            best_score = jnp.where(best_score >= SCORE_THRESHOLD,
                                   best_score, 0.0)

            area = jnp.maximum(ymax - ymin, 0) * jnp.maximum(xmax - xmin, 0)

            def iou_with(box):
                iy1 = jnp.maximum(boxes[:, 0], box[0])
                ix1 = jnp.maximum(boxes[:, 1], box[1])
                iy2 = jnp.minimum(boxes[:, 2], box[2])
                ix2 = jnp.minimum(boxes[:, 3], box[3])
                inter = jnp.maximum(iy2 - iy1, 0) * jnp.maximum(ix2 - ix1, 0)
                box_area = jnp.maximum(box[2] - box[0], 0) * \
                    jnp.maximum(box[3] - box[1], 0)
                return inter / jnp.maximum(area + box_area - inter, 1e-9)

            def body(i, state):
                scores, out_boxes, out_cls, out_scores = state
                j = jnp.argmax(scores)
                s = scores[j]
                box = boxes[j]
                keep = s > 0.0
                out_boxes = out_boxes.at[i].set(jnp.where(keep, box, 0.0))
                out_cls = out_cls.at[i].set(jnp.where(keep, best_cls[j], 0.0))
                out_scores = out_scores.at[i].set(jnp.where(keep, s, 0.0))
                # suppress overlapping candidates (greedy class-agnostic NMS)
                suppress = iou_with(box) > IOU_THRESHOLD
                scores = jnp.where(suppress & keep, 0.0, scores)
                scores = scores.at[j].set(0.0)
                return scores, out_boxes, out_cls, out_scores

            init = (best_score,
                    jnp.zeros((MAX_DETECTIONS, 4), jnp.float32),
                    jnp.zeros((MAX_DETECTIONS,), jnp.float32),
                    jnp.zeros((MAX_DETECTIONS,), jnp.float32))
            _, out_boxes, out_cls, out_scores = jax.lax.fori_loop(
                0, MAX_DETECTIONS, body, init)
            count = jnp.sum((out_scores > 0).astype(jnp.float32))
            return out_boxes, out_cls, out_scores, count

        def apply(params, inputs):
            import jax.numpy as jnp

            # Engine always supplies the batch dim when max_batch_size > 0
            # (model.py validate_inputs); per-sample output dims are
            # [1,10,4] / [1,10] / [1] per the reference config, so a leading
            # singleton is inserted per sample below.
            img = inputs["normalized_input_image_tensor"]
            x = (img.astype(jnp.bfloat16) - 127.5) / 127.5
            feats = backbone_feats(params, x)

            b = x.shape[0]
            box_parts, cls_parts = [], []
            for feat, head in zip(feats, params["heads"]):
                raw_box = _conv(feat, head["box"]).astype(jnp.float32)
                raw_cls = _conv(feat, head["cls"]).astype(jnp.float32)
                box_parts.append(raw_box.reshape(b, -1, 4))
                cls_parts.append(raw_cls.reshape(b, -1, NUM_CLASSES))
            boxes_enc = jnp.concatenate(box_parts, axis=1)
            scores_all = jax.nn.sigmoid(jnp.concatenate(cls_parts, axis=1))
            assert boxes_enc.shape[1] == n_anchors_total, \
                (boxes_enc.shape, n_anchors_total)

            out_b, out_c, out_s, count = jax.vmap(decode_and_nms)(
                boxes_enc, scores_all)

            return {
                "TFLite_Detection_PostProcess": out_b[:, None],
                "TFLite_Detection_PostProcess:1": out_c[:, None],
                "TFLite_Detection_PostProcess:2": out_s[:, None],
                "TFLite_Detection_PostProcess:3": count[:, None],
            }

        return apply, jax.device_put(self.load_or_init_params(self._init_params))


class SsdMobileNetV2TpuBackend(SsdMobileNetV2Backend):
    """Batched TPU-throughput variant — BASELINE.md north-star bench model."""

    def __init__(self):
        super().__init__(name="ssd_mobilenet_v2_tpu", max_batch_size=16)


register_model("ssd_mobilenet_v2_coco_quantized")(SsdMobileNetV2Backend)
register_model("ssd_mobilenet_v2_tpu")(SsdMobileNetV2TpuBackend)
