"""DLRM embedding-bag model family: ragged CSR lookups + MLPs.

The recommendation-serving workload class ("Dissecting Embedding Bag
Performance in DLRM Inference", PAPERS.md): per request, each of
``num_tables`` sparse features contributes a variable-length *bag* of
embedding-row ids; the model pools each bag (sum), crosses the pooled
vectors with a densified bottom-MLP feature via pairwise dot products,
and scores through a top MLP.  Cost scales with total lookups (nnz), not
batch rows — which is why this backend declares
``padding_axis="lookups"`` and is scheduled by the
:class:`~client_tpu.engine.ragged.RaggedScheduler`.

Wire format (KServe v2 tensors, both frontends):

- ``DENSE``   FP32 ``[dense_dim]`` — batched to ``[B, dense_dim]``;
- ``INDICES`` INT32 ragged ``[total_nnz]`` — all bags' row ids,
  concatenated row-major over ``[B, num_tables]`` bags;
- ``OFFSETS`` INT32 ragged ``[B * num_tables + 1]`` — CSR bag starts
  into ``INDICES`` (``OFFSETS[0] == 0``, last element ``== total_nnz``);
- ``OUTPUT0`` FP32 ``[B, 1]`` — the score.

Execution layout: ``pre_stage`` turns CSR into the static device shapes
(indices padded to the lookup bucket with sentinel segment ids, rows
padded to ``max_batch_size`` so lookups stay the only variable axis).
Tables live stacked (``[num_tables * table_rows, emb_dim]``) in one of
three modes:

- **device** (default): table is a jit param on one device;
- **sharded** (``emb_shards=N``): rows sharded over the ``"emb"`` mesh,
  lookups via :func:`~client_tpu.parallel.emb_shard.sharded_bag_sum`
  (bit-identical to the oracle — table values are 1/256-quantized);
- **host** (``host_tables=True``): table stays host-resident and
  ``pre_stage`` resolves lookups through the arena-budgeted
  :class:`~client_tpu.engine.rowcache.RowCache`; the device only pools
  pre-gathered vectors.
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.config import (
    DynamicBatchingConfig,
    ModelConfig,
    TensorConfig,
)
from client_tpu.engine.model import ModelBackend
from client_tpu.engine.types import EngineError
from client_tpu.models import register_model


def _init_mlp(rng, units: list[int]):
    """[(w, b)] per layer, modest scale; fp32."""
    out = []
    for d_in, d_out in zip(units, units[1:]):
        w = (rng.standard_normal((d_in, d_out)) / np.sqrt(d_in)).astype(
            np.float32)
        b = np.zeros((d_out,), np.float32)
        out.append((w, b))
    return out


class DlrmBackend(ModelBackend):
    """Sharded EmbeddingBag DLRM (see module docstring)."""

    indices_name = "INDICES"
    offsets_name = "OFFSETS"

    def __init__(self, name: str = "dlrm", num_tables: int = 4,
                 table_rows: int = 64, emb_dim: int = 8, dense_dim: int = 8,
                 max_batch_size: int = 8, max_lookups: int = 128,
                 lookup_buckets: list[int] | None = None,
                 emb_shards: int = 0, combine: str = "psum",
                 host_tables: bool = False, cache_budget_bytes: int = 0,
                 bottom_units: tuple = (16,), top_units: tuple = (16,),
                 seed: int = 0, max_queue_delay_us: int = 200):
        self.num_tables = int(num_tables)
        self.table_rows = int(table_rows)
        self.emb_dim = int(emb_dim)
        self.dense_dim = int(dense_dim)
        self.emb_shards = int(emb_shards)
        self.combine = combine
        self.host_tables = bool(host_tables)
        self.cache_budget_bytes = int(cache_budget_bytes)
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=int(max_batch_size),
            padding_axis="lookups",
            max_lookups=int(max_lookups),
            batch_buckets=(sorted({int(b) for b in lookup_buckets})
                           if lookup_buckets else None),
            input=[
                TensorConfig("DENSE", "FP32", [self.dense_dim]),
                TensorConfig("INDICES", "INT32", [-1], ragged=True),
                TensorConfig("OFFSETS", "INT32", [-1], ragged=True),
            ],
            output=[TensorConfig("OUTPUT0", "FP32", [1])],
            dynamic_batching=DynamicBatchingConfig(
                max_queue_delay_microseconds=int(max_queue_delay_us)),
            instance_count=1,
        )
        rng = np.random.default_rng(seed)
        # 1/256-quantized values sum exactly in fp32 regardless of
        # accumulation order (emb_shard.quantize_table): sharded-vs-oracle
        # parity is bit-identical, and a reload reproduces the same table.
        from client_tpu.parallel.emb_shard import quantize_table

        stacked_rows = self.num_tables * self.table_rows
        if self.emb_shards > 1 and stacked_rows % self.emb_shards:
            # Pad with zero rows (never indexed) to an even row partition.
            stacked_rows += self.emb_shards - stacked_rows % self.emb_shards
        table = np.zeros((stacked_rows, self.emb_dim), np.float32)
        table[: self.num_tables * self.table_rows] = quantize_table(
            rng.standard_normal(
                (self.num_tables * self.table_rows, self.emb_dim)) * 0.5)
        self.table_host = table
        self._bottom = _init_mlp(
            rng, [self.dense_dim, *bottom_units, self.emb_dim])
        n_pairs = (self.num_tables + 1) * self.num_tables // 2
        self._top = _init_mlp(
            rng, [self.emb_dim + n_pairs, *top_units, 1])
        self.row_cache = None
        if self.host_tables:
            from client_tpu.engine.rowcache import RowCache

            self.row_cache = RowCache(self.table_host,
                                      self.cache_budget_bytes)
        self.mesh = None
        if self.emb_shards > 1 and not self.host_tables:
            from client_tpu.parallel.emb_shard import emb_mesh

            self.mesh = emb_mesh(self.emb_shards)

    # -- capacity planning ----------------------------------------------------

    def hbm_reservation_bytes(self) -> int:
        """Per-model memory the placement layer should charge: device-
        resident table bytes (the dominant cost), or the host-mode cache
        budget (staged vectors transit HBM per batch; the cache bound is
        the honest steady-state figure)."""
        if self.host_tables:
            return self.cache_budget_bytes
        return int(self.table_host.nbytes)

    # -- ragged validation (engine.validate_inputs hook) ----------------------

    def validate_ragged(self, inputs: dict, batch: int) -> None:
        cfg = self.config
        idx = inputs.get("INDICES")
        off = inputs.get("OFFSETS")
        if idx is None or off is None:
            return  # missing-input errors are raised by the generic loop
        idx = np.asarray(idx)
        off = np.asarray(off)
        want = batch * self.num_tables + 1
        if off.shape[0] != want:
            raise EngineError(
                f"OFFSETS length {off.shape[0]} != batch({batch}) * "
                f"num_tables({self.num_tables}) + 1 = {want}", 400)
        if off.shape[0] and off[0] != 0:
            raise EngineError("OFFSETS[0] must be 0", 400)
        if np.any(np.diff(off) < 0):
            raise EngineError("OFFSETS must be non-decreasing", 400)
        if off[-1] != idx.shape[0]:
            raise EngineError(
                f"OFFSETS[-1] ({int(off[-1])}) != len(INDICES) "
                f"({idx.shape[0]})", 400)
        if idx.shape[0] > cfg.max_lookups:
            # A single request past the largest lookup bucket cannot be
            # split (the feature interaction couples its bags): reject it
            # like an over-max_batch_size batch.
            raise EngineError(
                f"request carries {idx.shape[0]} lookups, exceeding "
                f"max_lookups {cfg.max_lookups} for '{cfg.name}'", 400)
        if idx.size and (idx.min() < 0 or idx.max() >= self.table_rows):
            raise EngineError(
                f"INDICES out of range [0, {self.table_rows})", 400)

    # -- staging (Model.execute_timed hook) -----------------------------------

    def pre_stage(self, inputs: dict, pad_to: int | None) -> dict:
        """CSR → static device layout.  All padding happens HERE (the
        generic row-pad in ``execute_timed`` is bypassed): lookups pad to
        the bucket with row 0 + sentinel segment id ``Bmax*T`` (masked in
        ``apply``), rows pad to ``max_batch_size`` so the executable sees
        exactly one shape per lookup bucket."""
        dense = np.asarray(inputs["DENSE"], np.float32)
        idx = np.asarray(inputs["INDICES"], np.int64)
        off = np.asarray(inputs["OFFSETS"], np.int64)
        b_max = self.config.max_batch_size
        t = self.num_tables
        nnz = int(idx.shape[0])
        lookups = int(pad_to) if pad_to else nnz
        # Per-lookup bag id (b*T + t, row-major) from the CSR offsets.
        seg = np.repeat(
            np.arange(off.shape[0] - 1, dtype=np.int32),
            np.diff(off).astype(np.int64))
        # Stacked-table global row: each bag's table is its bag id mod T.
        rows = (idx + (seg % t).astype(np.int64)
                * self.table_rows).astype(np.int32)
        if lookups > nnz:
            rows = np.concatenate(
                [rows, np.zeros(lookups - nnz, np.int32)])
            seg = np.concatenate(
                [seg, np.full(lookups - nnz, b_max * t, np.int32)])
        if dense.shape[0] < b_max:
            dense = np.pad(
                dense, [(0, b_max - dense.shape[0]), (0, 0)])
        if self.row_cache is not None:
            # Only the real lookups go through the cache — padding would
            # count row 0 as a hot row and inflate the hit rate. Padded
            # vector slots are zero (masked in apply regardless).
            vectors, _hits = self.row_cache.lookup_counted(rows[:nnz])
            if lookups > nnz:
                vectors = np.concatenate([vectors, np.zeros(
                    (lookups - nnz, self.emb_dim), vectors.dtype)])
            return {"DENSE": dense, "VECTORS": vectors, "SEG_IDS": seg}
        return {"DENSE": dense, "INDICES": rows, "SEG_IDS": seg}

    def synthetic_inputs(self, lookups: int) -> dict:
        """A zero CSR batch with exactly ``lookups`` nnz (one row, bags
        evenly split) — warmup / autotuner bucket compiles."""
        lookups = max(1, int(lookups))
        t = self.num_tables
        counts = np.full(t, lookups // t, np.int64)
        counts[: lookups % t] += 1
        off = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(counts)]).astype(np.int32)
        return {
            "DENSE": np.zeros((1, self.dense_dim), np.float32),
            "INDICES": np.zeros(lookups, np.int32),
            "OFFSETS": off,
        }

    # -- execution ------------------------------------------------------------

    def make_apply_params(self):
        import jax
        import jax.numpy as jnp

        from client_tpu.parallel.emb_shard import (
            bag_sum_oracle,
            shard_table,
            sharded_bag_sum,
        )

        b_max = self.config.max_batch_size
        t = self.num_tables
        d = self.emb_dim
        num_seg = b_max * t
        iu, ju = np.triu_indices(t + 1, k=1)
        host_mode = self.row_cache is not None
        mesh = self.mesh
        combine = self.combine
        # The Pallas ring combine needs interpret mode off-TPU (the psum
        # combine is a plain XLA collective and runs anywhere).
        interpret = jax.default_backend() != "tpu"

        params = {
            "bottom": [(jax.device_put(w), jax.device_put(b))
                       for w, b in self._bottom],
            "top": [(jax.device_put(w), jax.device_put(b))
                    for w, b in self._top],
        }
        if not host_mode:
            params["table"] = (shard_table(self.table_host, mesh)
                               if mesh is not None
                               else jax.device_put(self.table_host))
            from client_tpu.observability.memory import hbm_census

            hbm_census().tag(self.config.name, "embedding",
                             params["table"])

        def mlp(layers, x):
            for i, (w, b) in enumerate(layers):
                x = x @ w + b
                if i < len(layers) - 1:
                    x = jax.nn.relu(x)
            return x

        def apply(p, inputs):
            seg = inputs["SEG_IDS"]
            if host_mode:
                vecs = inputs["VECTORS"]
                valid = seg < num_seg
                vecs = jnp.where(valid[:, None], vecs, 0.0).astype(
                    vecs.dtype)
                pooled = jax.ops.segment_sum(
                    vecs, jnp.where(valid, seg, 0), num_segments=num_seg)
            elif mesh is not None:
                pooled = sharded_bag_sum(
                    mesh, p["table"], inputs["INDICES"], seg, num_seg,
                    combine=combine, interpret=interpret)
            else:
                pooled = bag_sum_oracle(
                    p["table"], inputs["INDICES"], seg, num_seg)
            pooled = pooled.reshape(b_max, t, d)
            bottom = mlp(p["bottom"], inputs["DENSE"])  # [Bmax, D]
            feats = jnp.concatenate([bottom[:, None, :], pooled], axis=1)
            z = jnp.einsum("bid,bjd->bij", feats, feats)
            inter = z[:, iu, ju]  # upper-triangular pairwise dots
            out = mlp(p["top"], jnp.concatenate([bottom, inter], axis=-1))
            return {"OUTPUT0": out}

        return apply, params


register_model("dlrm")(DlrmBackend)
# Host-table + hot-row-cache variant: the default registered config keeps
# a cache big enough for the hot set of a Zipf workload but far smaller
# than the table, so hit-rate metrics are non-trivial out of the box.
register_model("dlrm_cached", default=False)(
    lambda: DlrmBackend(name="dlrm_cached", host_tables=True,
                        cache_budget_bytes=4096))
