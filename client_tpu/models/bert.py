"""BERT-base flagship model (`bert_base`).

Serving-side counterpart of BASELINE.md config 5 (ensemble
preprocess→BERT-base→postprocess); the reference carries no model code, so
this is a TPU-first encoder design:

- bfloat16 parameters and matmuls (MXU-friendly [B,S,H] einsums), float32
  layer-norm statistics and softmax accumulation,
- fixed sequence length per config (XLA static shapes; long-context variants
  shard the sequence axis over the mesh — see client_tpu.parallel),
- one pure ``apply`` over a params pytree; the engine jits per batch bucket.

Inputs follow the common BERT serving convention: ``input_ids`` INT32[S],
``attention_mask`` INT32[S]. Outputs: ``pooled_output`` FP32[hidden] (tanh
pooler over [CLS]) and ``logits`` FP32[num_labels] for the ensemble's
classification postprocess.
"""

from __future__ import annotations

import numpy as np

from client_tpu.engine.config import (
    DynamicBatchingConfig,
    ModelConfig,
    TensorConfig,
)
from client_tpu.engine.model import ModelBackend
from client_tpu.models import register_model

VOCAB_SIZE = 30522  # BERT wordpiece vocabulary size


class BertBackend(ModelBackend):
    """BERT-base encoder: 12 layers, hidden 768, 12 heads, FFN 3072."""

    def __init__(self, name: str = "bert_base", seq_len: int = 128,
                 hidden: int = 768, n_layers: int = 12, n_heads: int = 12,
                 ffn: int = 3072, num_labels: int = 2,
                 vocab: int = VOCAB_SIZE, max_batch_size: int = 16,
                 attention_impl: str = "einsum",
                 weights_path: str | None = None):
        # "einsum": XLA-scheduled O(S^2) scores — right up to ~512 tokens.
        # "flash": the Pallas kernel (client_tpu.ops.flash_attention) —
        # O(block) score memory, the long-context single-chip path.
        self.attention_impl = attention_impl
        self.weights_path = weights_path
        self.seq_len = seq_len
        self.hidden = hidden
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ffn = ffn
        self.num_labels = num_labels
        self.vocab = vocab
        self.config = ModelConfig(
            name=name,
            platform="jax",
            max_batch_size=max_batch_size,
            input=[
                TensorConfig("input_ids", "INT32", [seq_len]),
                TensorConfig("attention_mask", "INT32", [seq_len]),
            ],
            output=[
                TensorConfig("pooled_output", "FP32", [hidden]),
                TensorConfig("logits", "FP32", [num_labels]),
            ],
            dynamic_batching=DynamicBatchingConfig(
                preferred_batch_size=[max(1, max_batch_size // 2),
                                      max_batch_size],
                max_queue_delay_microseconds=500,
            ),
            instance_count=2,
        )

    def _init_params(self):
        import jax
        import jax.numpy as jnp

        dt = jnp.bfloat16
        h, f = self.hidden, self.ffn
        key = jax.random.PRNGKey(768)

        def nk():
            nonlocal key
            key, sub = jax.random.split(key)
            return sub

        def dense(cin, cout):
            std = np.sqrt(1.0 / cin)
            return {
                "w": (jax.random.normal(nk(), (cin, cout)) * std).astype(dt),
                "b": np.zeros((cout,), dt),
            }

        def ln(c):
            return {"scale": np.ones((c,), np.float32),
                    "bias": np.zeros((c,), np.float32)}

        params = {
            "tok_embed": (jax.random.normal(nk(), (self.vocab, h)) * 0.02
                          ).astype(dt),
            "pos_embed": (jax.random.normal(nk(), (self.seq_len, h)) * 0.02
                          ).astype(dt),
            "embed_ln": ln(h),
            "layers": [],
            "pooler": dense(h, h),
            "classifier": dense(h, self.num_labels),
        }
        for _ in range(self.n_layers):
            params["layers"].append({
                # Q/K/V projections fused into one [h, 3h] matmul: larger
                # MXU tiles, one dispatch — measured ~6% faster per layer
                # than three separate [h, h] projections on v5e.
                "wqkv": dense(h, 3 * h),
                "wo": dense(h, h),
                "ln1": ln(h),
                "w1": dense(h, f), "w2": dense(f, h),
                "ln2": ln(h),
            })
        return params

    def place_params(self, params):
        """Device placement for the weights (sharded in subclasses)."""
        import jax

        return jax.device_put(params)

    def make_attend(self, head_dim):
        """Attention primitive: [B,S,H,D] q/k/v + [B,S] additive key bias
        → [B,S,H,D]. Overridden by the parallel serving backends (ring
        attention over a sequence-sharded mesh)."""
        attention_impl = self.attention_impl

        def attend(q, k, v, bias2d):
            import jax
            import jax.numpy as jnp

            if attention_impl == "flash":
                from client_tpu.ops.flash_attention import flash_attention

                # Bigger tiles amortize the per-grid-step overhead at long
                # sequence (512/1024 measured fastest at s=2048 on v5e);
                # clamp to divisors of the actual sequence length so any
                # seq_len works. interpret=True off-TPU keeps the hermetic
                # CPU suite on the same kernel code path the chip compiles.
                def pick_block(s_len, cap):
                    # Largest divisor of s_len that is <= cap AND a legal
                    # TPU tile height (multiple of 8); fall back to the
                    # full sequence (always legal) when none exists.
                    best = None
                    for cand in range(8, min(cap, s_len) + 1, 8):
                        if s_len % cand == 0:
                            best = cand
                    return best if best is not None else s_len

                s_len = q.shape[1]
                return flash_attention(
                    q, k, v, bias2d,
                    block_q=pick_block(s_len, 512),
                    block_k=pick_block(s_len, 1024),
                    interpret=jax.default_backend() != "tpu")
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
            scores = (scores / np.sqrt(head_dim)
                      + bias2d[:, None, None, :].astype(jnp.float32))
            probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

        return attend

    def make_apply_params(self):
        return (self._build_apply(),
                self.place_params(self.load_or_init_params(self._init_params)))

    def _build_apply(self, constrain=None, head_major=False):
        """Build the pure ``apply(params, inputs)`` over a params pytree.

        Params are a jit *argument* (engine passes the placed tree each call),
        not closure constants — see ModelBackend.make_apply_params for why.
        ``constrain(x, spec)`` inserts sharding constraints at activation
        boundaries for multi-chip serving (ShardedBertBackend); None means
        single-device and the hooks are no-ops.
        """
        n_heads = self.n_heads
        head_dim = self.hidden // n_heads
        # Fused-QKV output layout, chosen by execution mode:
        # - default: qkv-major (b, s, 3, heads, hd) — leading-axis
        #   slices are contiguous, measured 1.24 ms vs 1.51 ms per b8 step
        #   on v5e for the head-major variant;
        # - head_major (tensor-parallel backends): (b, s, heads, 3, hd) so a
        #   tp column split of wqkv lands whole heads per shard
        #   and the heads-axis constraint matches the matmul's natural
        #   output sharding (no per-layer reshard collective).
        # Weights are random here; a pretrained-checkpoint loader must
        # interleave wq/wk/wv to match the layout in use. head_major is
        # requested only by tp-sharding backends, which permute the
        # canonical weights at placement (ShardedBertBackend.place_params).
        if constrain is None:
            def constrain(x, spec):  # noqa: ARG001 — single-device no-op
                return x

        def layer_norm(x, p):
            import jax
            import jax.numpy as jnp

            x32 = x.astype(jnp.float32)
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.var(x32, axis=-1, keepdims=True)
            y = (x32 - mu) * jax.lax.rsqrt(var + 1e-12)
            return (y * p["scale"] + p["bias"]).astype(jnp.bfloat16)

        def proj(x, p):
            return x @ p["w"] + p["b"]

        attend = self.make_attend(head_dim)

        def attention(x, bias2d, lp):
            b, s, h = x.shape
            if head_major:
                qkv = proj(x, lp["wqkv"]).reshape(b, s, n_heads, 3, head_dim)
                qkv = constrain(qkv, ("dp", None, "tp", None, None))
                q = qkv[:, :, :, 0]
                k = qkv[:, :, :, 1]
                v = qkv[:, :, :, 2]
            else:
                qkv = proj(x, lp["wqkv"]).reshape(b, s, 3, n_heads, head_dim)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            ctx = attend(q, k, v, bias2d).reshape(b, s, h)
            return proj(ctx, lp["wo"])

        def apply(params, inputs):
            import jax
            import jax.numpy as jnp

            ids = inputs["input_ids"]
            mask = inputs["attention_mask"].astype(jnp.float32)
            # additive attention bias: 0 where attended, -1e9 where masked
            bias2d = (mask - 1.0) * 1e9

            x = params["tok_embed"][ids] + params["pos_embed"][None, :, :]
            x = layer_norm(x, params["embed_ln"])
            x = constrain(x, ("dp", None, None))
            for lp in params["layers"]:
                x = layer_norm(x + attention(x, bias2d, lp), lp["ln1"])
                x = constrain(x, ("dp", None, None))
                y = jax.nn.gelu(proj(x, lp["w1"]))
                y = constrain(y, ("dp", None, "tp"))
                x = layer_norm(x + proj(y, lp["w2"]), lp["ln2"])
                x = constrain(x, ("dp", None, None))

            cls = x[:, 0, :].astype(jnp.float32)
            pooler = params["pooler"]
            pooled = jnp.tanh(cls @ pooler["w"].astype(jnp.float32)
                              + pooler["b"].astype(jnp.float32))
            clf = params["classifier"]
            logits = pooled @ clf["w"].astype(jnp.float32) \
                + clf["b"].astype(jnp.float32)
            return {"pooled_output": pooled, "logits": logits}

        return apply


register_model("bert_base")(BertBackend)
# Long-context single-chip variant: seq 2048 through the Pallas flash
# attention kernel — the O(S^2) score tensor never exists. Opt-in (a
# default load-all server shouldn't pay a second BERT load).
register_model("bert_long", default=False)(
    lambda: BertBackend(name="bert_long", seq_len=2048, max_batch_size=4,
                        attention_impl="flash"))
