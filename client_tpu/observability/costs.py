"""Per-request cost ledger: who bought the device seconds?

The profiler (observability/profiler.py) answers *where* device time
goes — per model/bucket, with padding waste — but not *who* caused it.
The flight recorder shows live p99 inflating 1.44x while shadow replay
runs, and nothing in the process can decompose that number into named
causes. This module is the accounting layer: every request carries a
**tenant tag** (``X-Tpu-Tenant`` HTTP header, ``tenant`` gRPC/infer
parameter, shm-ring slot header field, ``tools/replay.py`` stamping
``tenant=shadow``) and the serving layers charge measured resources to
it:

- **Device-seconds** — at batch completion the scheduler splits the
  batch's measured device time across member requests by real rows;
  the padded remainder is charged to the batch's *cause* (the dominant
  tenant by rows) under component ``padding``. Generative decode waves
  split per live stream the same way (component ``wave`` vs ``batch``).
- **Host-seconds** — the non-device remainder of a dense batch's wall
  time (input assembly, dispatch overhead, response scatter), split by
  the same weights. On a shared host this is capacity too: a shadow
  fleet's batches burn host time the live plane then waits behind, so
  foreign host occupancy feeds the bench's interference dilation leg
  alongside foreign device occupancy.
- **Queue-seconds** — the scheduler charges each request's measured
  queue wait at dequeue.
- **HBM-byte-seconds** — the generative KV arena charges rows held ×
  row bytes × wall time when a stream releases its row, reconcilable
  against the HBM census's ``kv_arena`` owner rows.
- **Interference** — a request co-batched with foreign-tenant rows
  records ``co_batch`` dilution seconds; a request that dequeued behind
  foreign-tenant occupancy records ``queue_wait`` seconds; admission
  sheds count under ``admission``. Together these decompose the shadow
  leak into named causes.

Conservation is the design invariant: Σ over tenants of device-seconds
(batch + wave + padding) equals the profiler's total device time for the
same interval, because both are fed the same measured ``device_ns`` —
the ledger only *splits*, never re-measures. ``tests/test_costs.py``
asserts this within 5%.

Tenant cardinality is bounded: ``default``, ``shadow``, any tenants
pre-registered via ``CLIENT_TPU_COSTS`` ``{"tenants": [...]}``, plus at
most ``max_tenants`` first-seen dynamic names; overflow folds to
``other`` so a tenant-per-request client cannot explode the metric
series space.

Like the profiler, the ledger is process-global (:func:`ledger`):
schedulers charge from below the engine, engines bind their
``MetricRegistry`` from above (:meth:`CostLedger.bind_metrics`,
per-registry weakrefs). Surfaces: ``GET /v2/costs`` / the ``Costs``
RPC render :meth:`CostLedger.snapshot`; ``tpu_cost_*`` counters carry
trace-id exemplars; ``cost.top_talker`` journal events fire when one
tenant's share of the rolling device-time window crosses the dominance
threshold; ``tools/cost_report.py`` pretty-prints the snapshot.
"""

from __future__ import annotations

import json
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

from client_tpu import config as envcfg
from client_tpu.utils import lockdep

ENV_VAR = "CLIENT_TPU_COSTS"

# The well-known tenants that always resolve to themselves: untagged
# traffic lands on "default"; the admission controller's shadow class
# (replay fleets) lands on "shadow"; dynamic overflow folds to "other".
TENANT_DEFAULT = "default"
TENANT_SHADOW = "shadow"
TENANT_OTHER = "other"

# Device-second components (the `component` label): scheduler batch
# executions, generative decode waves, and the padded remainder.
COMPONENTS = ("batch", "wave", "padding")
# Interference causes (the `cause` label on interference seconds);
# `admission` is a shed *count*, reported in the snapshot only.
INTERFERENCE_CAUSES = ("co_batch", "queue_wait")


@dataclass(frozen=True)
class CostsConfig:
    """Knobs behind ``CLIENT_TPU_COSTS`` (unset/``1``/``on`` = defaults,
    ``0``/``off`` disables charging, else inline JSON or ``@/path.json``)."""

    enabled: bool = True
    window_s: float = 60.0          # top-talker rolling window
    top_talker_fraction: float = 0.5
    # Ignore dominance verdicts until the window holds this much device
    # time — a single 2 ms warmup batch is not a top talker.
    top_talker_min_device_s: float = 0.05
    max_tenants: int = 32           # dynamic names before folding to other
    tenants: tuple[str, ...] = ()   # pre-registered tenant names

    @classmethod
    def from_env(cls, environ=None) -> "CostsConfig":
        text = envcfg.env_text(ENV_VAR, environ)
        low = text.lower()
        if low in ("0", "off", "false"):
            return cls(enabled=False)
        if low in ("", "1", "on", "true"):
            return cls()
        if text.startswith("@"):
            with open(text[1:], encoding="utf-8") as fh:
                text = fh.read()
        data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(
                f"{ENV_VAR} expects a JSON object, got {type(data).__name__}")
        return cls(
            enabled=bool(data.get("enabled", True)),
            window_s=max(1.0, float(data.get("window_s", 60.0))),
            top_talker_fraction=min(1.0, max(0.0, float(
                data.get("top_talker_fraction", 0.5)))),
            top_talker_min_device_s=max(0.0, float(
                data.get("top_talker_min_device_s", 0.05))),
            max_tenants=max(0, int(data.get("max_tenants", 32))),
            tenants=tuple(str(t) for t in data.get("tenants", ())),
        )


@dataclass
class _TenantCost:
    """Accumulated charges for one (tenant, model, version)."""

    device_s: float = 0.0       # batch + wave splits (no padding)
    padding_s: float = 0.0      # padded-row device time this tenant caused
    host_s: float = 0.0         # non-device batch wall (assembly/scatter)
    queue_s: float = 0.0
    hbm_byte_s: float = 0.0
    requests: int = 0
    co_batch_s: float = 0.0     # diluted by foreign-tenant rows
    queue_wait_s: float = 0.0   # waited behind foreign-tenant occupancy
    admission_sheds: int = 0


class _Bound:
    """One engine registry's cost-counter handles (see bind_metrics)."""

    __slots__ = ("registry_ref", "device_seconds", "host_seconds",
                 "queue_seconds", "hbm_byte_seconds",
                 "interference_seconds")

    def __init__(self, registry):
        self.registry_ref = weakref.ref(registry)
        self.device_seconds = registry.counter(
            "tpu_cost_device_seconds_total",
            "Device-seconds charged to a tenant (component: batch = "
            "real-row share of scheduler executions, wave = live-stream "
            "share of decode waves, padding = padded-row waste charged "
            "to the batch's dominant tenant)",
            ("tenant", "model", "component"))
        self.host_seconds = registry.counter(
            "tpu_cost_host_seconds_total",
            "Host-side batch seconds charged to a tenant: the non-device "
            "remainder of batch wall time (input assembly, dispatch "
            "overhead, response scatter), split by the same row weights "
            "as the device bill",
            ("tenant", "model"))
        self.queue_seconds = registry.counter(
            "tpu_cost_queue_seconds_total",
            "Scheduler queue-wait seconds charged to a tenant at dequeue",
            ("tenant", "model"))
        self.hbm_byte_seconds = registry.counter(
            "tpu_cost_hbm_byte_seconds_total",
            "KV-arena HBM residency charged to a tenant (row bytes x "
            "seconds held, charged when the stream releases its row)",
            ("tenant", "model"))
        self.interference_seconds = registry.counter(
            "tpu_cost_interference_seconds_total",
            "Seconds a tenant's requests lost to other tenants, by cause "
            "(co_batch = device dilution from foreign rows in the same "
            "batch, queue_wait = wait behind foreign queue occupancy)",
            ("tenant", "model", "cause"))


class CostLedger:
    """Tenant-tagged resource accounting; see module docstring."""

    def __init__(self, config: CostsConfig | None = None,
                 now=time.monotonic_ns):
        self.config = config or CostsConfig.from_env()
        self._now = now
        self._lock = lockdep.Lock("observability.costs")
        # (tenant, model, version) -> _TenantCost
        self._costs: dict[tuple[str, str, str], _TenantCost] = {}
        # Dynamically admitted tenant names (on top of the well-known
        # and pre-registered sets), capped at config.max_tenants.
        self._dynamic: set[str] = set()
        # Rolling device-time window for top-talker detection:
        # (mono_ns, tenant, device_s) per charge.
        self._window: deque[tuple[int, str, float]] = deque()
        # Rolling per-model arrival mix: {model: deque[(mono_ns, tenant)]}
        # — feeds the queue_wait interference split at dequeue. A mix
        # window (rather than live occupancy counting) survives requests
        # that dequeue without charging (timeouts, cancels, sheds).
        self._queue_mix: dict[str, deque[tuple[int, str]]] = {}
        self._top_latched: str | None = None
        self._bound: dict[int, _Bound] = {}

    # -- tenant identity -----------------------------------------------------

    def canonical_tenant(self, tenant: str | None) -> str:
        """Fold a wire-supplied tenant tag into the bounded label space:
        empty -> ``default``; well-known and pre-registered names pass;
        the first ``max_tenants`` novel names are admitted; the rest
        fold to ``other``."""
        t = str(tenant or "").strip()[:64]
        if not t:
            return TENANT_DEFAULT
        if t in (TENANT_DEFAULT, TENANT_SHADOW, TENANT_OTHER) \
                or t in self.config.tenants:
            return t
        with self._lock:
            if t in self._dynamic:
                return t
            if len(self._dynamic) < self.config.max_tenants:
                self._dynamic.add(t)
                return t
        return TENANT_OTHER

    # -- metric binding ------------------------------------------------------

    def bind_metrics(self, registry) -> None:
        """Declare the ``tpu_cost_*`` families on an engine's
        MetricRegistry and mirror later charges into it. Idempotent per
        registry; dead registries are pruned on the next charge."""
        b = _Bound(registry)
        with self._lock:
            self._bound[id(registry)] = b

    def _bindings(self) -> list[_Bound]:
        with self._lock:
            out = []
            for rid, b in list(self._bound.items()):
                if b.registry_ref() is None:
                    del self._bound[rid]
                else:
                    out.append(b)
            return out

    # -- charging (called from the schedulers) --------------------------------

    def _cost(self, tenant: str, model: str, version) -> _TenantCost:
        key = (tenant, str(model), str(version))
        c = self._costs.get(key)
        if c is None:
            c = self._costs[key] = _TenantCost()
        return c

    def charge_batch(self, model: str, version,
                     members: list[tuple[str, int, str | None]],
                     device_s: float, padded: int = 0,
                     component: str = "batch",
                     host_s: float = 0.0) -> None:
        """Split one batch's measured device time across its members.

        ``members`` is ``[(tenant, weight, trace_id), ...]`` where weight
        is the member's real rows (or summed lookups for ragged models,
        or 1 per live stream for decode waves); ``padded`` is the zero
        rows added to reach the bucket. Each member is charged
        ``device_s * weight / (total_weight + padded)``; the padded
        remainder is charged to the dominant tenant (most weight) under
        the ``padding`` component — the batch would not have run at that
        bucket without it. ``host_s`` (the batch's wall time net of the
        device interval) splits the same way, padded remainder to the
        dominant tenant, into the separate host-seconds meter. Members
        co-batched with foreign-tenant rows additionally record
        ``co_batch`` interference: their own share scaled by the foreign
        weight fraction — the slice of their device bill attributable to
        sharing the executable with someone else."""
        host_s = max(0.0, float(host_s))
        if not self.config.enabled or not members \
                or (device_s <= 0 and host_s <= 0):
            return
        device_s = max(0.0, float(device_s))
        members = [(self.canonical_tenant(t), max(0, int(w)), tr)
                   for t, w, tr in members]
        total_w = sum(w for _, w, _ in members)
        denom = total_w + max(0, int(padded))
        if denom <= 0:
            return
        per_tenant_w: dict[str, int] = {}
        for t, w, _ in members:
            per_tenant_w[t] = per_tenant_w.get(t, 0) + w
        dominant = max(per_tenant_w, key=lambda t: per_tenant_w[t])
        padding_s = device_s * max(0, int(padded)) / denom
        end = self._now()
        charges: list[tuple[str, str, float, str | None]] = []
        host_charges: list[tuple[str, float, str | None]] = []
        with self._lock:
            for t, w, tr in members:
                share = device_s * w / denom
                hshare = host_s * w / denom
                c = self._cost(t, model, version)
                c.device_s += share
                c.host_s += hshare
                c.requests += 1
                charges.append((t, component, share, tr))
                if hshare > 0:
                    host_charges.append((t, hshare, tr))
                foreign_w = total_w - per_tenant_w[t]
                if foreign_w > 0 and total_w > 0:
                    c.co_batch_s += share * foreign_w / total_w
            host_pad = host_s * max(0, int(padded)) / denom
            if padding_s > 0 or host_pad > 0:
                dom = self._cost(dominant, model, version)
                dom.padding_s += padding_s
                dom.host_s += host_pad
                if padding_s > 0:
                    charges.append((dominant, "padding", padding_s, None))
                if host_pad > 0:
                    host_charges.append((dominant, host_pad, None))
            self._window.append((end, dominant, device_s))
            self._prune_window_locked(end)
        for b in self._bindings():
            for t, comp, share, tr in charges:
                if share > 0:
                    b.device_seconds.inc(share, exemplar=tr, tenant=t,
                                         model=str(model), component=comp)
            for t, hshare, tr in host_charges:
                b.host_seconds.inc(hshare, exemplar=tr, tenant=t,
                                   model=str(model))
            for t, w, tr in members:
                foreign_w = total_w - per_tenant_w[t]
                if foreign_w > 0 and total_w > 0 and w > 0:
                    b.interference_seconds.inc(
                        (device_s * w / denom) * foreign_w / total_w,
                        exemplar=tr, tenant=t, model=str(model),
                        cause="co_batch")
        self._maybe_top_talker(end)

    def note_queued(self, model: str, tenant: str | None) -> None:
        """One request entered the scheduler queue — recorded into the
        model's rolling arrival mix so :meth:`charge_queue` can split
        each wait into own-tenant vs behind-foreign-tenant shares."""
        if not self.config.enabled:
            return
        t = self.canonical_tenant(tenant)
        now = self._now()
        with self._lock:
            mix = self._queue_mix.get(str(model))
            if mix is None:
                mix = self._queue_mix[str(model)] = deque(maxlen=4096)
            mix.append((now, t))

    def charge_queue(self, model: str, version, tenant: str | None,
                     queue_s: float, trace_id: str | None = None) -> None:
        """Charge one request's measured queue wait at dequeue. The
        ``queue_wait`` interference share is the wait scaled by the
        foreign-tenant fraction of the model's recent arrival mix — an
        approximation of who the request actually sat behind, but one
        that converges on sustained mixes, which is when interference
        matters (and it cannot leak: requests that dequeue without
        charging simply age out of the mix window)."""
        if not self.config.enabled:
            return
        t = self.canonical_tenant(tenant)
        queue_s = max(0.0, float(queue_s))
        horizon = self._now() - int(self.config.window_s * 1e9)
        with self._lock:
            mix = self._queue_mix.get(str(model))
            total = foreign = 0
            if mix:
                while mix and mix[0][0] < horizon:
                    mix.popleft()
                for _, mt in mix:
                    total += 1
                    if mt != t:
                        foreign += 1
            c = self._cost(t, model, version)
            c.queue_s += queue_s
            wait_behind = queue_s * foreign / total if total > 0 else 0.0
            c.queue_wait_s += wait_behind
        for b in self._bindings():
            if queue_s > 0:
                b.queue_seconds.inc(queue_s, exemplar=trace_id,
                                    tenant=t, model=str(model))
            if wait_behind > 0:
                b.interference_seconds.inc(wait_behind, exemplar=trace_id,
                                           tenant=t, model=str(model),
                                           cause="queue_wait")

    def charge_hbm(self, model: str, version, tenant: str | None,
                   byte_s: float, trace_id: str | None = None) -> None:
        """Charge KV-arena residency: row bytes x seconds held, called
        when a generative stream releases its arena row."""
        if not self.config.enabled:
            return
        t = self.canonical_tenant(tenant)
        byte_s = max(0.0, float(byte_s))
        if byte_s <= 0:
            return
        with self._lock:
            self._cost(t, model, version).hbm_byte_s += byte_s
        for b in self._bindings():
            b.hbm_byte_seconds.inc(byte_s, exemplar=trace_id,
                                   tenant=t, model=str(model))

    def note_shed(self, model: str, version, tenant: str | None,
                  reason: str) -> None:
        """One admission shed attributed to a tenant (the ``admission``
        leg of the interference taxonomy — a count, not seconds: the
        request never ran, so it has no measurable duration here)."""
        if not self.config.enabled:
            return
        t = self.canonical_tenant(tenant)
        with self._lock:
            self._cost(t, model, version).admission_sheds += 1

    # -- top-talker detection --------------------------------------------------

    def _prune_window_locked(self, now: int) -> None:
        horizon = now - int(self.config.window_s * 1e9)
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def _maybe_top_talker(self, now: int) -> None:
        """Edge-latched dominance check over the rolling device-time
        window: emits one ``cost.top_talker`` journal event when a tenant
        first crosses ``top_talker_fraction`` of the window (and again
        only after the crown changes hands or is vacated)."""
        with self._lock:
            self._prune_window_locked(now)
            totals: dict[str, float] = {}
            for _, t, s in self._window:
                totals[t] = totals.get(t, 0.0) + s
            window_s = sum(totals.values())
            top = max(totals, key=lambda t: totals[t]) if totals else None
            share = totals[top] / window_s if top and window_s > 0 else 0.0
            if window_s < self.config.top_talker_min_device_s \
                    or share < self.config.top_talker_fraction:
                self._top_latched = None
                return
            if top == self._top_latched:
                return
            self._top_latched = top
        # Lazy import, as the profiler does: importing the ledger must
        # not pull in the journal's env wiring.
        from client_tpu.observability.events import journal

        journal().emit(
            "cost", "top_talker", severity="WARNING", tenant=top,
            share=round(share, 4),
            window_device_s=round(window_s, 6),
            window_s=self.config.window_s)

    # -- report ---------------------------------------------------------------

    def snapshot(self, model: str | None = None) -> dict:
        """The ``GET /v2/costs`` body: per-tenant totals with a
        per-model breakdown and the interference taxonomy."""
        with self._lock:
            items = sorted(self._costs.items())
            self._prune_window_locked(self._now())
            win_totals: dict[str, float] = {}
            for _, t, s in self._window:
                win_totals[t] = win_totals.get(t, 0.0) + s
        tenants: dict[str, dict] = {}
        totals = {"device_s": 0.0, "padding_s": 0.0, "host_s": 0.0,
                  "queue_s": 0.0, "hbm_byte_s": 0.0, "requests": 0}
        for (tenant, mname, version), c in items:
            if model and mname != model:
                continue
            entry = tenants.get(tenant)
            if entry is None:
                entry = tenants[tenant] = {
                    "device_s": 0.0, "padding_s": 0.0, "host_s": 0.0,
                    "queue_s": 0.0, "hbm_byte_s": 0.0, "requests": 0,
                    "interference": {"co_batch_s": 0.0, "queue_wait_s": 0.0,
                                     "admission_sheds": 0},
                    "models": {},
                }
            row = {
                "model": mname, "version": version,
                "device_s": round(c.device_s, 6),
                "padding_s": round(c.padding_s, 6),
                "host_s": round(c.host_s, 6),
                "queue_s": round(c.queue_s, 6),
                "hbm_byte_s": round(c.hbm_byte_s, 3),
                "requests": c.requests,
                "interference": {
                    "co_batch_s": round(c.co_batch_s, 6),
                    "queue_wait_s": round(c.queue_wait_s, 6),
                    "admission_sheds": c.admission_sheds,
                },
            }
            entry["models"][f"{mname}:{version}"] = row
            entry["device_s"] += c.device_s
            entry["padding_s"] += c.padding_s
            entry["host_s"] += c.host_s
            entry["queue_s"] += c.queue_s
            entry["hbm_byte_s"] += c.hbm_byte_s
            entry["requests"] += c.requests
            entry["interference"]["co_batch_s"] += c.co_batch_s
            entry["interference"]["queue_wait_s"] += c.queue_wait_s
            entry["interference"]["admission_sheds"] += c.admission_sheds
            totals["device_s"] += c.device_s + c.padding_s
            totals["padding_s"] += c.padding_s
            totals["host_s"] += c.host_s
            totals["queue_s"] += c.queue_s
            totals["hbm_byte_s"] += c.hbm_byte_s
            totals["requests"] += c.requests
        for entry in tenants.values():
            for k in ("device_s", "padding_s", "host_s", "queue_s"):
                entry[k] = round(entry[k], 6)
            entry["hbm_byte_s"] = round(entry["hbm_byte_s"], 3)
            inter = entry["interference"]
            inter["co_batch_s"] = round(inter["co_batch_s"], 6)
            inter["queue_wait_s"] = round(inter["queue_wait_s"], 6)
        for k in ("device_s", "padding_s", "host_s", "queue_s"):
            totals[k] = round(totals[k], 6)
        totals["hbm_byte_s"] = round(totals["hbm_byte_s"], 3)
        window_total = sum(win_totals.values())
        top = max(win_totals, key=lambda t: win_totals[t]) \
            if win_totals else None
        return {
            "enabled": self.config.enabled,
            "window_s": self.config.window_s,
            "tenants": tenants,
            "totals": totals,
            "top_talker": {
                "tenant": top,
                "share": round(win_totals[top] / window_total, 4)
                if window_total > 0 else 0.0,
                "window_device_s": round(window_total, 6),
            } if top is not None else None,
        }

    def reset(self) -> None:
        """Drop accumulated charges (tests); metric bindings survive."""
        with self._lock:
            self._costs.clear()
            self._window.clear()
            self._queue_mix.clear()
            self._dynamic.clear()
            self._top_latched = None


# -- process-global default ledger --------------------------------------------

_default: CostLedger | None = None
_default_lock = lockdep.Lock("observability.costs.default")


def ledger() -> CostLedger:
    """The process-global cost ledger (double-checked, like
    :func:`client_tpu.observability.profiler.profiler`): schedulers
    charge into it from below the engine; engines bind their metric
    registries to it from above."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = CostLedger()
    return _default


def reset_ledger() -> None:
    """Drop the global ledger (tests); the next ledger() recreates it
    with current env settings."""
    global _default
    with _default_lock:
        _default = None
