"""Observability layer: metric primitives, per-request tracing, scrape
helpers.

Three pieces, threaded through every layer of the stack:

- :mod:`client_tpu.observability.metrics` — Prometheus-style Counter /
  Gauge / Histogram behind a :class:`MetricRegistry`;
  ``TpuEngine.prometheus_metrics()`` renders them alongside the legacy
  cumulative counters.
- :mod:`client_tpu.observability.tracing` — W3C ``traceparent``
  propagation, per-request phase spans in a bounded :class:`TraceStore`,
  Chrome trace-event export (``GET /v2/trace/requests``).
- :mod:`client_tpu.observability.client_stats` /
  :mod:`client_tpu.observability.scrape` — the client-side InferStat
  equivalent and /metrics parsing (bench's histogram-derived p50/p99).
- :mod:`client_tpu.observability.events` — bounded structured event
  journal (``GET /v2/events``) plus the CLIENT_TPU_LOG=json sink.
- :mod:`client_tpu.observability.slo` — per-model multi-window SLO
  burn-rate tracking (``GET /v2/slo``, ``tpu_slo_*`` gauges).
- :mod:`client_tpu.observability.profiler` — always-on efficiency
  profiler: batch-fill cost attribution, XLA compile telemetry, device
  duty-cycle (``GET /v2/profile``, ``tpu_batch_fill_ratio`` /
  ``tpu_xla_*`` / ``tpu_device_*`` families).
- :mod:`client_tpu.observability.fleet` — fleet-level merges of the
  per-replica surfaces (events/metrics/profile/slo/timeseries) plus
  the drift math behind ``tpu_fleet_drift_score`` (see
  :mod:`client_tpu.router.fleet` for the router-side half).
- :mod:`client_tpu.observability.timeseries` — the flight recorder: a
  process-global 1 Hz sampler recording duty cycle, queue depth, batch
  fill, shed rate, wave p50, HBM use and SLO burn into a bounded ring
  (``GET /v2/timeseries``, federated as ``/v2/fleet/timeseries``).
- :mod:`client_tpu.observability.memory` — the HBM census:
  byte-accurate device-memory attribution to ``(model, component)``
  owners, reconciled against planner arena reservations
  (``GET /v2/memory``, ``tpu_hbm_census_bytes`` /
  ``tpu_hbm_plan_drift_bytes``).

See docs/OBSERVABILITY.md for the metric vocabulary and wire formats.
"""

from client_tpu.observability.client_stats import InferStat  # noqa: F401
from client_tpu.observability.events import (  # noqa: F401
    Event,
    EventJournal,
    configure_logging,
    journal,
    reset_journal,
)
from client_tpu.observability.profiler import (  # noqa: F401
    EfficiencyProfiler,
    profiler,
    reset_profiler,
)
from client_tpu.observability.fleet import (  # noqa: F401
    FleetMonitorConfig,
    drift_scores,
    merge_events,
    merge_expositions,
    merge_profiles,
    merge_slo,
    parse_exposition,
    profile_signals,
)
from client_tpu.observability.timeseries import (  # noqa: F401
    FlightRecorder,
    TimeseriesConfig,
    recorder,
    reset_recorder,
)
from client_tpu.observability.memory import (  # noqa: F401
    HbmCensus,
    MemoryConfig,
    hbm_census,
    reset_hbm_census,
)
from client_tpu.observability.slo import SloConfig, SloTracker  # noqa: F401
from client_tpu.observability.metrics import (  # noqa: F401
    BATCH_SIZE_BUCKETS,
    DURATION_US_BUCKETS,
    Counter,
    EngineMetrics,
    Gauge,
    Histogram,
    MetricRegistry,
    REGISTRY,
)
from client_tpu.observability.tracing import (  # noqa: F401
    NamedSpan,
    RequestTrace,
    Span,
    SpanStore,
    TraceContext,
    TraceStore,
    build_request_trace,
    parse_server_timing,
    server_timing_header,
)
