"""Client-side per-request statistics (the InferStat equivalent).

The reference's C++ client keeps an ``InferStat`` (completed request count
and cumulative request/send/receive time) that perf_analyzer differences
per window. Our clients accumulate the same shape, extended with the
server-side phase breakdown surfaced by trace propagation: the HTTP client
reads it from the ``Server-Timing`` response header, the gRPC client from
``server_*_us`` response parameters.
"""

from __future__ import annotations

from client_tpu.utils import lockdep


class InferStat:
    """Thread-safe cumulative client-side stats; snapshot via get()."""

    _PHASES = ("queue", "compute_input", "compute_infer", "compute_output")

    def __init__(self):
        self._lock = lockdep.Lock("client_stats")
        self.completed_request_count = 0
        self.cumulative_total_request_time_us = 0.0
        # Server-phase cumulative sums; requests whose response carried no
        # phase timings contribute to the round-trip sum only.
        self.reported_request_count = 0
        self.cumulative_server_queue_us = 0.0
        self.cumulative_server_compute_input_us = 0.0
        self.cumulative_server_compute_infer_us = 0.0
        self.cumulative_server_compute_output_us = 0.0
        # Resilience events (PR-2): how often the client retried, replayed
        # a stale pooled socket, or was rejected locally by an open
        # circuit breaker. Zero unless the corresponding feature is on.
        self.retry_count = 0
        self.stale_socket_retry_count = 0
        self.breaker_rejected_count = 0
        # trace_id of the most recent completed request (empty until one
        # carries a trace) — the handle for jumping from client stats to
        # the server's /v2/events and /v2/trace/requests timelines.
        self.last_trace_id = ""
        # Cold-start attribution: requests whose Server-Timing carried a
        # `compile` entry (server_compile_us over gRPC) paid an XLA
        # compile — their latency outlier is compile, not queueing.
        self.cold_start_count = 0
        self.last_compile_s = 0.0

    def record(self, round_trip_us: float,
               server_timing: dict | None = None,
               trace_id: str | None = None) -> None:
        with self._lock:
            self.completed_request_count += 1
            self.cumulative_total_request_time_us += round_trip_us
            if trace_id:
                self.last_trace_id = trace_id
            if server_timing:
                self.reported_request_count += 1
                self.cumulative_server_queue_us += \
                    server_timing.get("queue", 0.0)
                self.cumulative_server_compute_input_us += \
                    server_timing.get("compute_input", 0.0)
                self.cumulative_server_compute_infer_us += \
                    server_timing.get("compute_infer", 0.0)
                self.cumulative_server_compute_output_us += \
                    server_timing.get("compute_output", 0.0)
                compile_us = server_timing.get("compile", 0.0)
                if compile_us > 0:
                    self.cold_start_count += 1
                    self.last_compile_s = compile_us / 1e6

    def record_retry(self) -> None:
        with self._lock:
            self.retry_count += 1

    def record_stale_socket_retry(self) -> None:
        with self._lock:
            self.stale_socket_retry_count += 1

    def record_breaker_rejection(self) -> None:
        with self._lock:
            self.breaker_rejected_count += 1

    def get(self) -> dict:
        with self._lock:
            return {
                "completed_request_count": self.completed_request_count,
                "cumulative_total_request_time_us":
                    round(self.cumulative_total_request_time_us, 1),
                "reported_request_count": self.reported_request_count,
                "cumulative_server_queue_us":
                    round(self.cumulative_server_queue_us, 1),
                "cumulative_server_compute_input_us":
                    round(self.cumulative_server_compute_input_us, 1),
                "cumulative_server_compute_infer_us":
                    round(self.cumulative_server_compute_infer_us, 1),
                "cumulative_server_compute_output_us":
                    round(self.cumulative_server_compute_output_us, 1),
                "retry_count": self.retry_count,
                "stale_socket_retry_count": self.stale_socket_retry_count,
                "breaker_rejected_count": self.breaker_rejected_count,
                "last_trace_id": self.last_trace_id,
                "cold_start_count": self.cold_start_count,
                "last_compile_s": round(self.last_compile_s, 6),
            }
