"""Per-request tracing: W3C trace context + a bounded span store.

Device-level profiling (``/v2/trace/setting`` → jax.profiler) answers "what
is the TPU doing"; this module answers "where did THIS request spend its
time". A trace id is adopted from the caller's ``traceparent`` HTTP header /
gRPC metadata (or generated at the frontend), carried on ``InferRequest``,
and when the final response lands the engine snapshots the request's phase
timestamps (queue / compute_input / compute_infer / compute_output) into a
``RequestTrace`` held in a ring buffer, exportable as Chrome trace-event
JSON via ``GET /v2/trace/requests`` (open the payload in
``chrome://tracing`` / Perfetto).

No external OpenTelemetry dependency: the traceparent format is 50 bytes of
hex and the export format is plain JSON, so the whole layer is stdlib.
"""

from __future__ import annotations

import json
import re
import secrets
from client_tpu.utils import lockdep
import time
from collections import deque
from dataclasses import dataclass, field

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# Decoupled streams can run to thousands of chunks; cap the per-request
# instant events so one long generation can't dominate the ring buffer.
MAX_CHUNK_EVENTS = 128

PHASES = ("queue", "compute_input", "compute_infer", "compute_output")


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class TraceContext:
    """Parsed W3C trace context (https://www.w3.org/TR/trace-context/)."""

    trace_id: str
    span_id: str
    parent_span_id: str = ""
    flags: int = 1

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=new_trace_id(), span_id=new_span_id())

    @classmethod
    def from_traceparent(cls, header: str | None) -> "TraceContext":
        """Adopt the caller's trace id (a fresh server span id becomes the
        child of the caller's span); invalid/absent headers start a new
        trace — never an error, per the spec's restart semantics."""
        if header:
            m = _TRACEPARENT_RE.match(header.strip().lower())
            if m and m.group(2) != "0" * 32 and m.group(3) != "0" * 16:
                return cls(trace_id=m.group(2), span_id=new_span_id(),
                           parent_span_id=m.group(3),
                           flags=int(m.group(4), 16))
        return cls.new()

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags & 0xFF:02x}"

    def child(self) -> "TraceContext":
        """Same trace, new span parented on this one (ensemble steps)."""
        return TraceContext(trace_id=self.trace_id, span_id=new_span_id(),
                            parent_span_id=self.span_id, flags=self.flags)


@dataclass
class Span:
    name: str
    start_ns: int
    end_ns: int


@dataclass
class RequestTrace:
    trace_id: str
    span_id: str
    parent_span_id: str
    model_name: str
    request_id: str
    ok: bool
    spans: list[Span] = field(default_factory=list)
    chunk_ts_ns: list[int] = field(default_factory=list)
    error: str = ""
    wall_time_ms: int = 0
    # XLA compile ns paid inside compute_infer (0 on warm requests); the
    # cold/warm flag rides the request span's args in the Chrome export.
    compile_ns: int = 0


def build_request_trace(ctx: TraceContext, model_name: str, request_id: str,
                        times, ok: bool, chunks=(),
                        error: str = "") -> RequestTrace:
    """Snapshot a finished request's phase timestamps into spans.

    ``times`` is the engine's RequestTimes; phases whose boundaries were
    never stamped (early rejects) are omitted rather than emitted as
    zero-width lies.
    """
    spans: list[Span] = []
    start = times.received or times.queue_start
    end = times.compute_output_end or times.compute_infer_end or start
    if start and end >= start:
        spans.append(Span("request", start, end))
    if times.queue_start and times.compute_start >= times.queue_start:
        spans.append(Span("queue", times.queue_start, times.compute_start))
    bounds = (
        ("compute_input", times.compute_start, times.compute_input_end),
        ("compute_infer", times.compute_input_end, times.compute_infer_end),
        ("compute_output", times.compute_infer_end,
         times.compute_output_end),
    )
    for name, s, e in bounds:
        if s and e >= s:
            spans.append(Span(name, s, e))
    return RequestTrace(
        trace_id=ctx.trace_id, span_id=ctx.span_id,
        parent_span_id=ctx.parent_span_id, model_name=model_name,
        request_id=request_id, ok=ok, spans=spans,
        chunk_ts_ns=list(chunks)[:MAX_CHUNK_EVENTS], error=error,
        # tpulint: allow[wall-clock] exported span timestamp (wall epoch by contract)
        wall_time_ms=int(time.time() * 1000),
        compile_ns=getattr(times, "compile_ns", 0))


class TraceStore:
    """Bounded ring buffer of finished request traces."""

    def __init__(self, capacity: int = 512):
        self._buf: deque[RequestTrace] = deque(maxlen=max(1, capacity))
        self._lock = lockdep.Lock("tracing.store")

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            self._buf.append(trace)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self, trace_id: str | None = None) -> list[RequestTrace]:
        with self._lock:
            traces = list(self._buf)
        if trace_id:
            traces = [t for t in traces if t.trace_id == trace_id]
        return traces

    def to_chrome_trace(self, trace_id: str | None = None) -> dict:
        """Chrome trace-event JSON (``ph:"X"`` complete events, µs units);
        one tid per request so parallel requests stack as lanes."""
        events = []
        for tid, t in enumerate(self.snapshot(trace_id), start=1):
            args = {"trace_id": t.trace_id, "span_id": t.span_id,
                    "model": t.model_name, "request_id": t.request_id,
                    "ok": t.ok, "cold_start": t.compile_ns > 0}
            if t.compile_ns:
                args["compile_ms"] = round(t.compile_ns / 1e6, 3)
            if t.parent_span_id:
                args["parent_span_id"] = t.parent_span_id
            if t.error:
                args["error"] = t.error
            for span in t.spans:
                events.append({
                    "name": f"{t.model_name}:{span.name}"
                            if span.name == "request" else span.name,
                    "cat": "request",
                    "ph": "X",
                    "ts": span.start_ns / 1e3,
                    "dur": max(0.0, (span.end_ns - span.start_ns) / 1e3),
                    "pid": 1,
                    "tid": tid,
                    "args": args,
                })
            for ts in t.chunk_ts_ns:
                events.append({
                    "name": "chunk", "cat": "stream", "ph": "i", "s": "t",
                    "ts": ts / 1e3, "pid": 1, "tid": tid,
                    "args": {"trace_id": t.trace_id},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def to_json(self, trace_id: str | None = None) -> str:
        return json.dumps(self.to_chrome_trace(trace_id))


@dataclass
class NamedSpan:
    """One free-form span: an interval with a name, optional span
    identity, and Chrome-trace ``args``. Unlike the engine's phase
    :class:`Span` (whose names are the fixed request phases), these are
    recorded by intermediaries — the router's select/proxy/shed spans —
    where the vocabulary is open."""

    name: str
    start_ns: int
    end_ns: int
    span_id: str = ""
    parent_span_id: str = ""
    args: dict = field(default_factory=dict)


@dataclass
class SpanGroup:
    """All spans one component recorded for one trace id (one request's
    router-side timeline)."""

    trace_id: str
    spans: list[NamedSpan]
    wall_time_ms: int = 0


class SpanStore:
    """Bounded ring buffer of :class:`SpanGroup`s — the intermediary
    (router) counterpart of :class:`TraceStore`. One ``add`` per routed
    request; export is Chrome trace events the fleet stitcher merges
    with the replicas' own ``/v2/trace/requests`` payloads."""

    def __init__(self, capacity: int = 512):
        self._buf: deque[SpanGroup] = deque(maxlen=max(1, capacity))
        self._lock = lockdep.Lock("tracing.spanstore")

    def add(self, trace_id: str, spans: list[NamedSpan]) -> None:
        if not spans:
            return
        with self._lock:
            self._buf.append(SpanGroup(
                trace_id=trace_id, spans=list(spans),
                # tpulint: allow[wall-clock] exported span timestamp (wall epoch by contract)
                wall_time_ms=int(time.time() * 1000)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self, trace_id: str | None = None) -> list[SpanGroup]:
        with self._lock:
            groups = list(self._buf)
        if trace_id:
            groups = [g for g in groups if g.trace_id == trace_id]
        return groups

    def to_chrome_events(self, trace_id: str | None = None,
                         pid: int = 1) -> list[dict]:
        """Chrome ``ph:"X"`` events; one tid per group so concurrent
        requests stack as lanes on the component's track."""
        events = []
        for tid, g in enumerate(self.snapshot(trace_id), start=1):
            for span in g.spans:
                args = {"trace_id": g.trace_id}
                if span.span_id:
                    args["span_id"] = span.span_id
                if span.parent_span_id:
                    args["parent_span_id"] = span.parent_span_id
                args.update(span.args)
                events.append({
                    "name": span.name,
                    "cat": "router",
                    "ph": "X",
                    "ts": span.start_ns / 1e3,
                    "dur": max(0.0, (span.end_ns - span.start_ns) / 1e3),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                })
        return events


def server_timing_header(times) -> str:
    """``Server-Timing`` response header (durations in ms per the spec).
    Requests that paid an XLA compile carry an extra ``compile`` entry so
    clients can attribute the latency outlier (InferStat cold-start)."""
    parts = []
    for phase, ns in (("queue", times.queue_ns),
                      ("compute_input", times.compute_input_ns),
                      ("compute_infer", times.compute_infer_ns),
                      ("compute_output", times.compute_output_ns)):
        parts.append(f"{phase};dur={ns / 1e6:.3f}")
    compile_ns = getattr(times, "compile_ns", 0)
    if compile_ns > 0:
        parts.append(f"compile;dur={compile_ns / 1e6:.3f}")
    return ", ".join(parts)


def parse_server_timing(header: str | None) -> dict[str, float]:
    """Parse a Server-Timing header into {phase: duration_us}."""
    out: dict[str, float] = {}
    if not header:
        return out
    for entry in header.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition(";")
        for attr in rest.split(";"):
            k, _, v = attr.strip().partition("=")
            if k == "dur":
                try:
                    out[name.strip()] = float(v) * 1e3  # ms -> us
                except ValueError:
                    pass
    return out
