"""Fleet-level observability: merge N replicas' surfaces into one view.

Each replica already exposes a rich local surface (``/metrics``,
``/v2/events``, ``/v2/profile``, ``/v2/slo``, ``/v2/trace/requests``).
This module is the pure-function half of the fleet plane: given the
payloads fetched from every replica (by the router's
:class:`client_tpu.router.fleet.FleetFederator`, or client-side by the
gRPC client iterating its endpoints), merge them with per-surface
semantics:

- **events** — tag each event with its replica, merge-sort by wall
  stamp, and return per-replica ``next_seq`` cursors so incremental
  fleet polls stay gap-detectable per replica.
- **metrics** — parse each replica's exposition text and re-render one
  fleet exposition: counters/histograms sum, gauges sum except
  level-like families (duty cycle, ratios, limits) which take the max.
- **profile / slo** — keyed by replica (summing device seconds across
  replicas would hide exactly the skew we want visible), plus a small
  computed fleet section.

Fetch failures are carried inline (``errors: {replica: reason}``) —
a dead replica degrades the aggregate, never fails it.

The second half is drift detection math: :func:`profile_signals`
extracts per-replica scalar signals (duty cycle, batch fill, decode
wave p50, queue wait) and :func:`drift_scores` scores each replica's
distance from the fleet median, normalized so one threshold works
across signals with different units. ``FleetMonitorConfig`` parses the
``CLIENT_TPU_FLEET_MONITOR`` env knob with the same grammar as
``CLIENT_TPU_AUTOTUNE``.
"""

from __future__ import annotations

import json
import os
from client_tpu import config as envcfg
import re
from dataclasses import dataclass, fields

__all__ = [
    "ENV_VAR",
    "FleetMonitorConfig",
    "drift_scores",
    "fleet_median",
    "merge_costs",
    "merge_events",
    "merge_expositions",
    "merge_profiles",
    "merge_slo",
    "merge_timeseries",
    "parse_exposition",
    "profile_signals",
    "timeseries_signals",
]

ENV_VAR = "CLIENT_TPU_FLEET_MONITOR"

# -- exposition merge ---------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+\S+)?\s*$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# Gauge families where "sum across replicas" is a lie: these are levels
# or ratios, so the fleet value is the worst replica, not the total.
# Matched by exact name or suffix.
_MAX_GAUGE_SUFFIXES = (
    "_ratio", "_fraction", "_duty_cycle", "_limit", "_burn_rate",
    "_drift_score", "_utilization",
)


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition (classic 0.0.4 or OpenMetrics)
    into ``{family: {"type", "help", "samples": [(name, labels, value)]}}``.

    Tolerant by design — unparseable lines are skipped, not fatal: this
    feeds an aggregation endpoint that must survive a replica mid-update.
    """
    families: dict[str, dict] = {}
    order: list[str] = []

    def fam(name: str) -> dict:
        if name not in families:
            families[name] = {"type": "untyped", "help": "", "samples": []}
            order.append(name)
        return families[name]

    for line in text.splitlines():
        line = line.strip()
        if not line or line == "# EOF":
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                name = parts[2]
                if parts[1] == "TYPE":
                    fam(name)["type"] = parts[3] if len(parts) > 3 \
                        else "untyped"
                else:
                    fam(name)["help"] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        sample_name, label_blob, raw_value = m.group(1), m.group(2), \
            m.group(3)
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = dict(_LABEL_RE.findall(label_blob)) if label_blob else {}
        # Attach the sample to its family: longest declared family name
        # that prefixes the sample name (covers _bucket/_sum/_count and
        # the OpenMetrics counter `_total` sample rename).
        owner = None
        for fname in order:
            if sample_name == fname or sample_name.startswith(fname + "_"):
                if owner is None or len(fname) > len(owner):
                    owner = fname
        if owner is None:
            owner = sample_name
        fam(owner)["samples"].append((sample_name, labels, value))
    return {name: families[name] for name in order if families[name]}


def _merge_mode(family: str, ftype: str) -> str:
    if ftype in ("counter", "histogram", "summary"):
        return "sum"
    if ftype == "gauge":
        for suffix in _MAX_GAUGE_SUFFIXES:
            if family.endswith(suffix) or family.endswith(suffix + "s"):
                return "max"
        return "sum"
    return "sum"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def merge_expositions(exposures: dict[str, str]) -> str:
    """Merge per-replica exposition texts into one classic-dialect text.

    Series identity is (sample name, labels); counters and histograms
    sum across replicas, level-like gauges take the fleet max (see
    module doc). Type/help come from the first replica declaring the
    family.
    """
    merged: dict[str, dict] = {}
    order: list[str] = []
    for _replica in sorted(exposures):
        for fname, f in parse_exposition(exposures[_replica]).items():
            if fname not in merged:
                merged[fname] = {"type": f["type"], "help": f["help"],
                                 "series": {}}
                order.append(fname)
            dst = merged[fname]
            if dst["type"] == "untyped" and f["type"] != "untyped":
                dst["type"] = f["type"]
            mode = _merge_mode(fname, dst["type"])
            for sample_name, labels, value in f["samples"]:
                key = (sample_name,
                       tuple(sorted(labels.items())))
                if key not in dst["series"]:
                    dst["series"][key] = value
                elif mode == "max":
                    dst["series"][key] = max(dst["series"][key], value)
                else:
                    dst["series"][key] += value
    lines: list[str] = []
    for fname in order:
        f = merged[fname]
        if f["help"]:
            lines.append(f"# HELP {fname} {f['help']}")
        lines.append(f"# TYPE {fname} {f['type']}")
        for (sample_name, labels), value in f["series"].items():
            blob = ""
            if labels:
                inner = ",".join(f'{k}="{v}"' for k, v in labels)
                blob = "{" + inner + "}"
            lines.append(f"{sample_name}{blob} {_fmt_value(value)}")
    return "\n".join(lines) + "\n" if lines else ""


# -- events merge -------------------------------------------------------------


def merge_events(exports: dict[str, dict],
                 errors: dict[str, str] | None = None,
                 limit: int | None = None) -> dict:
    """Merge per-replica ``/v2/events`` exports into one fleet timeline.

    Every event gains a ``replica`` field; ordering is by wall stamp
    (then per-replica seq) because seq spaces are per-process. The
    ``cursors`` map carries each replica's ``next_seq`` so a poller can
    resume each replica exactly where it left off (``?since=`` is
    per-replica, never global).
    """
    events: list[dict] = []
    cursors: dict[str, int] = {}
    dropped = 0
    for replica in sorted(exports):
        exp = exports[replica]
        cursors[replica] = int(exp.get("next_seq", 0))
        dropped += int(exp.get("dropped", 0))
        for evt in exp.get("events", ()):
            tagged = dict(evt)
            tagged["replica"] = replica
            events.append(tagged)
    events.sort(key=lambda e: (e.get("ts_wall", 0), e.get("replica", ""),
                               e.get("seq", 0)))
    if limit is not None and limit >= 0:
        events = events[-limit:]
    return {
        "events": events,
        "cursors": cursors,
        "dropped": dropped,
        "replicas": sorted(exports),
        "errors": dict(errors or {}),
    }


def merge_timeseries(exports: dict[str, dict],
                     errors: dict[str, str] | None = None,
                     limit: int | None = None) -> dict:
    """Merge per-replica ``/v2/timeseries`` exports into one fleet
    stream. Same contract as :func:`merge_events`: every sample gains a
    ``replica`` tag, ordering is by wall stamp (seq spaces are
    per-process), ``cursors`` carries each replica's ``next_seq`` so an
    incremental poller resumes per replica."""
    samples: list[dict] = []
    cursors: dict[str, int] = {}
    dropped = 0
    interval_s = None
    for replica in sorted(exports):
        exp = exports[replica] or {}
        cursors[replica] = int(exp.get("next_seq", 0))
        dropped += int(exp.get("dropped", 0))
        if interval_s is None and exp.get("interval_s") is not None:
            interval_s = exp["interval_s"]
        for s in exp.get("samples", ()):
            tagged = dict(s)
            tagged["replica"] = replica
            samples.append(tagged)
    samples.sort(key=lambda s: (s.get("ts_wall", 0),
                                s.get("replica", ""), s.get("seq", 0)))
    if limit is not None and limit >= 0:
        samples = samples[-limit:]
    return {
        "samples": samples,
        "cursors": cursors,
        "dropped": dropped,
        "interval_s": interval_s,
        "replicas": sorted(exports),
        "errors": dict(errors or {}),
    }


# -- profile / slo merge ------------------------------------------------------


def merge_profiles(profiles: dict[str, dict],
                   errors: dict[str, str] | None = None,
                   drift: dict | None = None) -> dict:
    """Fleet profile: per-replica snapshots keyed by replica id plus a
    computed fleet section (medians + per-replica signals). Raw
    snapshots are passed through untouched so ``tools/profile_report.py
    --fleet`` can reuse the single-replica renderer per row."""
    signals = {r: profile_signals(p) for r, p in profiles.items()}
    scores, medians = drift_scores(signals)
    fleet = {
        "replica_count": len(profiles),
        "signals": signals,
        "medians": medians,
        "drift_scores": scores,
    }
    out = {
        "replicas": profiles,
        "fleet": fleet,
        "errors": dict(errors or {}),
    }
    if drift is not None:
        out["drift"] = drift
    return out


def merge_slo(exports: dict[str, dict],
              errors: dict[str, str] | None = None) -> dict:
    """Fleet SLO: per-replica keyed (burn rates don't sum), plus the
    fleet-level alarm — the worst fast-burn seen anywhere."""
    worst = {"replica": None, "fast_burn": 0.0}
    for replica, exp in exports.items():
        for model in (exp or {}).get("models", {}).values():
            for window in model.get("windows", ()):
                burn = float(window.get("burn_rate", 0.0) or 0.0)
                if burn > worst["fast_burn"]:
                    worst = {"replica": replica, "fast_burn": burn}
    return {
        "replicas": exports,
        "worst": worst,
        "errors": dict(errors or {}),
    }


def merge_costs(exports: dict[str, dict],
                errors: dict[str, str] | None = None) -> dict:
    """Fleet cost ledger: per-replica snapshots keyed by replica, plus
    fleet-wide per-tenant totals (device/padding/queue/HBM seconds sum
    across replicas — each replica meters its own device) and the
    fleet's loudest top-talker."""
    tenants: dict[str, dict] = {}
    totals = {"device_s": 0.0, "padding_s": 0.0, "queue_s": 0.0,
              "hbm_byte_s": 0.0, "requests": 0}
    worst = {"replica": None, "tenant": None, "share": 0.0}
    for replica, exp in exports.items():
        for tenant, row in (exp or {}).get("tenants", {}).items():
            agg = tenants.setdefault(tenant, {
                "device_s": 0.0, "padding_s": 0.0, "queue_s": 0.0,
                "hbm_byte_s": 0.0, "requests": 0,
                "co_batch_s": 0.0, "queue_wait_s": 0.0,
                "admission_sheds": 0})
            for key in ("device_s", "padding_s", "queue_s",
                        "hbm_byte_s", "requests"):
                agg[key] += row.get(key, 0)
            interference = row.get("interference", {})
            for key in ("co_batch_s", "queue_wait_s", "admission_sheds"):
                agg[key] += interference.get(key, 0)
        for key in totals:
            totals[key] += (exp or {}).get("totals", {}).get(key, 0)
        top = (exp or {}).get("top_talker")
        if top and float(top.get("share", 0.0)) > worst["share"]:
            worst = {"replica": replica, "tenant": top.get("tenant"),
                     "share": float(top.get("share", 0.0))}
    return {
        "replicas": exports,
        "tenants": tenants,
        "totals": totals,
        "top_talker": worst if worst["tenant"] is not None else None,
        "errors": dict(errors or {}),
    }


# -- drift math ---------------------------------------------------------------

# Normalization floors: |v - median| / max(|median|, floor). The floor
# keeps near-zero medians (idle fleet) from turning measurement noise
# into huge relative scores.
SIGNAL_FLOORS = {
    "duty_cycle": 0.05,
    "fill_ratio": 0.05,
    "wave_ms_p50": 1.0,
    "wait_s": 0.05,
    "mfu": 0.02,
}


def profile_signals(profile: dict | None,
                    load: dict | None = None) -> dict[str, float]:
    """Extract the drift signals from one replica's ``/v2/profile``
    snapshot (plus optionally its LoadReport dict for queue wait).
    Signals without evidence are omitted, not zeroed — a replica that
    has never decoded must not read as 'drifted to 0 ms waves'."""
    signals: dict[str, float] = {}
    if profile:
        duty = profile.get("duty_cycle")
        if duty is not None:
            signals["duty_cycle"] = float(duty)
        rows = padded = 0.0
        waves_total = 0.0
        wave_weighted = 0.0
        mfu_weighted = mfu_weight = 0.0
        for m in profile.get("models", {}).values():
            for b in m.get("buckets", ()):
                rows += float(b.get("rows", 0) or 0)
                padded += float(b.get("padded_rows", 0) or 0)
            for w in m.get("decode_waves", ()):
                n = float(w.get("waves", 0) or 0)
                p50 = w.get("wave_ms_p50")
                if n > 0 and p50 is not None:
                    waves_total += n
                    wave_weighted += n * float(p50)
            # Roofline MFU, device-time weighted across models: a busy
            # model's utilization should dominate the replica signal.
            mfu = (m.get("roofline") or {}).get("mfu")
            if mfu is not None:
                weight = max(float(m.get("device_s", 0.0) or 0.0), 1e-9)
                mfu_weighted += float(mfu) * weight
                mfu_weight += weight
        if padded > 0:
            signals["fill_ratio"] = rows / padded
        if waves_total > 0:
            signals["wave_ms_p50"] = wave_weighted / waves_total
        if mfu_weight > 0:
            signals["mfu"] = mfu_weighted / mfu_weight
    if load:
        wait = load.get("wait_s")
        if wait is not None:
            signals["wait_s"] = float(wait)
    return signals


def timeseries_signals(export: dict | None, window_s: float = 60.0,
                       now: float | None = None) -> dict[str, float]:
    """Extract the drift signals from one replica's ``/v2/timeseries``
    export as *windowed medians* — the flight-recorder upgrade over
    :func:`profile_signals`' single-scrape instantaneous values. A
    replica mid-GC or mid-compile no longer reads as drifted: one
    outlier second cannot move a 60-sample median. Keys match
    ``profile_signals`` (duty_cycle / fill_ratio / wave_ms_p50) so
    :func:`drift_scores` and SIGNAL_FLOORS apply unchanged; signals
    without evidence in the window are omitted, not zeroed."""
    if not export:
        return {}
    samples = export.get("samples") or []
    if not samples:
        return {}
    if now is None:
        now = max(float(s.get("ts_wall", 0) or 0) for s in samples)
    duty: list[float] = []
    fill: list[float] = []
    wave: list[float] = []
    mfu: list[float] = []
    for s in samples:
        if float(s.get("ts_wall", 0) or 0) < now - window_s:
            continue
        sig = s.get("signals") or {}
        if sig.get("duty_cycle") is not None:
            duty.append(float(sig["duty_cycle"]))
        for source, dest in (("batch_fill", fill), ("wave_p50_ms", wave),
                             ("mfu", mfu)):
            per_model = sig.get(source)
            if isinstance(per_model, dict) and per_model:
                vals = [float(v) for v in per_model.values()]
                dest.append(sum(vals) / len(vals))
    signals: dict[str, float] = {}
    if duty:
        signals["duty_cycle"] = fleet_median(duty)
    if fill:
        signals["fill_ratio"] = fleet_median(fill)
    if wave:
        signals["wave_ms_p50"] = fleet_median(wave)
    if mfu:
        signals["mfu"] = fleet_median(mfu)
    return signals


def fleet_median(values: list[float]) -> float:
    s = sorted(values)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    if n % 2:
        return s[mid]
    return (s[mid - 1] + s[mid]) / 2.0


def drift_scores(signals_by_replica: dict[str, dict[str, float]],
                 ) -> tuple[dict[str, dict[str, float]],
                            dict[str, float]]:
    """Score each replica's distance from the fleet median per signal.

    ``score = |v - median| / max(|median|, floor)`` — a unitless skew so
    one threshold (FleetMonitorConfig.threshold) covers duty cycle
    (0..1) and wave latency (ms) alike. Signals reported by fewer than
    two replicas are skipped: no fleet, no drift.
    """
    by_signal: dict[str, dict[str, float]] = {}
    for replica, signals in signals_by_replica.items():
        for name, value in signals.items():
            by_signal.setdefault(name, {})[replica] = value
    medians: dict[str, float] = {}
    scores: dict[str, dict[str, float]] = {
        r: {} for r in signals_by_replica}
    for name, per_replica in by_signal.items():
        if len(per_replica) < 2:
            continue
        median = fleet_median(list(per_replica.values()))
        medians[name] = median
        floor = SIGNAL_FLOORS.get(name, 1.0)
        denom = max(abs(median), floor)
        for replica, value in per_replica.items():
            scores[replica][name] = abs(value - median) / denom
    return scores, medians


# -- monitor config -----------------------------------------------------------


@dataclass
class FleetMonitorConfig:
    """``CLIENT_TPU_FLEET_MONITOR`` knobs (grammar matches
    ``CLIENT_TPU_AUTOTUNE``: unset/"0"/"off" disables, "1"/"true"/"on"
    takes defaults, else inline JSON or ``@file``)."""

    interval_s: float = 5.0    # monitor wake period
    threshold: float = 0.5     # drift score above this flags the replica
    min_replicas: int = 2      # no drift math below this fleet size
    window_s: float = 60.0     # flight-recorder median window per scrape

    @classmethod
    def from_dict(cls, data: dict) -> "FleetMonitorConfig":
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        cfg = cls()
        for f in fields(cls):
            if f.name not in data:
                continue
            raw = data[f.name]
            try:
                coerce = int if f.name == "min_replicas" else float
                setattr(cfg, f.name, coerce(raw))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{ENV_VAR}: key '{f.name}' expects a number, "
                    f"got {raw!r}") from None
        if cfg.interval_s <= 0:
            raise ValueError(f"{ENV_VAR}: interval_s must be > 0")
        if cfg.threshold <= 0:
            raise ValueError(f"{ENV_VAR}: threshold must be > 0")
        if cfg.min_replicas < 2:
            raise ValueError(f"{ENV_VAR}: min_replicas must be >= 2")
        if cfg.window_s <= 0:
            raise ValueError(f"{ENV_VAR}: window_s must be > 0")
        return cfg

    @classmethod
    def from_env(cls, env_var: str = ENV_VAR,
                 environ=os.environ) -> "FleetMonitorConfig | None":
        raw = envcfg.env_text(env_var, environ)
        if not raw or raw.lower() in ("0", "false", "off"):
            return None
        if raw.lower() in ("1", "true", "on"):
            return cls()
        if raw.startswith("@"):
            try:
                with open(raw[1:]) as f:
                    raw = f.read()
            except OSError as exc:
                raise ValueError(
                    f"{env_var}: cannot read '{raw[1:]}': {exc}") from None
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{env_var}: invalid JSON ({exc})") from None
        if not isinstance(data, dict):
            raise ValueError(f"{env_var}: expected a JSON object")
        return cls.from_dict(data)

    def summary(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}
