"""Exposition-format scraping helpers: parse /metrics, diff histograms,
derive quantiles.

Used by bench.py to snapshot the engine's request-duration histogram
before/after a load run and attach histogram-derived p50/p99 to the BENCH
record alongside the wall-clock numbers — the cross-check that catches a
client-side timer measuring its own scheduling jitter.
"""

from __future__ import annotations

import math
import re

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+\d+)?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_samples(text: str) -> list[tuple[str, dict, float]]:
    """Yield (metric_name, labels, value) for every sample line."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # OpenMetrics exposition suffixes bucket/counter samples with an
        # exemplar ("... # {trace_id=...} value"); drop it, or the greedy
        # label match would read the exemplar value as the sample value.
        # (Our label values never contain " # ", so the split is safe.)
        if " # " in line:
            line = line.split(" # ", 1)[0].rstrip()
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelstr, raw = m.group(1), m.group(2) or "", m.group(3)
        labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(labelstr)}
        try:
            value = float(raw)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def histogram_state(text: str, family: str) -> dict:
    """Aggregate one histogram family over ALL its label sets into
    {"buckets": {le: cumulative_count}, "sum": s, "count": n}.

    Aggregating cumulative buckets across label sets is sound because every
    series of a family shares the same ``le`` ladder.
    """
    buckets: dict[float, float] = {}
    total_sum = 0.0
    total_count = 0.0
    for name, labels, value in parse_samples(text):
        if name == f"{family}_bucket" and "le" in labels:
            le = (math.inf if labels["le"] == "+Inf"
                  else float(labels["le"]))
            buckets[le] = buckets.get(le, 0.0) + value
        elif name == f"{family}_sum":
            total_sum += value
        elif name == f"{family}_count":
            total_count += value
    return {"buckets": buckets, "sum": total_sum, "count": total_count}


def delta(after: dict, before: dict) -> dict:
    """Windowed difference of two histogram_state snapshots."""
    buckets = {
        le: after["buckets"].get(le, 0.0) - before["buckets"].get(le, 0.0)
        for le in after["buckets"]
    }
    return {"buckets": buckets,
            "sum": after["sum"] - before["sum"],
            "count": after["count"] - before["count"]}


def quantile(state: dict, q: float) -> float:
    """Prometheus-style histogram_quantile: linear interpolation inside the
    target bucket; returns NaN for an empty window and the highest finite
    bound when the target lands in +Inf."""
    count = state["count"]
    if count <= 0 or not state["buckets"]:
        return float("nan")
    rank = q * count
    les = sorted(state["buckets"])
    prev_le, prev_cum = 0.0, 0.0
    for le in les:
        cum = state["buckets"][le]
        if cum >= rank:
            if math.isinf(le):
                finite = [b for b in les if not math.isinf(b)]
                return finite[-1] if finite else float("nan")
            width = le - prev_le
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return le
            return prev_le + width * (rank - prev_cum) / in_bucket
        prev_le, prev_cum = le, cum
    return les[-1] if les and not math.isinf(les[-1]) else float("nan")
