"""Continuous efficiency profiler: where does the device time go?

PR-1 tracing answers "where did THIS request spend its time" and PR-4
events/SLO answer "is the server healthy"; this module answers the cost
question the ROADMAP north-star ("as fast as the hardware allows") is
ultimately judged by: which model/bucket pairs burn device seconds, how
much of every padded batch is real work, and how often XLA recompiles.

Three always-on signals, recorded from ``Model.execute_timed`` at a cost
of a few dict operations per *batch* (not per request):

- **Batch-fill cost attribution** — per (model, version, bucket): call
  counts, real vs padded rows, device/host time totals + per-call EWMA.
  Rendered as the ``tpu_batch_fill_ratio`` histogram and the
  ``tpu_padded_rows_total`` counter; the padding-waste estimate in
  :meth:`EfficiencyProfiler.snapshot` is ``device_s * padded/(real+padded)``
  — the device seconds spent multiplying zeros.
- **Compile telemetry** — every first-call XLA trace of a bucket counts on
  ``tpu_xla_compilations_total{model,version,bucket}``, observes
  ``tpu_xla_compile_seconds``, and emits a ``compile.finished`` event into
  the PR-4 journal. Cold executions are excluded from device-time
  accumulation so one 30 s compile doesn't masquerade as load.
- **Device duty-cycle** — a sliding window (default 60 s,
  ``CLIENT_TPU_PROFILE_WINDOW_S``) of executable-busy intervals, sampled
  at scrape time into the ``tpu_device_duty_cycle`` gauge (busy device
  time / wall time; can exceed 1.0 when model instances execute
  concurrently on multiple devices) plus the per-model
  ``tpu_device_seconds_total`` counter.

Like the fault registry and the event journal, the profiler is
process-global (:func:`profiler`) because models execute below the engine
and must not hold engine references; each engine binds its own
``MetricRegistry`` via :meth:`EfficiencyProfiler.bind_metrics` (per-registry
weakrefs — dead engines are pruned, rebinding replaces). The JSON cost
table behind ``GET /v2/profile`` / the ``Profile`` RPC comes from
:meth:`EfficiencyProfiler.snapshot`; ``tools/profile_report.py``
pretty-prints it.
"""

from __future__ import annotations

import os
from client_tpu import config as envcfg
from client_tpu.observability import roofline as _roofline
from client_tpu.utils import lockdep
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

# Fill ratio lives in (0, 1]; power-of-two ladders can't go below 0.5 but
# custom ladders (and max_batch_size overflow buckets) can.
FILL_RATIO_BUCKETS = (0.25, 0.5, 0.625, 0.75, 0.875, 1.0)
# First compiles run 20-40 s on TPU, sub-second on CPU tests.
COMPILE_SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                           20.0, 40.0, 80.0, 160.0)
# Decode wave steps: ~1-3 ms on TPU, tens of ms on the CPU test backend.
WAVE_SECONDS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                        0.05, 0.1, 0.25, 1.0)

# EWMA smoothing for per-call device/host time (~last 10 calls dominate).
_EWMA_ALPHA = 0.2

# A bucket ladder tweak is only suggested once a bucket has enough calls
# to make its fill ratio meaningful, and only when it wastes real time.
_SUGGEST_MIN_CALLS = 8
_SUGGEST_MAX_FILL = 0.85
# A bucket is "cold" (retire candidate) when its call rate over the
# profile window drops below this floor — warmup gives every ladder
# bucket one execution, so a bucket nobody uses decays to ~0 calls/min
# once the window slides past it. The autotuner applies its own floor on
# top (AutotuneConfig.retire_rate_per_min); this default keeps
# /v2/profile's suggestions aligned with what the tuner can do.
_SUGGEST_RETIRE_RATE_PER_MIN = 0.5


@dataclass
class _BucketCost:
    """Accumulated cost of one (model, version, bucket) execution shape."""

    calls: int = 0
    cold_calls: int = 0
    rows: int = 0            # real rows executed
    padded_rows: int = 0     # zero rows added to reach the bucket
    device_ns: int = 0       # executable time, warm calls only
    host_ns: int = 0         # staging + fetch host time, warm calls only
    device_ns_ewma: float = 0.0
    host_ns_ewma: float = 0.0
    compile_count: int = 0
    compile_ns: int = 0
    max_rows: int = 0
    # Which quantity the bucket pads: "rows" (default) or "lookups"
    # (ragged DLRM — ``rows`` above then counts summed lookups, and the
    # fill/suggestion math is identical; only renderers need the tag so a
    # 512-lookup bucket isn't misread as a 512-row batch).
    axis: str = "rows"
    # Recency tracking for retire suggestions: a two-window rotation gives
    # an O(1)-per-call sliding call rate (a timestamp deque would cost
    # memory proportional to call rate — thousands/s under load). The
    # current window accumulates calls since ``win_start``; when it
    # exceeds the profiler window it rotates into ``prev_*``. The rate at
    # snapshot time is (prev + current calls) / (prev + current span) —
    # a bucket that goes quiet decays toward zero as the span grows.
    first_seen: int = 0      # mono ns of first record (0 = never)
    win_start: int = 0       # current rate-window start, mono ns
    win_calls: int = 0
    prev_win_s: float = 0.0  # span of the rotated-out window, seconds
    prev_win_calls: int = 0
    # Static XLA cost model captured at compile time (record_cost_model):
    # {"available": True, "flops", "bytes_accessed", ...} or the
    # annotated absence. None until the first capture attempt.
    cost_model: dict | None = None

    def fill_ratio(self) -> float:
        total = self.rows + self.padded_rows
        return (self.rows / total) if total else 1.0

    def padding_waste_device_s(self) -> float:
        """Device seconds spent on padding rows: the executable runs the
        full bucket, so the padded fraction of its time is pure waste."""
        total = self.rows + self.padded_rows
        if not total or not self.padded_rows:
            return 0.0
        return (self.device_ns / 1e9) * (self.padded_rows / total)

    def touch(self, now: int, window_ns: int) -> None:
        """Count one call into the sliding rate window (rotate first when
        the current window has outlived the profiler window)."""
        if self.first_seen == 0:
            self.first_seen = now
        if self.win_start == 0:
            self.win_start = now
        elif now - self.win_start >= window_ns:
            self.prev_win_calls = self.win_calls
            self.prev_win_s = (now - self.win_start) / 1e9
            self.win_calls = 0
            self.win_start = now
        self.win_calls += 1

    def calls_per_min(self, now: int) -> float:
        """Sliding call rate: counted calls over the covered span (clamped
        to ≥1 s so a just-created bucket doesn't read as infinite)."""
        if self.win_start == 0:
            return 0.0
        span_s = (now - self.win_start) / 1e9 + self.prev_win_s
        return 60.0 * (self.win_calls + self.prev_win_calls) \
            / max(span_s, 1.0)


@dataclass
class _WaveCost:
    """Accumulated decode-wave timing for one (model, version, bucket,
    chunk) shape — fed by the generative scheduler at fetch time (waves
    don't pass through ``Model.execute_timed``; they are dispatched
    pipelined and their occupancy is only known when the token fetch
    lands)."""

    waves: int = 0
    dispatches: int = 0      # executable launches (waves / chunk)
    device_ns: int = 0
    wave_ns_ewma: float = 0.0
    # Per-dispatch per-wave samples for snapshot percentiles; bounded so
    # a long-running engine can't grow it.
    recent: deque = field(default_factory=lambda: deque(maxlen=512))
    # Static cost of one dispatch (the whole K-chunk, not one wave).
    cost_model: dict | None = None


class _Bound:
    """One engine registry's instrument handles (see bind_metrics)."""

    __slots__ = ("registry_ref", "fill_ratio", "padded_rows",
                 "compilations", "compile_seconds", "device_seconds",
                 "duty_cycle", "wave_seconds", "model_flops",
                 "mfu", "mbu")

    def __init__(self, registry):
        self.registry_ref = weakref.ref(registry)
        self.fill_ratio = registry.histogram(
            "tpu_batch_fill_ratio",
            "Real rows / padded bucket rows per device execution",
            ("model", "version"), buckets=FILL_RATIO_BUCKETS)
        self.padded_rows = registry.counter(
            "tpu_padded_rows_total",
            "Zero rows added to reach the batch bucket (pure device waste)",
            ("model", "version", "bucket"))
        self.compilations = registry.counter(
            "tpu_xla_compilations_total",
            "XLA compilations (first call per model/bucket signature)",
            ("model", "version", "bucket"))
        self.compile_seconds = registry.histogram(
            "tpu_xla_compile_seconds",
            "XLA compile duration per first-call bucket trace (seconds)",
            ("model", "version"), buckets=COMPILE_SECONDS_BUCKETS)
        self.device_seconds = registry.counter(
            "tpu_device_seconds_total",
            "Cumulative executable-busy device time (warm executions)",
            ("model", "version"))
        self.duty_cycle = registry.gauge(
            "tpu_device_duty_cycle",
            "Busy device time / wall time over the profiler window "
            "(sampled at scrape; >1.0 means concurrent instances)")
        self.duty_cycle.set(0.0)
        self.wave_seconds = registry.histogram(
            "tpu_decode_wave_seconds",
            "Per-wave decode step time of the generative engine "
            "(bucket = wave lane count, chunk = waves per dispatch)",
            ("model", "version", "bucket", "chunk"),
            buckets=WAVE_SECONDS_BUCKETS)
        self.model_flops = registry.counter(
            "tpu_model_flops_total",
            "XLA cost-model FLOPs dispatched by warm executions "
            "(static flops per call, padded bucket priced in full)",
            ("model", "version", "bucket"))
        self.mfu = registry.gauge(
            "tpu_mfu",
            "Model FLOP/s utilization per bucket: cost-model flops x "
            "warm calls / device seconds, over the device-kind peak "
            "(absent when peaks or cost model are unknown)",
            ("model", "version", "bucket"))
        self.mbu = registry.gauge(
            "tpu_mbu",
            "Memory bandwidth utilization per bucket: cost-model bytes "
            "accessed x warm calls / device seconds, over the "
            "device-kind peak (absent when unknown)",
            ("model", "version", "bucket"))


class EfficiencyProfiler:
    """Low-overhead always-on cost attribution; see module docstring."""

    def __init__(self, window_s: float | None = None, now=time.monotonic_ns):
        if window_s is None:
            window_s = envcfg.env_float("CLIENT_TPU_PROFILE_WINDOW_S")
        self.window_s = max(1.0, window_s)
        self._now = now
        self._t0 = now()
        self._lock = lockdep.Lock("observability.profiler")
        self._costs: dict[tuple[str, str, int], _BucketCost] = {}
        # (model, version, wave bucket, chunk) -> _WaveCost.
        self._waves: dict[tuple[str, str, int, int], _WaveCost] = {}
        # (end_mono_ns, device_ns) of warm executions inside the window.
        self._busy: deque[tuple[int, int]] = deque()
        self._bound: dict[int, _Bound] = {}

    # -- metric binding ------------------------------------------------------

    def bind_metrics(self, registry) -> None:
        """Declare the profiler's metric families on an engine's
        MetricRegistry and mirror every later observation into it.
        Idempotent per registry; multiple engines may bind; dead
        registries are pruned on the next record."""
        b = _Bound(registry)
        with self._lock:
            self._bound[id(registry)] = b

    def _bindings(self) -> list[_Bound]:
        with self._lock:
            out = []
            for rid, b in list(self._bound.items()):
                if b.registry_ref() is None:
                    del self._bound[rid]
                else:
                    out.append(b)
            return out

    # -- recording (the hot path) -------------------------------------------

    def record_execution(self, model: str, version, bucket: int | None,
                         rows: int, device_ns: int, host_ns: int = 0,
                         cold: bool = False, axis: str = "rows") -> None:
        """One device execution: ``rows`` real units padded up to
        ``bucket`` (None/0 = unbatched model, no padding), taking
        ``device_ns`` in the executable and ``host_ns`` in staging+fetch.
        ``axis`` names the padded unit — batch "rows" (default) or summed
        embedding "lookups" for ragged models; the accounting is the same,
        renderers use the tag. ``cold=True`` (first call, XLA traced)
        keeps the call/row counts but excludes the interval from
        device-time accumulation — it is compile, not load, and is
        accounted by :meth:`record_compile`."""
        key = (str(model), str(version), int(bucket or 0))
        rows = max(0, int(rows))
        padded = max(0, key[2] - rows) if key[2] else 0
        end = self._now()
        with self._lock:
            c = self._costs.get(key)
            if c is None:
                c = self._costs[key] = _BucketCost()
            c.axis = axis
            c.calls += 1
            c.rows += rows
            c.padded_rows += padded
            c.max_rows = max(c.max_rows, rows)
            c.touch(end, int(self.window_s * 1e9))
            if cold:
                c.cold_calls += 1
            else:
                c.device_ns += max(0, device_ns)
                c.host_ns += max(0, host_ns)
                c.device_ns_ewma = (
                    device_ns if c.device_ns_ewma == 0.0
                    else _EWMA_ALPHA * device_ns
                    + (1 - _EWMA_ALPHA) * c.device_ns_ewma)
                c.host_ns_ewma = (
                    host_ns if c.host_ns_ewma == 0.0
                    else _EWMA_ALPHA * host_ns
                    + (1 - _EWMA_ALPHA) * c.host_ns_ewma)
                self._busy.append((end, max(0, device_ns)))
                self._prune_locked(end)
            flops = 0.0
            if not cold and c.cost_model and c.cost_model.get("available"):
                flops = float(c.cost_model.get("flops", 0.0))
        fill = (rows / key[2]) if key[2] else 1.0
        for b in self._bindings():
            b.fill_ratio.observe(fill, model=key[0], version=key[1])
            if padded:
                b.padded_rows.inc(padded, model=key[0], version=key[1],
                                  bucket=str(key[2]))
            if not cold and device_ns > 0:
                b.device_seconds.inc(device_ns / 1e9,
                                     model=key[0], version=key[1])
            if flops > 0:
                b.model_flops.inc(flops, model=key[0], version=key[1],
                                  bucket=str(key[2]))

    def record_compile(self, model: str, version, bucket: int | None,
                       compile_ns: int, trace_id: str | None = None,
                       axis: str = "rows") -> None:
        """A first-call XLA trace finished: count it, observe its
        duration, and journal ``compile.finished``. ``axis`` tags the
        bucket's padded unit up front — warmup/tuner compiles are
        synthetic (no ``record_execution`` follows), so without it a
        warm-compiled lookup bucket would sit mislabelled "rows" until
        real traffic landed on it."""
        key = (str(model), str(version), int(bucket or 0))
        with self._lock:
            c = self._costs.get(key)
            if c is None:
                c = self._costs[key] = _BucketCost()
            c.axis = axis
            c.compile_count += 1
            c.compile_ns += max(0, compile_ns)
        for b in self._bindings():
            b.compilations.inc(model=key[0], version=key[1],
                               bucket=str(key[2]))
            b.compile_seconds.observe(compile_ns / 1e9,
                                      model=key[0], version=key[1])
        # Lazy import: observability.metrics users must not pull in the
        # journal (and its env wiring) just by importing this module.
        from client_tpu.observability.events import journal

        journal().emit("compile", "finished", model=key[0],
                       version=key[1], trace_id=trace_id,
                       bucket=key[2], compile_s=round(compile_ns / 1e9, 3))

    def record_cost_model(self, model: str, version, bucket: int | None,
                          cost: dict | None, axis: str = "rows") -> None:
        """Attach the static XLA cost model captured for a bucket's
        executable (:func:`client_tpu.observability.roofline.
        capture_cost_model`, called once per first-call trace alongside
        :meth:`record_compile`). An available capture always replaces a
        prior one (recompile = new executable); an *unavailable* capture
        only fills an empty slot — a bucket serving multiple signatures
        keeps its working cost model even if one exotic signature's
        analysis fails."""
        if not cost:
            return
        key = (str(model), str(version), int(bucket or 0))
        with self._lock:
            c = self._costs.get(key)
            if c is None:
                c = self._costs[key] = _BucketCost()
            c.axis = axis
            if cost.get("available") or c.cost_model is None:
                c.cost_model = dict(cost)

    def record_wave_cost_model(self, model: str, version, bucket: int,
                               chunk: int, cost: dict | None) -> None:
        """Same contract as :meth:`record_cost_model` for a decode-wave
        executable — the cost prices one *dispatch* (all ``chunk``
        scanned waves), matching _WaveCost.dispatches."""
        if not cost:
            return
        key = (str(model), str(version), int(bucket), max(1, int(chunk)))
        with self._lock:
            w = self._waves.get(key)
            if w is None:
                w = self._waves[key] = _WaveCost()
            if cost.get("available") or w.cost_model is None:
                w.cost_model = dict(cost)

    def record_wave(self, model: str, version, bucket: int, chunk: int,
                    duration_ns: int, waves: int = 1) -> None:
        """One generative decode dispatch completed: ``waves`` logical
        wave steps (``chunk`` > 1 when a scanned K-chunk) over a
        ``bucket``-lane executable took ``duration_ns`` of device
        occupancy.  Feeds ``tpu_decode_wave_seconds`` (one observation per
        logical wave, at the per-wave time), the snapshot's decode-wave
        table, and the duty-cycle window — generative waves never pass
        through ``Model.execute_timed``, so without this the busiest
        engine in the fleet read as idle."""
        key = (str(model), str(version), int(bucket), max(1, int(chunk)))
        waves = max(1, int(waves))
        duration_ns = max(0, int(duration_ns))
        per_wave_ns = duration_ns / waves
        end = self._now()
        with self._lock:
            w = self._waves.get(key)
            if w is None:
                w = self._waves[key] = _WaveCost()
            w.waves += waves
            w.dispatches += 1
            w.device_ns += duration_ns
            w.wave_ns_ewma = (
                per_wave_ns if w.wave_ns_ewma == 0.0
                else _EWMA_ALPHA * per_wave_ns
                + (1 - _EWMA_ALPHA) * w.wave_ns_ewma)
            w.recent.append(per_wave_ns)
            self._busy.append((end, duration_ns))
            self._prune_locked(end)
            flops = 0.0
            if w.cost_model and w.cost_model.get("available"):
                flops = float(w.cost_model.get("flops", 0.0))
        per_wave_s = per_wave_ns / 1e9
        for b in self._bindings():
            for _ in range(waves):
                b.wave_seconds.observe(per_wave_s, model=key[0],
                                       version=key[1], bucket=str(key[2]),
                                       chunk=str(key[3]))
            if flops > 0:
                b.model_flops.inc(flops, model=key[0], version=key[1],
                                  bucket=str(key[2]))

    # -- duty cycle ----------------------------------------------------------

    def _prune_locked(self, now: int) -> None:
        horizon = now - int(self.window_s * 1e9)
        while self._busy and self._busy[0][0] < horizon:
            self._busy.popleft()

    def duty_cycle(self) -> float:
        """Busy device time / wall time over the sliding window. Intervals
        straddling the window edge contribute their overlap only."""
        now = self._now()
        window_ns = int(self.window_s * 1e9)
        start = now - window_ns
        with self._lock:
            self._prune_locked(now)
            busy = 0
            for end, dur in self._busy:
                busy += min(end, now) - max(end - dur, start)
        wall = min(window_ns, max(1, now - self._t0))
        return busy / wall

    def update_gauges(self) -> None:
        """Refresh ``tpu_device_duty_cycle`` and the per-bucket
        ``tpu_mfu`` / ``tpu_mbu`` gauges on every bound registry; called
        at scrape time so a quiet period still reads current. MFU/MBU
        rows exist only where both the cost model and the device peaks
        are known — an unknown-peaks CPU host scrapes the (empty)
        families cleanly rather than lying with zeros."""
        duty = self.duty_cycle()
        rows = self._utilization_rows()
        for b in self._bindings():
            b.duty_cycle.set(round(duty, 6))
            for model, version, bucket, mfu, mbu in rows:
                if mfu is not None:
                    b.mfu.set(round(mfu, 6), model=model, version=version,
                              bucket=bucket)
                if mbu is not None:
                    b.mbu.set(round(mbu, 6), model=model, version=version,
                              bucket=bucket)

    def _utilization_rows(self) -> list[tuple]:
        """(model, version, bucket, mfu, mbu) for every bucket with an
        available cost model and warm device time; wave cells aggregate
        across chunks into their lane bucket. Empty when peaks are
        unknown (CPU host without a CLIENT_TPU_ROOFLINE override)."""
        peaks = _roofline.resolve_peaks()
        if peaks is None or not (peaks.flops_per_s or peaks.bytes_per_s):
            return []
        agg: dict[tuple[str, str, str], list[float]] = {}
        with self._lock:
            for (mname, version, bucket), c in self._costs.items():
                warm = c.calls - c.cold_calls
                if warm <= 0 or c.device_ns <= 0:
                    continue
                if not (c.cost_model and c.cost_model.get("available")):
                    continue
                row = agg.setdefault((mname, version, str(bucket)),
                                     [0.0, 0.0, 0.0])
                row[0] += float(c.cost_model.get("flops", 0.0)) * warm
                row[1] += float(
                    c.cost_model.get("bytes_accessed", 0.0)) * warm
                row[2] += c.device_ns / 1e9
            for (mname, version, bucket, _chunk), w in self._waves.items():
                if w.dispatches <= 0 or w.device_ns <= 0:
                    continue
                if not (w.cost_model and w.cost_model.get("available")):
                    continue
                row = agg.setdefault((mname, version, str(bucket)),
                                     [0.0, 0.0, 0.0])
                row[0] += float(
                    w.cost_model.get("flops", 0.0)) * w.dispatches
                row[1] += float(
                    w.cost_model.get("bytes_accessed", 0.0)) * w.dispatches
                row[2] += w.device_ns / 1e9
        out = []
        for (mname, version, bucket), (flops, byts, dev_s) in agg.items():
            if dev_s <= 0:
                continue
            mfu = (flops / dev_s / peaks.flops_per_s) \
                if peaks.flops_per_s else None
            mbu = (byts / dev_s / peaks.bytes_per_s) \
                if peaks.bytes_per_s else None
            if mfu is not None or mbu is not None:
                out.append((mname, version, bucket, mfu, mbu))
        return out

    # -- report ---------------------------------------------------------------

    def snapshot(self, model: str | None = None) -> dict:
        """The ``GET /v2/profile`` body: per-model/per-bucket cost table
        with padding-waste estimates and a bucket-ladder suggestion."""
        now = self._now()
        ctx = _roofline.roofline_context()
        peaks_dict = ctx.get("peaks")
        peaks = None
        if isinstance(peaks_dict, dict):
            peaks = _roofline.PeakSpec(peaks_dict.get("flops_per_s"),
                                       peaks_dict.get("bytes_per_s"),
                                       peaks_dict.get("source", "registry"))
        with self._lock:
            items = sorted(self._costs.items())
            wave_items = sorted(
                (k, (w.waves, w.device_ns, w.wave_ns_ewma,
                     sorted(w.recent), w.dispatches, w.cost_model))
                for k, w in self._waves.items())
        models: dict[str, dict] = {}
        # Per-model roofline accumulators: [flops, bytes, wasted_flops,
        # covered_device_s] summed over buckets+waves with cost models.
        roofline_agg: dict[str, list[float]] = {}

        def model_entry(mname: str, version: str) -> dict:
            mkey = f"{mname}:{version}"
            entry = models.get(mkey)
            if entry is None:
                entry = models[mkey] = {
                    "model": mname, "version": version,
                    "device_s": 0.0, "host_s": 0.0,
                    "padding_waste_device_s": 0.0,
                    "compilations": 0, "compile_s": 0.0,
                    "buckets": [], "suggestion": None,
                    "suggestions": [],
                }
                roofline_agg[mkey] = [0.0, 0.0, 0.0, 0.0]
            return entry

        def accumulate_roofline(mkey: str, rl: dict,
                                device_s: float) -> None:
            if rl.get("cost_model") != "xla":
                return
            agg = roofline_agg[mkey]
            agg[0] += rl["total_flops"]
            agg[1] += rl["total_bytes"]
            agg[2] += rl["padding_wasted_flops"]
            agg[3] += device_s

        for (mname, version, bucket), c in items:
            if model and mname != model:
                continue
            entry = model_entry(mname, version)
            waste = c.padding_waste_device_s()
            entry["device_s"] += c.device_ns / 1e9
            entry["host_s"] += c.host_ns / 1e9
            entry["padding_waste_device_s"] += waste
            entry["compilations"] += c.compile_count
            entry["compile_s"] += c.compile_ns / 1e9
            warm = c.calls - c.cold_calls
            total_rows = c.rows + c.padded_rows
            rl = _roofline.bucket_roofline(
                c.cost_model, warm, c.device_ns / 1e9,
                (c.padded_rows / total_rows) if total_rows else 0.0,
                peaks)
            accumulate_roofline(f"{mname}:{version}", rl,
                                c.device_ns / 1e9)
            entry["buckets"].append({
                "bucket": bucket,
                "axis": c.axis,
                "roofline": rl,
                "executions": c.calls,
                "cold_executions": c.cold_calls,
                "rows": c.rows,
                "padded_rows": c.padded_rows,
                "max_rows": c.max_rows,
                "fill_ratio": round(c.fill_ratio(), 4),
                "device_s": round(c.device_ns / 1e9, 6),
                "host_s": round(c.host_ns / 1e9, 6),
                "device_s_per_call_ewma": round(c.device_ns_ewma / 1e9, 6),
                "host_s_per_call_ewma": round(c.host_ns_ewma / 1e9, 6),
                "padding_waste_device_s": round(waste, 6),
                "compilations": c.compile_count,
                "compile_s": round(c.compile_ns / 1e9, 6),
                "calls_per_min": round(c.calls_per_min(now), 3),
                "observed_s": round(
                    (now - c.first_seen) / 1e9 if c.first_seen else 0.0, 3),
            })
        # Generative decode waves (record_wave): per (bucket, chunk) wave
        # step times.  Wave device time also counts into the model's
        # device_s total — generative engines never pass execute_timed,
        # so without this their models profile as idle.
        for (mname, version, bucket, chunk), \
                (wv, dns, ewma, recent, dispatches, wcost) in wave_items:
            if model and mname != model:
                continue
            entry = model_entry(mname, version)
            entry["device_s"] += dns / 1e9
            rl = _roofline.bucket_roofline(wcost, dispatches, dns / 1e9,
                                           0.0, peaks)
            accumulate_roofline(f"{mname}:{version}", rl, dns / 1e9)

            def pct(q: float) -> float:
                if not recent:
                    return 0.0
                return recent[min(len(recent) - 1, int(q * len(recent)))]

            entry.setdefault("decode_waves", []).append({
                "bucket": bucket,
                "chunk": chunk,
                "waves": wv,
                "dispatches": dispatches,
                "device_s": round(dns / 1e9, 6),
                "wave_ms_ewma": round(ewma / 1e6, 3),
                "wave_ms_p50": round(pct(0.5) / 1e6, 3),
                "wave_ms_p99": round(pct(0.99) / 1e6, 3),
                "roofline": rl,
            })
        for mkey, entry in models.items():
            entry["device_s"] = round(entry["device_s"], 6)
            entry["host_s"] = round(entry["host_s"], 6)
            entry["compile_s"] = round(entry["compile_s"], 6)
            entry["padding_waste_device_s"] = round(
                entry["padding_waste_device_s"], 6)
            entry["suggestion"] = _suggest_bucket_tweak(entry["buckets"])
            entry["suggestions"] = _suggest_ladder_tweaks(
                entry["buckets"], self.window_s)
            entry["roofline"] = _model_roofline(
                roofline_agg[mkey], entry["device_s"], peaks)
        return {
            "window_s": self.window_s,
            "duty_cycle": round(self.duty_cycle(), 6),
            "roofline": ctx,
            "models": models,
        }

    def reset(self) -> None:
        """Drop accumulated costs (tests); metric bindings survive."""
        with self._lock:
            self._costs.clear()
            self._waves.clear()
            self._busy.clear()
            self._t0 = self._now()


def _model_roofline(agg: list[float], device_s: float, peaks) -> dict:
    """Model-level roofline rollup from the per-bucket accumulators
    (flops, bytes, padding-wasted flops, covered device seconds).
    ``cost_model_coverage`` is the fraction of the model's device time
    whose executables carry a cost model — the honesty knob: a 0.4
    coverage MFU describes 40% of the time, not the model."""
    flops, byts, wasted, covered_s = agg
    out = {
        "total_flops": flops,
        "total_bytes": byts,
        "padding_wasted_flops": wasted,
        "cost_model_coverage": round(covered_s / device_s, 4)
        if device_s > 0 else 0.0,
        "achieved_flops_per_s": None,
        "achieved_bytes_per_s": None,
        "arithmetic_intensity": None,
        "mfu": None,
        "mbu": None,
        "bound": "unknown",
    }
    if covered_s <= 0:
        return out
    achieved_f = flops / covered_s
    achieved_b = byts / covered_s
    intensity = (flops / byts) if byts > 0 else None
    out["achieved_flops_per_s"] = achieved_f
    out["achieved_bytes_per_s"] = achieved_b
    out["arithmetic_intensity"] = round(intensity, 4) \
        if intensity is not None else None
    out["bound"] = _roofline.classify_bound(intensity, peaks)
    if peaks and peaks.flops_per_s:
        out["mfu"] = round(achieved_f / peaks.flops_per_s, 6)
    if peaks and peaks.bytes_per_s:
        out["mbu"] = round(achieved_b / peaks.bytes_per_s, 6)
    return out


def _suggest_bucket_tweak(buckets: list[dict]) -> dict | None:
    """Greedy ladder tweak: the bucket wasting the most device time on
    padding, with enough calls to trust its fill ratio and headroom below
    it (max observed rows < bucket), suggests inserting a bucket at the
    observed row high-water mark. Returns None when the ladder looks
    right-sized."""
    worst = None
    for b in buckets:
        if b["bucket"] <= 1 or b["executions"] < _SUGGEST_MIN_CALLS:
            continue
        if b["fill_ratio"] >= _SUGGEST_MAX_FILL:
            continue
        if b["max_rows"] >= b["bucket"]:
            continue
        if worst is None or (b["padding_waste_device_s"]
                             > worst["padding_waste_device_s"]):
            worst = b
    if worst is None:
        return None
    suggested = max(1, worst["max_rows"])
    # Executable time scales ~linearly with bucket rows on TPU, so
    # re-landing these executions on the smaller bucket saves the row
    # fraction of their device time.
    saving = worst["device_s"] * (1 - suggested / worst["bucket"])
    return {
        "action": "add_bucket",
        "bucket": suggested,
        "below": worst["bucket"],
        "fill_ratio": worst["fill_ratio"],
        "est_saving_device_s": round(saving, 6),
        "reason": (f"bucket {worst['bucket']} ran {worst['executions']} "
                   f"executions at {worst['fill_ratio']:.0%} fill "
                   f"(max {worst['max_rows']} real rows); a "
                   f"{suggested}-row bucket would absorb them"),
    }


def _suggest_ladder_tweaks(buckets: list[dict],
                           window_s: float) -> list[dict]:
    """The full suggestion list the autotuner acts on: the greedy
    ``add_bucket`` (same semantics as :func:`_suggest_bucket_tweak`) plus
    one ``retire_bucket`` per cold bucket — tracked for at least a full
    profile window yet called below :data:`_SUGGEST_RETIRE_RATE_PER_MIN`.
    The largest tracked bucket is never suggested for retirement (the
    ladder must keep covering max_batch_size); the tuner re-validates
    against the actual configured ladder before acting."""
    out: list[dict] = []
    add = _suggest_bucket_tweak(buckets)
    if add is not None:
        out.append(add)
    largest = max((b["bucket"] for b in buckets), default=0)
    for b in buckets:
        if b["bucket"] < 1 or b["bucket"] >= largest:
            continue
        if b.get("observed_s", 0.0) < window_s:
            continue  # too young: absence of calls is not yet evidence
        rate = b.get("calls_per_min", 0.0)
        if rate >= _SUGGEST_RETIRE_RATE_PER_MIN:
            continue
        out.append({
            "action": "retire_bucket",
            "bucket": b["bucket"],
            "calls_per_min": rate,
            "reason": (f"bucket {b['bucket']} saw "
                       f"{rate:.2f} calls/min over the last "
                       f"{window_s:.0f}s window (floor "
                       f"{_SUGGEST_RETIRE_RATE_PER_MIN})"),
        })
    return out


# -- process-global default profiler ------------------------------------------

_default: EfficiencyProfiler | None = None
_default_lock = lockdep.Lock("observability.profiler.default")


def profiler() -> EfficiencyProfiler:
    """The process-global profiler (double-checked, like
    :func:`client_tpu.observability.events.journal`): models record into
    it from below the engine; engines bind their metric registries to it
    from above."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = EfficiencyProfiler()
    return _default


def reset_profiler() -> None:
    """Drop the global profiler (tests); the next profiler() recreates it
    with current env settings."""
    global _default
    with _default_lock:
        _default = None
