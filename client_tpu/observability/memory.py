"""HBM census: byte-accurate attribution of live device memory.

The planning arena (:mod:`client_tpu.engine.arena`) *reserves* HBM but
never places buffers, and the per-device gauges only report raw
``memory_stats()`` totals — so nothing could say which model owns which
live bytes, or whether the planner's reservations match reality. The
census closes both gaps:

- **Owner tagging** — load paths register the device buffers they
  create (model weights via :class:`~client_tpu.engine.model.Model`,
  generative KV arenas, DLRM embedding tables, autotune warm buffers)
  against an ``(model, component)`` owner. Registration is weak: a
  freed buffer drops out of the census on the next walk, never pinned.
- **The walk** — :meth:`HbmCensus.report` sums live tagged bytes per
  owner, reads ``device.memory_stats()`` per device (zeros on CPU,
  matching the long-standing gauge behavior), totals
  ``jax.live_arrays()`` as the platform-independent committed-bytes
  figure, and buckets the remainder as ``unattributed``.
- **Plan reconciliation** — planner arenas registered by the autotuner
  are reconciled reservation-by-reservation against the census actuals:
  ``drift_bytes = plan - actual`` per owner (positive = the planner
  reserved more than is live; negative = live memory the plan never
  charged).

Rendered as ``tpu_hbm_census_bytes{model,component}`` /
``tpu_hbm_plan_drift_bytes{model,component}`` plus watermark gauges,
served at ``GET /v2/memory`` and summarized in ``/v2/profile``.
Crossing the pressure threshold (``CLIENT_TPU_MEMORY``, default 90% of
``bytes_limit``) emits an edge-triggered ``memory.pressure`` journal
event.
"""

from __future__ import annotations

import json
import os
from client_tpu import config as envcfg
from client_tpu.utils import lockdep
import weakref
from dataclasses import dataclass, fields

__all__ = [
    "COMPONENTS",
    "MemoryConfig",
    "HbmCensus",
    "hbm_census",
    "reset_hbm_census",
]

ENV_VAR = "CLIENT_TPU_MEMORY"

# The owner vocabulary load paths tag with. Free-form strings are
# accepted (future components shouldn't need a census edit), but these
# are the wired ones.
COMPONENTS = ("weights", "kv_arena", "embedding", "rowcache",
              "autotune_warm")

# Arena reservation-name prefixes -> census component, for plan
# reconciliation (see Autotuner._reserve_ladder for the name grammar:
# "bucket:{model}:{version}:{b}", "kv:{model}:{version}", ...).
_PLAN_COMPONENTS = {
    "bucket": "autotune_warm",
    "kv": "kv_arena",
    "rowcache": "rowcache",
}


@dataclass
class MemoryConfig:
    """``CLIENT_TPU_MEMORY`` knobs (grammar matches CLIENT_TPU_AUTOTUNE
    except unset means defaults — the census is always on; ``0``/``off``
    only silences pressure events)."""

    pressure_fraction: float = 0.9   # bytes_in_use/bytes_limit threshold
    pressure_events: bool = True

    @classmethod
    def from_dict(cls, data: dict) -> "MemoryConfig":
        known = {f.name for f in fields(cls) if f.name != "pressure_events"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        cfg = cls()
        if "pressure_fraction" in data:
            try:
                cfg.pressure_fraction = float(data["pressure_fraction"])
            except (TypeError, ValueError):
                raise ValueError(
                    f"{ENV_VAR}: key 'pressure_fraction' expects a "
                    f"number, got {data['pressure_fraction']!r}") from None
        if not 0 < cfg.pressure_fraction <= 1:
            raise ValueError(
                f"{ENV_VAR}: pressure_fraction must be in (0, 1]")
        return cfg

    @classmethod
    def from_env(cls, environ=os.environ) -> "MemoryConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if raw.lower() in ("0", "false", "off"):
            return cls(pressure_events=False)
        if not raw or raw.lower() in ("1", "true", "on"):
            return cls()
        if raw.startswith("@"):
            try:
                with open(raw[1:]) as f:
                    raw = f.read()
            except OSError as exc:
                raise ValueError(
                    f"{ENV_VAR}: cannot read '{raw[1:]}': {exc}") from None
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{ENV_VAR}: invalid JSON ({exc})") from None
        if not isinstance(data, dict):
            raise ValueError(f"{ENV_VAR}: expected a JSON object")
        return cls.from_dict(data)


def _buffer_nbytes(buf) -> int:
    """Committed bytes of one device array: per-device shard size times
    addressable device count (a replicated array really holds one copy
    per device), falling back to the logical nbytes when sharding
    introspection is unavailable. Computed from sharding *metadata* on
    purpose: materializing ``shard.data`` would mint a new jax.Array per
    shard per walk — the census must never allocate what it counts."""
    try:
        sharding = buf.sharding
        shard_shape = sharding.shard_shape(buf.shape)
        n_dev = len(sharding.addressable_devices)
        per_shard = int(buf.dtype.itemsize)
        for dim in shard_shape:
            per_shard *= int(dim)
        return per_shard * n_dev
    # tpulint: allow[swallowed-exception] non-jax leaves, odd shardings
    except Exception:  # noqa: BLE001 — non-jax leaves, odd shardings
        pass
    try:
        return int(buf.nbytes)
    except Exception:  # noqa: BLE001
        return 0


class HbmCensus:
    """Registration-tag map + the census walk. Process-global (load
    paths run below the engine and must find it without plumbing);
    :func:`reset_hbm_census` drops it between tests."""

    def __init__(self, config: MemoryConfig | None = None):
        self.config = config or MemoryConfig()
        self._lock = lockdep.Lock("observability.memory")
        # id(buffer) -> (weakref, model, component). Keyed by id because
        # jax.Arrays are unhashable; the weakref both detects death and
        # guards against id reuse (a dead ref's entry is pruned before a
        # recycled id could collide).
        self._tags: dict[int, tuple[weakref.ref, str, str]] = {}
        # Dynamic owners whose buffers are continuously replaced (donated
        # KV arenas): id(owner) -> (weakref, model, component, fn) where
        # fn(owner) -> (bytes, buffers). fn must be a plain function (no
        # closure over the owner — the census must not keep it alive).
        self._providers: dict[int, tuple] = {}
        # Planner arenas (ArenaAllocator) registered by the autotuner,
        # weakly so a stopped tuner's arena ages out.
        self._arenas: list[weakref.ref] = []
        self._watermark = 0          # high-water bytes_in_use (or live)
        self._pressured = False      # edge-trigger latch

    # -- registration ---------------------------------------------------------

    def tag(self, model: str, component: str, tree, *,
            overwrite: bool = True) -> int:
        """Attribute every weakref-able leaf of ``tree`` (a pytree /
        list / single array) to ``(model, component)``. Re-tagging a
        buffer overwrites its owner unless ``overwrite=False`` — the
        generic weights pass in ``Model.__init__`` passes False so a
        more specific tag placed during ``make_apply_params`` (DLRM's
        ``embedding`` tables) survives it. Returns the number of
        buffers registered."""
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(tree)
        except Exception:  # noqa: BLE001 — jax-less callers pass lists
            leaves = tree if isinstance(tree, (list, tuple)) else [tree]
        count = 0
        with self._lock:
            for leaf in leaves:
                try:
                    ref = weakref.ref(leaf)
                except TypeError:
                    continue  # ints, numpy scalars: not device buffers
                prior = self._tags.get(id(leaf))
                if (not overwrite and prior is not None
                        and prior[0]() is leaf):
                    continue
                self._tags[id(leaf)] = (ref, str(model), str(component))
                count += 1
        return count

    def untag(self, model: str | None = None,
              component: str | None = None) -> int:
        """Drop tags by owner (unload paths); None matches everything."""
        with self._lock:
            victims = [
                key for key, (_, m, c) in self._tags.items()
                if (model is None or m == model)
                and (component is None or c == component)]
            for key in victims:
                del self._tags[key]
        return len(victims)

    def register_provider(self, model: str, component: str, owner,
                          fn) -> None:
        """Dynamic attribution for owners whose buffers are replaced on
        every step (donated KV arenas outlive no two waves, so static
        tags would die instantly). ``fn(owner) -> (bytes, buffers)`` is
        called at walk time while ``owner`` is alive; it must be a plain
        function taking the owner, never a closure over it (the census
        holds the owner weakly and must not pin it). Idempotent per
        owner identity."""
        with self._lock:
            self._providers[id(owner)] = (
                weakref.ref(owner), str(model), str(component), fn)

    def unregister_provider(self, owner) -> None:
        with self._lock:
            self._providers.pop(id(owner), None)

    def register_arena(self, arena) -> None:
        """Register a planner :class:`ArenaAllocator` for plan-vs-actual
        reconciliation (idempotent per arena identity)."""
        with self._lock:
            self._arenas = [r for r in self._arenas
                            if r() is not None and r() is not arena]
            self._arenas.append(weakref.ref(arena))

    def unregister_arena(self, arena) -> None:
        with self._lock:
            self._arenas = [r for r in self._arenas
                            if r() is not None and r() is not arena]

    # -- the walk -------------------------------------------------------------

    def device_stats(self) -> list[dict]:
        """Per-device memory stats, one entry per local device; zeros
        where the platform reports none (CPU) — the single source of
        truth behind the ``tpu_device_hbm_bytes_in_use`` /
        ``tpu_hbm_limit_bytes`` / ``tpu_hbm_peak_bytes`` gauges. Empty
        when no backend is reachable at all."""
        out: list[dict] = []
        try:
            import jax

            for d in jax.local_devices():
                try:
                    ms = d.memory_stats()
                except Exception:  # noqa: BLE001 — per-device probe
                    ms = None
                ms = ms or {}
                out.append({
                    "device": str(d.id),
                    "platform": getattr(d, "platform", "unknown"),
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                    "peak_bytes_in_use": int(ms.get("peak_bytes_in_use",
                                                    0)),
                })
        except Exception:  # noqa: BLE001 — no backend at all
            return []
        return out

    def _attributed(self) -> dict[tuple[str, str], dict]:
        """{(model, component): {"bytes": n, "buffers": k}} over live
        tagged buffers; dead tags pruned as a side effect."""
        with self._lock:
            items = list(self._tags.items())
        owners: dict[tuple[str, str], dict] = {}
        dead = []
        for key, (ref, model, component) in items:
            buf = ref()
            if buf is None:
                dead.append(key)
                continue
            nbytes = _buffer_nbytes(buf)
            row = owners.setdefault((model, component),
                                    {"bytes": 0, "buffers": 0})
            row["bytes"] += nbytes
            row["buffers"] += 1
        if dead:
            with self._lock:
                for key in dead:
                    self._tags.pop(key, None)
        with self._lock:
            providers = list(self._providers.items())
        for key, (ref, model, component, fn) in providers:
            obj = ref()
            if obj is None:
                with self._lock:
                    self._providers.pop(key, None)
                continue
            try:
                nbytes, buffers = fn(obj)
            # tpulint: allow[swallowed-exception] owner mid-teardown
            except Exception:  # noqa: BLE001 — owner mid-teardown
                continue
            row = owners.setdefault((model, component),
                                    {"bytes": 0, "buffers": 0})
            row["bytes"] += int(nbytes)
            row["buffers"] += int(buffers)
        return owners

    def _plans(self) -> dict[tuple[str, str], int]:
        """{(model, component): reserved bytes} from every registered
        planner arena, component mapped by reservation-name prefix."""
        with self._lock:
            arenas = [r() for r in self._arenas]
        plans: dict[tuple[str, str], int] = {}
        for arena in arenas:
            if arena is None:
                continue
            try:
                snap = arena.snapshot()
            # tpulint: allow[swallowed-exception] arena mid-teardown
            except Exception:  # noqa: BLE001 — arena mid-teardown
                continue
            for res in snap.get("reservations", ()):
                parts = str(res.get("name", "")).split(":")
                if len(parts) < 2:
                    continue
                component = _PLAN_COMPONENTS.get(parts[0])
                if component is None:
                    continue
                owner = (parts[1], component)
                plans[owner] = plans.get(owner, 0) + int(
                    res.get("nbytes", 0))
        return plans

    def report(self, extra_plans: dict | None = None,
               events=None) -> dict:
        """The ``GET /v2/memory`` body. ``extra_plans`` maps
        ``(model, component)`` to planned bytes from sources outside the
        arenas (e.g. DLRM's ``hbm_reservation_bytes``); ``events`` is an
        EventJournal for pressure emission (None = no events)."""
        devices = self.device_stats()
        total_in_use = sum(d["bytes_in_use"] for d in devices)
        total_limit = sum(d["bytes_limit"] for d in devices)
        total_peak = sum(d["peak_bytes_in_use"] for d in devices)
        live_bytes = 0
        live_count = 0
        try:
            import jax

            for arr in jax.live_arrays():
                live_bytes += _buffer_nbytes(arr)
                live_count += 1
        # tpulint: allow[swallowed-exception] no backend
        except Exception:  # noqa: BLE001 — no backend
            pass
        # On platforms without memory stats (CPU) the live-array total is
        # the honest committed-bytes figure; on TPU bytes_in_use also
        # covers allocator overhead the census attributes as slack.
        committed = total_in_use if total_in_use > 0 else live_bytes

        attributed = self._attributed()
        plans = self._plans()
        for owner, nbytes in (extra_plans or {}).items():
            plans[owner] = plans.get(owner, 0) + int(nbytes)

        owners = []
        attributed_bytes = 0
        for owner in sorted(set(attributed) | set(plans)):
            actual = attributed.get(owner, {"bytes": 0, "buffers": 0})
            plan = plans.get(owner)
            attributed_bytes += actual["bytes"]
            row = {
                "model": owner[0],
                "component": owner[1],
                "bytes": actual["bytes"],
                "buffers": actual["buffers"],
            }
            if plan is not None:
                row["plan_bytes"] = plan
                row["drift_bytes"] = plan - actual["bytes"]
            owners.append(row)
        unattributed = max(0, committed - attributed_bytes)
        fraction = (attributed_bytes / committed) if committed else 1.0

        watermark_src = committed
        with self._lock:
            if watermark_src > self._watermark:
                self._watermark = watermark_src
            watermark = self._watermark

        pressure = None
        if total_limit > 0:
            used_fraction = total_in_use / total_limit
            pressure = {
                "fraction": round(used_fraction, 6),
                "threshold": self.config.pressure_fraction,
                "over": used_fraction >= self.config.pressure_fraction,
            }
            self._pressure_edge(pressure, total_in_use, total_limit,
                                events)
        return {
            "devices": devices,
            "totals": {
                "bytes_in_use": total_in_use,
                "bytes_limit": total_limit,
                "peak_bytes_in_use": total_peak,
                "live_array_bytes": live_bytes,
                "live_arrays": live_count,
                "committed_bytes": committed,
            },
            "owners": owners,
            "attributed_bytes": attributed_bytes,
            "unattributed_bytes": unattributed,
            "attributed_fraction": round(fraction, 6),
            "watermark_bytes": watermark,
            "pressure": pressure,
        }

    def _pressure_edge(self, pressure: dict, in_use: int, limit: int,
                       events) -> None:
        """Edge-triggered ``memory.pressure`` journal events: one on
        crossing the threshold upward, one ``pressure_cleared`` on the
        way back down — never one per scrape."""
        if not self.config.pressure_events or events is None:
            return
        over = pressure["over"]
        with self._lock:
            was = self._pressured
            self._pressured = over
        if over and not was:
            events.emit("memory", "pressure", severity="WARNING",
                        bytes_in_use=in_use, bytes_limit=limit,
                        fraction=pressure["fraction"],
                        threshold=pressure["threshold"])
        elif was and not over:
            events.emit("memory", "pressure_cleared",
                        bytes_in_use=in_use, bytes_limit=limit,
                        fraction=pressure["fraction"])


# -- process-global census -----------------------------------------------------

_default: HbmCensus | None = None
_default_lock = lockdep.Lock("observability.memory.default")


def hbm_census() -> HbmCensus:
    """The process-global census (double-checked, like
    :func:`client_tpu.observability.events.journal`): load paths tag
    into it from below the engine, the engine reads reports out of it."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HbmCensus(MemoryConfig.from_env())
    return _default


def reset_hbm_census() -> None:
    """Drop the global census (tests); the next :func:`hbm_census` call
    recreates it with current env settings."""
    global _default
    with _default_lock:
        _default = None
