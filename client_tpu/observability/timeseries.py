"""Flight recorder: a process-global 1 Hz time-series sampler.

Every control loop in the stack (autotuner, fleet drift monitor, SLO
burn alerting) acts on *instantaneous* scrapes today; nothing can answer
"what did duty cycle / queue depth / HBM do over the last ten minutes".
The flight recorder closes that gap with one daemon thread sampling a
small signal vocabulary once per second into a bounded ring:

- ``duty_cycle`` — busy-device fraction (efficiency profiler window);
- ``queue_depth`` / ``in_flight`` — per-model scheduler backlog and
  batches executing on device;
- ``batch_fill`` — per-model EWMA of the padded-batch fill ratio;
- ``shed_rate`` — per-model admission sheds per second (counter delta);
- ``wave_p50_ms`` — per-model generative decode-wave p50;
- ``hbm_used`` / ``hbm_reserved`` — device bytes in use (HBM census)
  vs planner arena reservations;
- ``slo_burn`` — per-model fast-window availability burn rate.

The recorder is process-global like the fault registry and the event
journal: engines *attach* themselves (weakly — a shut-down engine is
pruned, never keeps sampling) and contribute one sample dict per tick
through ``timeseries_sample()``. Export mirrors the event journal's
cursor contract: a monotonically increasing ``seq`` per sample,
``since=`` exclusive, ``next_seq`` to resume, ``dropped`` counting ring
overwrites.

``CLIENT_TPU_TIMESERIES`` sizes or disables the recorder (grammar
matches CLIENT_TPU_AUTOTUNE, except unset means *enabled with
defaults* — flight recording is meant to be always on): ``0``/``off``
disables, ``1``/``on``/unset takes defaults (1 Hz, 900-sample ≈ 15 min
ring), else inline JSON or ``@file`` with ``interval_s`` / ``capacity``
keys. Served as ``GET /v2/timeseries?signal=&model=&since=`` and
federated by the router as ``/v2/fleet/timeseries``.
"""

from __future__ import annotations

import json
import logging
import os
from client_tpu import config as envcfg
import threading
from client_tpu.utils import lockdep
import time
import weakref
from collections import deque
from dataclasses import dataclass, fields

__all__ = [
    "SIGNALS",
    "SCALAR_SIGNALS",
    "TimeseriesConfig",
    "FlightRecorder",
    "recorder",
    "reset_recorder",
]

ENV_VAR = "CLIENT_TPU_TIMESERIES"

# Per-model signals carry {model: value} maps; scalar signals one float.
# (`tenant_cost_rate` reuses the map machinery with TENANT keys: each
# tenant's device-seconds-per-second — its share of device occupancy —
# from cost-ledger deltas.)
SCALAR_SIGNALS = ("duty_cycle", "hbm_used", "hbm_reserved",
                  "qos_throttled")
MODEL_SIGNALS = ("queue_depth", "in_flight", "batch_fill", "shed_rate",
                 "wave_p50_ms", "slo_burn", "tenant_cost_rate", "mfu")
SIGNALS = SCALAR_SIGNALS + MODEL_SIGNALS


@dataclass
class TimeseriesConfig:
    """``CLIENT_TPU_TIMESERIES`` knobs. Unlike the opt-in subsystems the
    recorder defaults ON: unset takes defaults, ``0``/``off`` disables."""

    interval_s: float = 1.0   # sampling period
    capacity: int = 900       # ring size in samples (~15 min at 1 Hz)
    enabled: bool = True

    @classmethod
    def from_dict(cls, data: dict) -> "TimeseriesConfig":
        known = {f.name for f in fields(cls) if f.name != "enabled"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        cfg = cls()
        if "interval_s" in data:
            try:
                cfg.interval_s = float(data["interval_s"])
            except (TypeError, ValueError):
                raise ValueError(
                    f"{ENV_VAR}: key 'interval_s' expects a number, "
                    f"got {data['interval_s']!r}") from None
        if "capacity" in data:
            try:
                cfg.capacity = int(data["capacity"])
            except (TypeError, ValueError):
                raise ValueError(
                    f"{ENV_VAR}: key 'capacity' expects an integer, "
                    f"got {data['capacity']!r}") from None
        if cfg.interval_s <= 0:
            raise ValueError(f"{ENV_VAR}: interval_s must be > 0")
        if cfg.capacity < 1:
            raise ValueError(f"{ENV_VAR}: capacity must be >= 1")
        return cfg

    @classmethod
    def from_env(cls, environ=os.environ) -> "TimeseriesConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if raw.lower() in ("0", "false", "off"):
            return cls(enabled=False)
        if not raw or raw.lower() in ("1", "true", "on"):
            return cls()
        if raw.startswith("@"):
            try:
                with open(raw[1:]) as f:
                    raw = f.read()
            except OSError as exc:
                raise ValueError(
                    f"{ENV_VAR}: cannot read '{raw[1:]}': {exc}") from None
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{ENV_VAR}: invalid JSON ({exc})") from None
        if not isinstance(data, dict):
            raise ValueError(f"{ENV_VAR}: expected a JSON object")
        return cls.from_dict(data)


class FlightRecorder:
    """Bounded ring of per-second signal samples over weakly-attached
    providers (engines). Thread-safe; the sampling thread starts lazily
    on the first :meth:`attach` and dies with the process (daemon)."""

    def __init__(self, config: TimeseriesConfig | None = None, *,
                 clock=time.time):
        self.config = config or TimeseriesConfig()
        self._clock = clock
        self._ring: deque = deque(maxlen=self.config.capacity)
        self._seq = 0
        self._dropped = 0
        self._lock = lockdep.Lock("timeseries.recorder")
        # id(provider) -> weakref; id keys survive unhashable providers
        # and give O(1) detach. Dead refs are pruned every tick.
        self._providers: dict[int, weakref.ref] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._tick_failures = 0

    # -- providers ------------------------------------------------------------

    def attach(self, provider) -> None:
        """Register a sample provider (an object with a zero-arg
        ``timeseries_sample() -> dict`` method) and make sure the
        sampling thread runs. Idempotent per provider identity; a no-op
        when the recorder is disabled."""
        if not self.config.enabled:
            return
        with self._lock:
            self._providers[id(provider)] = weakref.ref(provider)
        self.start()

    def detach(self, provider) -> None:
        with self._lock:
            self._providers.pop(id(provider), None)

    def providers(self) -> list:
        """Live providers (dead weakrefs pruned as a side effect)."""
        out = []
        with self._lock:
            for key in list(self._providers):
                obj = self._providers[key]()
                if obj is None:
                    del self._providers[key]
                else:
                    out.append(obj)
        return out

    # -- lifecycle ------------------------------------------------------------

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "FlightRecorder":
        """Start the sampling thread (idempotent; no-op when disabled)."""
        if not self.config.enabled or self.running():
            return self
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="flight-recorder", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the sampling thread (idempotent); the ring is kept."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the recorder must not die
                self._tick_failures += 1
                if self._tick_failures == 1:
                    logging.getLogger(
                        "client_tpu.timeseries").exception(
                        "flight-recorder tick failed (logged once; "
                        "further failures only counted)")

    # -- sampling -------------------------------------------------------------

    def tick(self) -> dict | None:
        """Take one sample across all live providers and append it to
        the ring. Also the test/offline entry point — callable without
        the thread. Returns the sample (None when disabled)."""
        if not self.config.enabled:
            return None
        signals: dict = {}
        for provider in self.providers():
            try:
                contributed = provider.timeseries_sample()
            # tpulint: allow[swallowed-exception] one sick provider must not stop the others from recording
            except Exception:  # noqa: BLE001 — one sick provider must
                continue       # not stop the others from recording
            if not contributed:
                continue
            for name, value in contributed.items():
                if name in SCALAR_SIGNALS:
                    # Co-resident engines share one device: take the max
                    # rather than double-counting the same HBM.
                    prev = signals.get(name)
                    signals[name] = (value if prev is None
                                     else max(prev, value))
                elif name in MODEL_SIGNALS and isinstance(value, dict):
                    signals.setdefault(name, {}).update(value)
        with self._lock:
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            sample = {"seq": self._seq, "ts_wall": self._clock(),
                      "signals": signals}
            self._ring.append(sample)
        return sample

    # -- export ---------------------------------------------------------------

    def export(self, *, signal: str | None = None,
               model: str | None = None,
               since_seq: int | None = None,
               since_wall: float | None = None,
               until_wall: float | None = None,
               limit: int | None = None) -> dict:
        """The ``GET /v2/timeseries`` body. ``signal`` narrows to one
        signal family, ``model`` narrows per-model maps to one model,
        ``since_seq`` is the exclusive cursor from the previous
        response's ``next_seq``, ``since_wall``/``until_wall`` bound the
        samples by wall stamp (exclusive lower, inclusive upper — "the
        60 s around this edge" without cursor arithmetic), ``limit``
        keeps the newest n samples. Unknown signal names raise
        ValueError (HTTP 400)."""
        if signal is not None and signal not in SIGNALS:
            raise ValueError(
                f"unknown signal {signal!r}; valid: {list(SIGNALS)}")
        with self._lock:
            samples = list(self._ring)
            next_seq = self._seq
            dropped = self._dropped
        if since_seq is not None:
            samples = [s for s in samples if s["seq"] > since_seq]
        if since_wall is not None:
            samples = [s for s in samples if s["ts_wall"] > since_wall]
        if until_wall is not None:
            samples = [s for s in samples if s["ts_wall"] <= until_wall]
        if limit is not None and limit >= 0:
            samples = samples[-limit:]
        out_samples = []
        for s in samples:
            sig = s["signals"]
            if signal is not None:
                sig = {signal: sig[signal]} if signal in sig else {}
            if model is not None:
                narrowed = {}
                for name, value in sig.items():
                    if isinstance(value, dict):
                        if model in value:
                            narrowed[name] = {model: value[model]}
                    else:
                        narrowed[name] = value
                sig = narrowed
            out_samples.append({"seq": s["seq"], "ts_wall": s["ts_wall"],
                                "signals": sig})
        return {
            "enabled": self.config.enabled,
            "interval_s": self.config.interval_s,
            "capacity": self.config.capacity,
            "signals": list(SIGNALS),
            "samples": out_samples,
            "next_seq": next_seq,
            "dropped": dropped,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# -- process-global recorder ---------------------------------------------------

_default: FlightRecorder | None = None
_default_lock = lockdep.Lock("timeseries.default")


def recorder() -> FlightRecorder:
    """The process-global flight recorder (double-checked, like
    :func:`client_tpu.observability.events.journal`); sized from
    ``CLIENT_TPU_TIMESERIES`` on first access."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder(TimeseriesConfig.from_env())
    return _default


def reset_recorder() -> None:
    """Stop and drop the global recorder (tests); the next
    :func:`recorder` call recreates it with current env settings."""
    global _default
    with _default_lock:
        if _default is not None:
            _default.stop()
        _default = None
