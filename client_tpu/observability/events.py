"""Structured operational event journal + opt-in JSON log sink.

PRs 2–3 added the state machines that matter in production — circuit
breakers, admission DEGRADED holds, graceful drains, deadline expiry —
but their transitions were visible only as bare counters. This module is
the causally-ordered timeline behind them: every state transition lands
here as one :class:`Event` carrying monotonic + wall-clock timestamps,
the model/version it concerns, a severity, and — when the transition was
caused by a specific request — that request's ``trace_id``, so an
operator can jump from ``GET /v2/events`` straight to the request's span
timeline in ``GET /v2/trace/requests``.

Emit points (category.name):

* ``lifecycle.server_start`` / ``lifecycle.server_shutdown``
* ``lifecycle.health`` — health_state() transition (READY/DEGRADED/DRAINING)
* ``model.load`` / ``model.unload``
* ``breaker.open`` / ``breaker.half_open`` / ``breaker.closed``
* ``admission.shed`` / ``admission.degraded_enter`` /
  ``admission.degraded_exit``
* ``drain.begin`` / ``drain.end``
* ``fault.injected``
* ``deadline.expired``

Like :func:`client_tpu.faults.registry`, the default journal is
process-global: breaker transitions happen inside client objects with no
engine handle, and chaos tests run client + server in one process — a
single journal gives them one correlated timeline. The buffer is a
bounded deque (``CLIENT_TPU_EVENT_BUFFER``, default 1024); old events
fall off the head and ``dropped`` counts them so ``since``-cursor readers
can detect gaps.

``CLIENT_TPU_LOG=json`` additionally mirrors every event (and every
``client_tpu`` logger record) to stderr as one JSON object per line —
the structured replacement for the bare ``logging.getLogger("client_tpu")``
stream handler, with ``trace_id`` correlation preserved.
"""

from __future__ import annotations

import json
import logging
import os
from client_tpu import config as envcfg
import sys
from client_tpu.utils import lockdep
import time
from collections import deque

__all__ = [
    "SEVERITIES",
    "Event",
    "EventJournal",
    "journal",
    "reset_journal",
    "configure_logging",
]

ENV_BUFFER = "CLIENT_TPU_EVENT_BUFFER"
ENV_LOG = "CLIENT_TPU_LOG"
DEFAULT_CAPACITY = 1024

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


class Event:
    """One journal entry. ``seq`` is a process-monotonic cursor (gap-free
    per journal); ``ts_wall`` is epoch seconds for humans, ``ts_mono_ns``
    the monotonic stamp for ordering against trace spans."""

    __slots__ = ("seq", "ts_wall", "ts_mono_ns", "category", "name",
                 "severity", "model", "version", "trace_id", "detail")

    def __init__(self, seq, ts_wall, ts_mono_ns, category, name, severity,
                 model=None, version=None, trace_id=None, detail=None):
        self.seq = seq
        self.ts_wall = ts_wall
        self.ts_mono_ns = ts_mono_ns
        self.category = category
        self.name = name
        self.severity = severity
        self.model = model
        self.version = version
        self.trace_id = trace_id
        self.detail = detail or {}

    def to_dict(self) -> dict:
        d = {
            "seq": self.seq,
            "ts_wall": self.ts_wall,
            "ts_mono_ns": self.ts_mono_ns,
            "category": self.category,
            "name": self.name,
            "severity": self.severity,
        }
        if self.model is not None:
            d["model"] = self.model
        if self.version:
            d["version"] = str(self.version)
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.detail:
            d["detail"] = self.detail
        return d

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Event(seq={self.seq}, name={self.name!r}, "
                f"severity={self.severity!r}, model={self.model!r}, "
                f"trace_id={self.trace_id!r})")


class EventJournal:
    """Bounded, thread-safe event ring. ``emit`` is the hot write path
    (one lock acquisition + deque append); sinks run outside the lock so
    a slow stderr cannot stall the serving path's lock."""

    def __init__(self, capacity: int | None = None, clock=time.time,
                 mono_ns=time.monotonic_ns):
        if capacity is None:
            try:
                capacity = envcfg.env_int(ENV_BUFFER)
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(1, int(capacity))
        self._clock = clock
        self._mono_ns = mono_ns
        self._events: deque[Event] = deque(maxlen=self.capacity)
        self._lock = lockdep.Lock("observability.events")
        self._seq = 0
        self._dropped = 0
        self._sinks: list = []

    # -- write path ----------------------------------------------------------

    def emit(self, category: str, name: str, *, severity: str = "INFO",
             model: str | None = None, version=None,
             trace_id: str | None = None, **detail) -> Event:
        if severity not in _SEV_RANK:
            raise ValueError(f"unknown severity {severity!r} "
                             f"(valid: {', '.join(SEVERITIES)})")
        with self._lock:
            self._seq += 1
            evt = Event(self._seq, self._clock(), self._mono_ns(),
                        category, name, severity, model=model,
                        version=str(version) if version is not None else None,
                        trace_id=trace_id, detail=detail or None)
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(evt)
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink(evt)
            # tpulint: allow[swallowed-exception] a broken sink must not take down the serving path
            except Exception:  # noqa: BLE001 — a broken sink must not
                pass           # take down the serving path
        return evt

    # -- read path -----------------------------------------------------------

    def snapshot(self, *, model: str | None = None,
                 severity: str | None = None, since_seq: int | None = None,
                 since_ts: float | None = None,
                 until_ts: float | None = None,
                 category: str | None = None,
                 limit: int | None = None) -> list[Event]:
        """Filtered copy, oldest first. ``severity`` is a minimum (WARNING
        returns WARNING + ERROR); ``since_seq``/``since_ts`` are exclusive
        cursors for incremental polls; ``until_ts`` is an inclusive wall
        upper bound so callers can ask for "the window around this edge"
        (the blackbox bundle writer, postmortem scrapes)."""
        min_rank = None
        if severity is not None:
            sev = str(severity).upper()
            if sev not in _SEV_RANK:
                raise ValueError(f"unknown severity {severity!r} "
                                 f"(valid: {', '.join(SEVERITIES)})")
            min_rank = _SEV_RANK[sev]
        with self._lock:
            events = list(self._events)
        out = []
        for e in events:
            if model is not None and e.model != model:
                continue
            if category is not None and e.category != category:
                continue
            if min_rank is not None and _SEV_RANK[e.severity] < min_rank:
                continue
            if since_seq is not None and e.seq <= since_seq:
                continue
            if since_ts is not None and e.ts_wall <= since_ts:
                continue
            if until_ts is not None and e.ts_wall > until_ts:
                continue
            out.append(e)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def export(self, **filters) -> dict:
        """The ``GET /v2/events`` response shape."""
        events = self.snapshot(**filters)
        with self._lock:
            next_seq = self._seq
            dropped = self._dropped
        return {
            "events": [e.to_dict() for e in events],
            "next_seq": next_seq,
            "dropped": dropped,
            "capacity": self.capacity,
        }

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        """Empty the ring (tests); seq keeps counting so cursors held
        across a clear stay valid."""
        with self._lock:
            self._events.clear()
            self._dropped = 0

    # -- sinks ---------------------------------------------------------------

    def add_sink(self, fn) -> None:
        """Subscribe ``fn(event)``; called after every emit, outside the
        journal lock. Idempotent per callable identity."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)


# -- process-global default journal ------------------------------------------

_default: EventJournal | None = None
_default_lock = lockdep.Lock("observability.events.default")


def journal() -> EventJournal:
    """The process-global journal (double-checked, like
    :func:`client_tpu.faults.registry`); ``CLIENT_TPU_LOG=json`` wires
    the stderr sink on first access."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                j = EventJournal()
                _default = j
                configure_logging()
    return _default


def reset_journal() -> None:
    """Drop the global journal (tests); the next journal() recreates it
    with current env settings."""
    global _default
    with _default_lock:
        _default = None


# -- structured JSON log sink (CLIENT_TPU_LOG=json) ---------------------------


class _JsonLogFormatter(logging.Formatter):
    """One JSON object per log record; ``trace_id`` rides along when the
    caller attached one via ``extra={"trace_id": ...}``."""

    def format(self, record: logging.LogRecord) -> str:
        d = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", None)
        if trace_id:
            d["trace_id"] = trace_id
        if record.exc_info and record.exc_info[0] is not None:
            d["exc"] = self.formatException(record.exc_info)
        return json.dumps(d, default=str)


def _event_sink(stream):
    def sink(evt: Event) -> None:
        d = evt.to_dict()
        d["kind"] = "event"
        try:
            stream.write(json.dumps(d, default=str) + "\n")
            stream.flush()
        except (OSError, ValueError):
            pass

    return sink


def configure_logging(environ=os.environ, stream=None,
                      jour: EventJournal | None = None) -> bool:
    """When ``CLIENT_TPU_LOG=json``: attach a JSON-lines handler to the
    ``client_tpu`` logger (replacing logging's default plain-text
    propagation for it) and mirror every journal event to the same
    stream. Returns True when the sink was installed. Idempotent."""
    mode = envcfg.env_text(ENV_LOG, environ).lower()
    if mode != "json":
        return False
    out = stream or sys.stderr
    logger = logging.getLogger("client_tpu")
    already = any(getattr(h, "_client_tpu_json", False)
                  for h in logger.handlers)
    if not already:
        handler = logging.StreamHandler(out)
        handler.setFormatter(_JsonLogFormatter())
        handler._client_tpu_json = True
        logger.addHandler(handler)
        logger.propagate = False
        if logger.level == logging.NOTSET:
            logger.setLevel(logging.INFO)
    target = jour if jour is not None else _default
    if target is not None:
        target.add_sink(_event_sink(out))
    return True
