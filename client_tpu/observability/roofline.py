"""Roofline attribution: XLA's static cost model joined to measured time.

Every other efficiency signal in the stack is host-timed — duty cycle,
fill ratio, and wave latency say how *long* a bucket runs, not how well
it uses the chip. This module supplies the other axis of the roofline
plot: the numerator (static FLOPs / bytes accessed per executable, from
XLA's HLO cost analysis) and the denominator (per-device-kind peak
specs), so the profiler can turn its measured device seconds into
achieved FLOP/s, achieved bytes/s, arithmetic intensity, and MFU/MBU
per (model, version, bucket).

Three deliberately separable pieces:

- :func:`capture_cost_model` — pull ``flops`` / ``bytes accessed`` out
  of ``jitted.lower(*args).cost_analysis()``. The lowering is
  trace-cached after the first real call, so this costs well under a
  millisecond and **never** triggers a backend compile (we never call
  ``.compile()`` here: AOT-compiled executables do not share the jit
  dispatch cache, so compiling one would double every compile).
  ``memory_analysis()`` only exists on *compiled* executables, which the
  jit path never hands out — :func:`capture_memory_analysis` covers
  callers that do hold one. Capture never raises: a backend without a
  cost model (interpret-mode Pallas, exotic plugins) degrades to an
  annotated ``{"available": False, "reason": ...}``.
- the **peak-spec registry** — bf16 peak FLOP/s and HBM bytes/s per
  chip, keyed by the ``device_kind`` string jax reports, overridable
  via ``CLIENT_TPU_ROOFLINE`` (inline JSON or ``@file``). On CPU (or an
  unlisted kind) peaks resolve to None and every ratio degrades to
  ``None`` / ``bound: unknown`` — measured-only, never an error.
- :func:`bucket_roofline` — the pure join: static cost × warm calls
  over measured device seconds, against the resolved peaks. The static
  model counts the *padded* bucket, so padded-fraction × total FLOPs is
  exactly the FLOPs spent multiplying zeros.

Trust the static model only as far as it goes: XLA counts algebraic
FLOPs after fusion/DCE on the optimized HLO, so a bucket that lowers to
a gather (DLRM embedding-bag) legitimately reports ~0 flops and its MFU
is meaningless — look at MBU instead; that asymmetry is what the
``bound`` classification (arithmetic intensity vs the ridge point) is
for.

``bert_flops_per_example`` lives here (not in side-effect-heavy
``bench.py``) so tools/mfu_diag.py and bench share one denominator
without importing a benchmark harness.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from client_tpu import config as envcfg

__all__ = [
    "ENV_VAR",
    "PEAK_SPECS",
    "PeakSpec",
    "RooflineConfig",
    "bert_flops_per_example",
    "bucket_roofline",
    "capture_cost_model",
    "capture_memory_analysis",
    "classify_bound",
    "detect_device_kind",
    "peak_flops_for_gen",
    "reset_roofline",
    "roofline_config",
    "roofline_context",
]

ENV_VAR = "CLIENT_TPU_ROOFLINE"


@dataclass(frozen=True)
class PeakSpec:
    """Per-chip peak rates. Either field may be None (partially known
    hardware): the ratios that need it degrade to None, the others
    still compute."""

    flops_per_s: float | None   # dense bf16 peak FLOP/s per chip
    bytes_per_s: float | None   # peak HBM bandwidth, bytes/s per chip
    source: str = "registry"

    def ridge(self) -> float | None:
        """Arithmetic intensity (flops/byte) at which the roofline
        bends: below it a kernel is bandwidth-bound, above compute."""
        if not self.flops_per_s or not self.bytes_per_s:
            return None
        return self.flops_per_s / self.bytes_per_s

    def as_dict(self) -> dict:
        return {"flops_per_s": self.flops_per_s,
                "bytes_per_s": self.bytes_per_s,
                "source": self.source}


# Public spec-sheet bf16 peaks per chip, keyed by (lowercased)
# ``device_kind``. HBM numbers are the vendor-quoted bandwidth.
PEAK_SPECS: dict[str, PeakSpec] = {
    "tpu v2": PeakSpec(45e12, 700e9),
    "tpu v3": PeakSpec(123e12, 900e9),
    "tpu v4": PeakSpec(275e12, 1228e9),
    "tpu v5 lite": PeakSpec(197e12, 819e9),
    "tpu v5e": PeakSpec(197e12, 819e9),
    "tpu v5p": PeakSpec(459e12, 2765e9),
    "tpu v6 lite": PeakSpec(918e12, 1640e9),
    "tpu v6e": PeakSpec(918e12, 1640e9),
}

# bench.py / tooling shorthand ("PALLAS_AXON_TPU_GEN=v5e") -> registry key.
_GEN_ALIASES = {
    "v2": "tpu v2", "v3": "tpu v3", "v4": "tpu v4",
    "v5e": "tpu v5e", "v5litepod": "tpu v5e", "v5p": "tpu v5p",
    "v6e": "tpu v6e",
}


def peak_flops_for_gen(gen: str) -> float | None:
    """Peak FLOP/s for a TPU-generation shorthand (``v5e``, ``v4``...);
    None for unknown — bench's MFU line is advisory, never fatal."""
    spec = PEAK_SPECS.get(_GEN_ALIASES.get(gen.strip().lower(), ""))
    return spec.flops_per_s if spec else None


# -- CLIENT_TPU_ROOFLINE ------------------------------------------------------


@dataclass
class RooflineConfig:
    """``CLIENT_TPU_ROOFLINE`` knobs. Grammar matches the other
    observability knobs, defaulting ON: unset/``1``/``on`` captures with
    registry peaks, ``0``/``off`` disables capture, else inline JSON or
    ``@file`` with ``peak_flops`` / ``peak_bytes_per_s`` (forces the
    peaks regardless of detected kind — the only way to get MFU on a
    CPU dev host) and/or ``device_kinds`` (extra registry rows:
    ``{"kind": {"peak_flops": ..., "peak_bytes_per_s": ...}}``)."""

    capture: bool = True
    peak_flops: float | None = None
    peak_bytes_per_s: float | None = None
    device_kinds: dict[str, PeakSpec] | None = None

    @classmethod
    def from_dict(cls, data: dict) -> "RooflineConfig":
        known = {"capture", "peak_flops", "peak_bytes_per_s",
                 "device_kinds"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        cfg = cls()
        if "capture" in data:
            if not isinstance(data["capture"], bool):
                raise ValueError(
                    f"{ENV_VAR}: key 'capture' expects a boolean, "
                    f"got {data['capture']!r}")
            cfg.capture = data["capture"]
        for key in ("peak_flops", "peak_bytes_per_s"):
            if key in data:
                setattr(cfg, key, _positive_number(key, data[key]))
        if "device_kinds" in data:
            kinds = data["device_kinds"]
            if not isinstance(kinds, dict):
                raise ValueError(
                    f"{ENV_VAR}: key 'device_kinds' expects an object")
            cfg.device_kinds = {}
            for kind, spec in kinds.items():
                if not isinstance(spec, dict):
                    raise ValueError(
                        f"{ENV_VAR}: device_kinds[{kind!r}] expects an "
                        "object with peak_flops / peak_bytes_per_s")
                extra = set(spec) - {"peak_flops", "peak_bytes_per_s"}
                if extra:
                    raise ValueError(
                        f"{ENV_VAR}: device_kinds[{kind!r}] unknown "
                        f"key(s) {sorted(extra)}")
                cfg.device_kinds[kind.strip().lower()] = PeakSpec(
                    _positive_number(f"device_kinds[{kind!r}].peak_flops",
                                     spec["peak_flops"])
                    if "peak_flops" in spec else None,
                    _positive_number(
                        f"device_kinds[{kind!r}].peak_bytes_per_s",
                        spec["peak_bytes_per_s"])
                    if "peak_bytes_per_s" in spec else None,
                    source="env")
        return cfg

    @classmethod
    def from_env(cls, environ=None) -> "RooflineConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if raw.lower() in ("0", "false", "off"):
            return cls(capture=False)
        if not raw or raw.lower() in ("1", "true", "on"):
            return cls()
        if raw.startswith("@"):
            try:
                with open(raw[1:]) as f:
                    raw = f.read()
            except OSError as exc:
                raise ValueError(
                    f"{ENV_VAR}: cannot read '{raw[1:]}': {exc}") from None
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{ENV_VAR}: invalid JSON ({exc})") from None
        if not isinstance(data, dict):
            raise ValueError(f"{ENV_VAR}: expected a JSON object")
        return cls.from_dict(data)

    def resolve_peaks(self, device_kind: str) -> PeakSpec | None:
        """Peaks for a detected kind: an explicit env ``peak_flops`` /
        ``peak_bytes_per_s`` pair wins outright (that is the CPU-host
        escape hatch), then env ``device_kinds`` rows, then the built-in
        registry; None when nothing matches (``peaks: unknown``)."""
        if self.peak_flops is not None or self.peak_bytes_per_s is not None:
            return PeakSpec(self.peak_flops, self.peak_bytes_per_s,
                            source="env")
        kind = device_kind.strip().lower()
        for table, src in ((self.device_kinds or {}, "env"),
                           (PEAK_SPECS, "registry")):
            spec = table.get(kind)
            if spec is None:
                # Substring match: libtpu has reported both "TPU v5e"
                # and "TPU v5 lite" for the same part across versions.
                for key, candidate in table.items():
                    if key and key in kind:
                        spec = candidate
                        break
            if spec is not None:
                return PeakSpec(spec.flops_per_s, spec.bytes_per_s,
                                source=src)
        return None


def _positive_number(key: str, raw) -> float:
    if isinstance(raw, bool) or not isinstance(raw, (int, float)):
        raise ValueError(f"{ENV_VAR}: key '{key}' expects a number, "
                         f"got {raw!r}")
    value = float(raw)
    if value <= 0:
        raise ValueError(f"{ENV_VAR}: key '{key}' must be > 0")
    return value


def roofline_config(environ=None) -> RooflineConfig:
    """Parse ``CLIENT_TPU_ROOFLINE`` (fresh each call — it is a few
    string compares for the common unset case). Raises ValueError on a
    malformed value; the engine resolves it once at startup so operators
    fail fast, while the snapshot path catches and annotates instead."""
    return RooflineConfig.from_env(environ)


# -- device detection ---------------------------------------------------------

_detected_kind: str | None = None


def detect_device_kind() -> str:
    """``device_kind`` of device 0 ("TPU v5 lite", "cpu", ...); cached
    for the process — a backend cannot change under a running server.
    "unknown" when jax is absent or unhappy, never an exception."""
    global _detected_kind
    if _detected_kind is None:
        try:
            import jax

            devices = jax.devices()
            kind = getattr(devices[0], "device_kind", "") if devices else ""
            _detected_kind = str(kind) or "unknown"
        except Exception:  # noqa: BLE001 — detection is advisory
            _detected_kind = "unknown"
    return _detected_kind


def roofline_context(environ=None) -> dict:
    """The resolved roofline environment for snapshot headers:
    ``{"device_kind", "peaks": {...} | "unknown"}`` plus a
    ``config_error`` annotation instead of a raise when the env knob is
    malformed (the profile surface must render regardless)."""
    try:
        cfg = roofline_config(environ)
    except ValueError as exc:
        return {"device_kind": detect_device_kind(), "peaks": "unknown",
                "config_error": str(exc)}
    kind = detect_device_kind()
    peaks = cfg.resolve_peaks(kind)
    return {
        "device_kind": kind,
        "peaks": peaks.as_dict() if peaks else "unknown",
    }


def resolve_peaks(environ=None) -> PeakSpec | None:
    """Peaks only (gauge refresh path); None on malformed env too —
    fail-fast belongs to engine startup, not the scrape loop."""
    try:
        return roofline_config(environ).resolve_peaks(detect_device_kind())
    except ValueError:
        return None


def reset_roofline() -> None:
    """Forget the cached device-kind detection (tests)."""
    global _detected_kind
    _detected_kind = None


# -- static cost capture ------------------------------------------------------


def capture_cost_model(jitted, args=(), kwargs=None,
                       config: RooflineConfig | None = None) -> dict:
    """Static cost of one jitted callable at one signature, via
    ``jitted.lower(*args).cost_analysis()``.

    Returns ``{"available": True, "flops", "bytes_accessed",
    "transcendentals"}`` or ``{"available": False, "reason": ...}`` —
    never raises, never compiles (see module docstring). Call it right
    after the first real execution: the lowering is then trace-cached
    and this is sub-millisecond dict work.
    """
    if config is None:
        try:
            config = roofline_config()
        except ValueError:
            # Malformed env: the engine fail-fasted at startup if it
            # could; a late mutation must not break the serve path.
            config = RooflineConfig()
    if not config.capture:
        return {"available": False, "reason": f"disabled by {ENV_VAR}"}
    try:
        lower = getattr(jitted, "lower", None)
        if lower is None:
            return {"available": False,
                    "reason": "callable has no .lower (not jitted)"}
        lowered = lower(*args, **(kwargs or {}))
        analysis = lowered.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if not isinstance(analysis, dict):
            return {"available": False,
                    "reason": "cost_analysis returned "
                              f"{type(analysis).__name__}"}
        flops = analysis.get("flops")
        byts = analysis.get("bytes accessed")
        if flops is None and byts is None:
            return {"available": False,
                    "reason": "cost_analysis has neither 'flops' nor "
                              "'bytes accessed'"}
        return {
            "available": True,
            # XLA uses -1 as "unknown" for some ops; clamp, don't poison.
            "flops": max(0.0, float(flops or 0.0)),
            "bytes_accessed": max(0.0, float(byts or 0.0)),
            "transcendentals": max(
                0.0, float(analysis.get("transcendentals") or 0.0)),
        }
    except Exception as exc:  # noqa: BLE001 — degrade, never 500
        return {"available": False,
                "reason": f"{type(exc).__name__}: {exc}"[:200]}


def capture_memory_analysis(compiled) -> dict:
    """``memory_analysis()`` where a *compiled* executable is actually in
    hand (the jit dispatch path never exposes one — see module
    docstring); same never-raise contract as cost capture."""
    try:
        mem = compiled.memory_analysis()
        if mem is None:
            return {"available": False,
                    "reason": "memory_analysis returned None"}
        out = {"available": True}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes"):
            value = getattr(mem, attr, None)
            if value is not None:
                out[attr] = int(value)
        return out
    except Exception as exc:  # noqa: BLE001 — degrade, never 500
        return {"available": False,
                "reason": f"{type(exc).__name__}: {exc}"[:200]}


# -- the join -----------------------------------------------------------------


def classify_bound(intensity: float | None,
                   peaks: PeakSpec | None) -> str:
    """``compute`` | ``bandwidth`` | ``unknown``: arithmetic intensity
    against the device ridge point. Unknown when either side is."""
    if intensity is None or peaks is None:
        return "unknown"
    ridge = peaks.ridge()
    if ridge is None:
        return "unknown"
    return "bandwidth" if intensity < ridge else "compute"


def bucket_roofline(cost: dict | None, calls: int, device_s: float,
                    padded_fraction: float = 0.0,
                    peaks: PeakSpec | None = None) -> dict:
    """Join one bucket's static cost model with its measured warm-call
    device seconds. ``calls`` must be the *warm* execution count —
    ``device_s`` excludes cold (compiling) calls, so the rates divide
    like with like. Cost-model-less buckets return the annotated
    absence the satellite demands, with ``bound: unknown``."""
    if not cost or not cost.get("available"):
        return {
            "cost_model": "unavailable",
            "reason": (cost or {}).get("reason", "not captured"),
            "bound": "unknown",
        }
    flops = float(cost.get("flops", 0.0))
    byts = float(cost.get("bytes_accessed", 0.0))
    calls = max(0, int(calls))
    intensity = (flops / byts) if byts > 0 else None
    out = {
        "cost_model": "xla",
        "flops_per_call": flops,
        "bytes_per_call": byts,
        "arithmetic_intensity": round(intensity, 4)
        if intensity is not None else None,
        "total_flops": flops * calls,
        "total_bytes": byts * calls,
        # The static model prices the padded bucket, so the padded row
        # fraction of its FLOPs was spent multiplying zeros.
        "padding_wasted_flops": flops * calls * max(
            0.0, min(1.0, padded_fraction)),
        "achieved_flops_per_s": None,
        "achieved_bytes_per_s": None,
        "mfu": None,
        "mbu": None,
        "bound": classify_bound(intensity, peaks),
    }
    if device_s > 0 and calls > 0:
        achieved_f = flops * calls / device_s
        achieved_b = byts * calls / device_s
        out["achieved_flops_per_s"] = achieved_f
        out["achieved_bytes_per_s"] = achieved_b
        if peaks and peaks.flops_per_s:
            out["mfu"] = round(achieved_f / peaks.flops_per_s, 6)
        if peaks and peaks.bytes_per_s:
            out["mbu"] = round(achieved_b / peaks.bytes_per_s, 6)
    return out


# -- shared analytic denominators --------------------------------------------


def bert_flops_per_example(seq_len=128, hidden=768, n_layers=12, ffn=3072):
    """Analytic forward FLOPs for one BERT-base example (2*MAC convention):
    per layer 4 QKVO projections + 2 attention einsums + 2 FFN matmuls.
    Shared by bench's MFU probe and tools/mfu_diag.py — one denominator,
    one place to get it wrong."""
    s, h, f = seq_len, hidden, ffn
    per_layer = 8 * s * h * h + 4 * s * s * h + 4 * s * h * f
    return n_layers * per_layer
