"""Incident blackbox: edge-triggered postmortem bundles.

Every sensor PRs 11–19 built — the event journal, the 1 Hz flight
recorder, the HBM census, the cost ledger, the QoS table, roofline
attribution — lives in a bounded in-memory ring. By the time an
operator asks *why* the fleet throttled tenant X at 03:14, the samples
and journal tail that explain it have rotated out. The blackbox closes
that gap: a :class:`BlackboxRecorder` subscribes to the journal as a
sink and, on configured trigger *edges*, atomically snapshots the
correlated state into an on-disk bundle:

- ``journal`` — the journal tail (``journal_tail`` newest events);
- ``timeseries`` — the flight-recorder ring windowed around the trigger
  (``window_s`` before, ``post_window_s`` after);
- ``profile`` — the efficiency profiler snapshot (incl. roofline,
  autotune and selfdrive sections);
- ``memory`` — the HBM census (owners, drift, pressure);
- ``costs`` / ``qos`` / ``slo`` — tenant ledger, class table, burn rates;
- ``traces`` — stitched Chrome traces of the ``worst_requests`` slowest
  recently completed requests;
- ``fingerprint`` — env/config/git/process identity, so a bundle pulled
  off a dead machine still says what was running.

Trigger vocabulary (journal ``category.name`` edges): ``slo.fast_burn``
(health flips DEGRADED with burning models), ``qos.throttle``,
``admission.tighten``, ``fleet.rebalance``, ``memory.pressure``,
``breaker.storm`` (>= ``storm_count`` breaker-opens in
``storm_window_s``), ``deadline.burst`` (same, deadline expiries) —
plus ``manual`` (the ``POST /v2/debug/capture`` surface) and ``crash``
(unhandled-exception / atexit hooks, :func:`install_crash_hooks`).

A burning fleet must write one bundle per *incident*, not one per tick:
a global ``debounce_s`` plus a per-trigger ``cooldown_s`` suppress
repeat edges, and the bundle ring itself is capped by count and bytes
with oldest-first eviction. Trigger matching runs on the emitting
thread (journal sinks are called outside the journal lock) and only
enqueues; the actual snapshot runs on a dedicated capture thread so no
data-plane lock order is ever crossed.

``CLIENT_TPU_BLACKBOX`` follows the flight-recorder grammar and
defaults ON with conservative caps: unset/``1``/``on`` takes defaults,
``0``/``off`` disables, else inline JSON or ``@/path.json``. Served as
``GET /v2/debug/bundles``, ``GET /v2/debug/bundles/{id}`` and
``POST /v2/debug/capture`` (plus gRPC mirrors); rendered by
``tools/blackbox_report.py``; coordinated fleet-wide by the router
(``client_tpu.router.blackbox``).
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import os
import re
import sys
import tempfile
import threading
import time
import uuid
import weakref
from collections import deque
from dataclasses import dataclass, field, fields

from client_tpu import config as envcfg
from client_tpu.utils import lockdep

__all__ = [
    "ENV_VAR",
    "DEFAULT_TRIGGERS",
    "BlackboxConfig",
    "BundleStore",
    "BlackboxRecorder",
    "match_trigger",
    "install_crash_hooks",
]

ENV_VAR = "CLIENT_TPU_BLACKBOX"

_log = logging.getLogger("client_tpu.blackbox")

# Single-event edges: (category, name) -> trigger. lifecycle.health is
# special-cased in match_trigger (only DEGRADED-with-burning-models
# transitions count, not every health flip).
_EDGE_TRIGGERS = {
    ("qos", "throttle"): "qos.throttle",
    ("admission", "tighten"): "admission.tighten",
    ("fleet", "rebalance"): "fleet.rebalance",
    ("memory", "pressure"): "memory.pressure",
}

# Rate edges: a single breaker-open or deadline-expiry is routine; a
# storm/burst of them inside storm_window_s is an incident.
_STORM_TRIGGERS = {
    ("breaker", "open"): "breaker.storm",
    ("deadline", "expired"): "deadline.burst",
}

DEFAULT_TRIGGERS = (
    "slo.fast_burn",
    "qos.throttle",
    "admission.tighten",
    "fleet.rebalance",
    "memory.pressure",
    "breaker.storm",
    "deadline.burst",
)

# Always-valid trigger names for the manual surface (anything in the
# automatic vocabulary is also accepted so the router can fan out the
# edge it observed).
MANUAL_TRIGGERS = ("manual", "crash", "fleet")

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

# Bundle ids embed a process-global sequence (not per-recorder): multiple
# engines in one process share a bundle directory, and per-recorder
# counters would collide on bb-<pid>-0001-... and silently overwrite.
_seq_lock = threading.Lock()
_seq_counter = 0


def _next_seq() -> int:
    global _seq_counter
    with _seq_lock:
        _seq_counter += 1
        return _seq_counter


def match_trigger(category: str, name: str, detail: dict | None) -> str | None:
    """The trigger this journal edge maps to, or None. Pure function so
    the vocabulary is unit-testable without a recorder."""
    if category == "lifecycle" and name == "health":
        if detail and detail.get("slo_fast_burn"):
            return "slo.fast_burn"
        return None
    return (_EDGE_TRIGGERS.get((category, name))
            or _STORM_TRIGGERS.get((category, name)))


@dataclass
class BlackboxConfig:
    """``CLIENT_TPU_BLACKBOX`` knobs. Defaults ON (like the flight
    recorder): unset takes defaults, ``0``/``off`` disables."""

    dir: str = ""              # bundle directory ("" = per-pid tmp dir)
    window_s: float = 60.0     # flight-recorder window before the trigger
    post_window_s: float = 2.0  # settle time after the trigger edge
    debounce_s: float = 30.0   # global min gap between automatic captures
    cooldown_s: float = 300.0  # per-trigger min gap
    storm_count: int = 5       # breaker/deadline edges to call it a storm
    storm_window_s: float = 10.0
    journal_tail: int = 256    # newest journal events per bundle
    worst_requests: int = 3    # stitched traces of the slowest requests
    max_bundles: int = 12      # bundle-ring count cap
    max_bundle_bytes: int = 4 * 1024 * 1024    # per-bundle size cap
    max_total_bytes: int = 48 * 1024 * 1024    # ring byte cap (eviction)
    triggers: tuple = DEFAULT_TRIGGERS
    enabled: bool = True

    _NUMS = ("window_s", "post_window_s", "debounce_s", "cooldown_s",
             "storm_window_s")
    _INTS = ("storm_count", "journal_tail", "worst_requests",
             "max_bundles", "max_bundle_bytes", "max_total_bytes")

    @classmethod
    def from_dict(cls, data: dict) -> "BlackboxConfig":
        known = {f.name for f in fields(cls) if f.name != "enabled"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        cfg = cls()
        if "dir" in data:
            if not isinstance(data["dir"], str) or not data["dir"]:
                raise ValueError(
                    f"{ENV_VAR}: key 'dir' expects a non-empty path")
            cfg.dir = data["dir"]
        for key in cls._NUMS:
            if key in data:
                try:
                    setattr(cfg, key, float(data[key]))
                except (TypeError, ValueError):
                    raise ValueError(
                        f"{ENV_VAR}: key {key!r} expects a number, "
                        f"got {data[key]!r}") from None
        for key in cls._INTS:
            if key in data:
                try:
                    setattr(cfg, key, int(data[key]))
                except (TypeError, ValueError):
                    raise ValueError(
                        f"{ENV_VAR}: key {key!r} expects an integer, "
                        f"got {data[key]!r}") from None
        if "triggers" in data:
            trigs = data["triggers"]
            if not isinstance(trigs, (list, tuple)):
                raise ValueError(
                    f"{ENV_VAR}: key 'triggers' expects a list of "
                    "trigger names")
            bad = [t for t in trigs if t not in DEFAULT_TRIGGERS]
            if bad:
                raise ValueError(
                    f"{ENV_VAR}: unknown trigger(s) {bad}; "
                    f"valid: {list(DEFAULT_TRIGGERS)}")
            cfg.triggers = tuple(trigs)
        if cfg.window_s <= 0:
            raise ValueError(f"{ENV_VAR}: window_s must be > 0")
        if cfg.post_window_s < 0:
            raise ValueError(f"{ENV_VAR}: post_window_s must be >= 0")
        if cfg.debounce_s < 0 or cfg.cooldown_s < 0:
            raise ValueError(
                f"{ENV_VAR}: debounce_s/cooldown_s must be >= 0")
        if cfg.storm_count < 1 or cfg.storm_window_s <= 0:
            raise ValueError(
                f"{ENV_VAR}: storm_count >= 1 and storm_window_s > 0 "
                "required")
        if cfg.max_bundles < 1 or cfg.max_bundle_bytes < 4096 \
                or cfg.max_total_bytes < cfg.max_bundle_bytes:
            raise ValueError(
                f"{ENV_VAR}: max_bundles >= 1, max_bundle_bytes >= 4096 "
                "and max_total_bytes >= max_bundle_bytes required")
        if cfg.journal_tail < 1 or cfg.worst_requests < 0:
            raise ValueError(
                f"{ENV_VAR}: journal_tail >= 1 and worst_requests >= 0 "
                "required")
        return cfg

    @classmethod
    def from_env(cls, environ=os.environ) -> "BlackboxConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if raw.lower() in ("0", "false", "off"):
            return cls(enabled=False)
        if not raw or raw.lower() in ("1", "true", "on"):
            return cls()
        if raw.startswith("@"):
            try:
                with open(raw[1:]) as f:
                    raw = f.read()
            except OSError as exc:
                raise ValueError(
                    f"{ENV_VAR}: cannot read '{raw[1:]}': {exc}") from None
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{ENV_VAR}: invalid JSON ({exc})") from None
        if not isinstance(data, dict):
            raise ValueError(f"{ENV_VAR}: expected a JSON object")
        return cls.from_dict(data)

    def resolved_dir(self) -> str:
        """The bundle directory: configured, else a per-pid tmp dir —
        files survive the process (that is the point of a blackbox);
        the pid scoping keeps concurrent test processes apart."""
        if self.dir:
            return self.dir
        return os.path.join(tempfile.gettempdir(),
                            f"client_tpu_blackbox_{os.getpid()}")


def fingerprint() -> dict:
    """Env/config/git/process identity for a bundle: enough to say what
    was running without the machine it ran on. Best-effort everywhere —
    a fingerprint must never fail a capture."""
    # tpulint: allow[wall-clock] exported identity stamp, not duration math
    info: dict = {
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "ts_wall": time.time(),  # tpulint: allow[wall-clock] wall stamp
    }
    try:
        import platform

        info["platform"] = platform.platform()
    except Exception as exc:  # noqa: BLE001 — best-effort identity
        info["platform"] = f"unknown ({exc})"
    # Registered CLIENT_TPU_* env (values as set; the registry owns the
    # defaults, the bundle records the overrides).
    env = {}
    for key, value in os.environ.items():
        if key.startswith("CLIENT_TPU_"):
            env[key] = value
    info["env"] = dict(sorted(env.items()))
    # Library versions of interest, only if already imported — a crash
    # bundle must not pay (or risk) a jax import.
    versions = {}
    for mod in ("jax", "numpy", "grpc"):
        m = sys.modules.get(mod)
        ver = getattr(m, "__version__", None) if m is not None else None
        if ver:
            versions[mod] = str(ver)
    info["versions"] = versions
    info["git"] = _git_identity()
    return info


def _git_identity() -> dict:
    """Commit hash via .git plumbing files (no subprocess: capture can
    run in a crashing process)."""
    out: dict = {}
    try:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        head_path = os.path.join(root, ".git", "HEAD")
        with open(head_path) as f:
            head = f.read().strip()
        if head.startswith("ref: "):
            ref = head[5:]
            out["ref"] = ref
            ref_path = os.path.join(root, ".git", *ref.split("/"))
            try:
                with open(ref_path) as f:
                    out["commit"] = f.read().strip()
            except OSError:
                # packed refs: one "hash ref" line each
                with open(os.path.join(root, ".git",
                                       "packed-refs")) as f:
                    for line in f:
                        if line.strip().endswith(ref):
                            out["commit"] = line.split()[0]
                            break
        else:
            out["commit"] = head
    except Exception as exc:  # noqa: BLE001 — identity is best-effort
        out["error"] = str(exc)
    return out


class BundleStore:
    """Size/count-capped ring of bundle files in one directory.

    One JSON file per bundle (``<id>.json``), written atomically
    (tmp + rename) so a reader — or a crash — never sees a torn
    bundle. Eviction is oldest-first by mtime whenever the count or
    total-byte cap is exceeded. Thread-safe."""

    def __init__(self, directory: str, *, max_bundles: int = 12,
                 max_total_bytes: int = 48 * 1024 * 1024):
        self.directory = directory
        self.max_bundles = max_bundles
        self.max_total_bytes = max_total_bytes
        self._lock = lockdep.Lock("observability.blackbox.store")
        self._metas: dict[str, dict] = {}  # id -> meta for our writes

    def _path(self, bundle_id: str) -> str:
        return os.path.join(self.directory, f"{bundle_id}.json")

    def write(self, bundle_id: str, payload: bytes, meta: dict) -> dict:
        """Atomically persist one serialized bundle, evict past the
        caps, and return the enriched meta."""
        if not _ID_RE.match(bundle_id):
            raise ValueError(f"invalid bundle id {bundle_id!r}")
        os.makedirs(self.directory, exist_ok=True)
        path = self._path(bundle_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        meta = dict(meta, id=bundle_id, bytes=len(payload))
        with self._lock:
            self._metas[bundle_id] = meta
        self._evict()
        return meta

    def _scan(self) -> list[tuple[str, int, float]]:
        """(id, bytes, mtime) for every bundle file on disk."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                st = os.stat(os.path.join(self.directory, name))
            except OSError:
                continue
            out.append((name[:-5], st.st_size, st.st_mtime))
        return out

    def _evict(self) -> None:
        entries = sorted(self._scan(), key=lambda e: (e[2], e[0]))
        total = sum(e[1] for e in entries)
        while entries and (len(entries) > self.max_bundles
                           or total > self.max_total_bytes):
            victim, nbytes, _ = entries.pop(0)
            try:
                os.remove(self._path(victim))
            except OSError:
                _log.warning("blackbox: could not evict bundle %s",
                             victim)
            total -= nbytes
            with self._lock:
                self._metas.pop(victim, None)

    def total_bytes(self) -> int:
        return sum(e[1] for e in self._scan())

    def list(self) -> list[dict]:
        """Bundle metas, newest first. Bundles written by this process
        carry their full meta; files found on disk from an earlier
        process carry id/bytes/mtime only."""
        with self._lock:
            metas = dict(self._metas)
        out = []
        for bundle_id, nbytes, mtime in sorted(
                self._scan(), key=lambda e: (e[2], e[0]), reverse=True):
            meta = metas.get(bundle_id)
            if meta is None:
                meta = {"id": bundle_id, "bytes": nbytes,
                        "mtime": mtime}
            else:
                meta = dict(meta, bytes=nbytes)
            out.append(meta)
        return out

    def load(self, bundle_id: str) -> dict:
        """Parse one bundle. Raises KeyError (unknown — read surfaces
        map it to 404) or ValueError (malformed id / corrupt file —
        400, never 500)."""
        if not _ID_RE.match(bundle_id or ""):
            raise ValueError(f"invalid bundle id {bundle_id!r}")
        path = self._path(bundle_id)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raise KeyError(bundle_id) from None
        except OSError as exc:
            raise ValueError(
                f"unreadable bundle {bundle_id}: {exc}") from None
        try:
            bundle = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            raise ValueError(
                f"corrupt bundle {bundle_id}: {exc}") from None
        if not isinstance(bundle, dict):
            raise ValueError(
                f"corrupt bundle {bundle_id}: expected a JSON object")
        return bundle


class BlackboxRecorder:
    """Journal-triggered incident capture for one engine.

    Holds the engine weakly (a shut-down engine must be collectable);
    trigger matching runs on the emitting thread and only enqueues,
    capture runs on a lazily started daemon thread with a stop event.
    ``clock``/``mono`` are injectable for fake-clock debounce tests."""

    def __init__(self, engine, config: BlackboxConfig | None = None, *,
                 registry=None, clock=time.time, mono=time.monotonic,
                 store: BundleStore | None = None):
        self.config = config or BlackboxConfig()
        self._engine_ref = weakref.ref(engine)
        self._clock = clock
        self._mono = mono
        self.store = store or BundleStore(
            self.config.resolved_dir(),
            max_bundles=self.config.max_bundles,
            max_total_bytes=self.config.max_total_bytes)
        self._lock = lockdep.Lock("observability.blackbox")
        self._pending: deque = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_capture = float("-inf")      # mono, automatic only
        self._cooldowns: dict[str, float] = {}  # trigger -> mono stamp
        self._last_bundle: dict[str, str] = {}  # trigger -> bundle id
        self._storms: dict[str, deque] = {}
        self.captures = 0
        self.suppressed = 0
        self.failures = 0
        self.last_capture_ms: float | None = None
        self._captures_total = None
        self._bundle_bytes = None
        self._failures_total = None
        if registry is not None:
            self.bind_metrics(registry)

    def bind_metrics(self, registry) -> None:
        self._captures_total = registry.counter(
            "tpu_blackbox_captures_total",
            "Incident bundles captured, by trigger edge",
            ("trigger",))
        self._bundle_bytes = registry.gauge(
            "tpu_blackbox_bundle_bytes",
            "Total bytes of incident bundles currently retained on disk")
        self._failures_total = registry.counter(
            "tpu_blackbox_capture_failures_total",
            "Incident captures that failed (snapshot or write error)")

    # -- lifecycle ------------------------------------------------------------

    def install(self) -> "BlackboxRecorder":
        """Subscribe to the process journal and arm the crash hooks.
        No-op when disabled."""
        if not self.config.enabled:
            return self
        from client_tpu.observability.events import journal

        journal().add_sink(self._on_event)
        install_crash_hooks(self)
        return self

    def close(self) -> None:
        """Unsubscribe and stop the capture thread (pending captures
        are abandoned — the engine is going away with their state)."""
        from client_tpu.observability.events import journal

        journal().remove_sink(self._on_event)
        self._stop.set()
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
        self._thread = None

    def _ensure_thread(self) -> None:
        if self._stop.is_set():
            return
        thread = self._thread
        if thread is not None and thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="blackbox-capture", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            if self._stop.is_set():
                return
            # Let the post-trigger window fill before snapshotting so
            # the bundle shows the edge with context on both sides.
            if self._pending and self.config.post_window_s > 0:
                self._stop.wait(self.config.post_window_s)
            self.drain()

    # -- trigger path ---------------------------------------------------------

    def _on_event(self, event) -> None:
        """Journal sink: match, debounce, enqueue. Runs on the emitting
        thread — must stay cheap and take only the blackbox lock."""
        if not self.config.enabled:
            return
        if event.category == "blackbox":
            return  # our own captured edges must not re-trigger
        if self._engine_ref() is None:
            # The engine died without close(); detach ourselves.
            from client_tpu.observability.events import journal

            journal().remove_sink(self._on_event)
            return
        trigger = match_trigger(event.category, event.name, event.detail)
        if trigger is None or trigger not in self.config.triggers:
            return
        now = self._mono()
        with self._lock:
            if trigger in _STORM_TRIGGERS.values():
                ring = self._storms.setdefault(
                    trigger, deque(maxlen=max(self.config.storm_count,
                                              64)))
                ring.append(now)
                while ring and now - ring[0] > self.config.storm_window_s:
                    ring.popleft()
                if len(ring) < self.config.storm_count:
                    return
                ring.clear()
            if not self._admit_locked(trigger, now):
                self.suppressed += 1
                return
            self._pending.append((trigger, event.to_dict(),
                                  event.ts_wall))
        self._wake.set()
        self._ensure_thread()

    def _admit_locked(self, trigger: str, now: float) -> bool:
        """Debounce + per-trigger cooldown; stamps on admit so the next
        edge in the same incident is suppressed at enqueue time."""
        if now - self._last_capture < self.config.debounce_s:
            return False
        last = self._cooldowns.get(trigger)
        if last is not None and now - last < self.config.cooldown_s:
            return False
        self._last_capture = now
        self._cooldowns[trigger] = now
        return True

    def drain(self) -> int:
        """Capture everything pending (the capture thread's body; also
        the deterministic test entry point). Returns bundles written."""
        written = 0
        while True:
            with self._lock:
                if not self._pending:
                    return written
                trigger, event_dict, wall = self._pending.popleft()
            try:
                self.capture(trigger, trigger_event=event_dict,
                             trigger_wall=wall)
                written += 1
            except Exception:  # noqa: BLE001 — capture must not wedge
                self.failures += 1
                if self._failures_total is not None:
                    self._failures_total.inc()
                if self.failures == 1:
                    _log.exception(
                        "blackbox capture failed (logged once; further "
                        "failures only counted)")

    # -- capture --------------------------------------------------------------

    def capture(self, trigger: str = "manual", *, incident: str | None = None,
                note: str | None = None, trigger_event: dict | None = None,
                trigger_wall: float | None = None,
                respect_cooldown: bool = False) -> dict:
        """Snapshot one bundle now (synchronous; the manual surface and
        the capture thread both land here). With ``respect_cooldown``
        a non-manual trigger inside its debounce/cooldown window
        returns ``{"deduped": True, ...}`` instead of writing a second
        bundle for the same incident (the router fan-out path)."""
        engine = self._engine_ref()
        if engine is None:
            raise RuntimeError("engine is gone")
        if trigger not in DEFAULT_TRIGGERS \
                and trigger not in MANUAL_TRIGGERS:
            raise ValueError(
                f"unknown trigger {trigger!r}; valid: "
                f"{list(DEFAULT_TRIGGERS) + list(MANUAL_TRIGGERS)}")
        auto = trigger in DEFAULT_TRIGGERS
        if respect_cooldown and auto:
            now = self._mono()
            with self._lock:
                admitted = self._admit_locked(trigger, now)
                last_id = self._last_bundle.get(trigger)
            if not admitted:
                self.suppressed += 1
                return {"deduped": True, "trigger": trigger,
                        "incident": incident, "bundle": last_id}
        t0 = time.perf_counter()
        wall = trigger_wall if trigger_wall is not None else self._clock()
        bundle_id = (f"bb-{os.getpid()}-{_next_seq():04d}-"
                     + trigger.replace(".", "-"))
        incident = incident or f"inc-{uuid.uuid4().hex[:12]}"
        cfg = self.config
        sections: dict = {}

        def section(name, fn):
            try:
                sections[name] = fn()
            except Exception as exc:  # noqa: BLE001 — partial bundles
                sections[name] = {"error": f"{type(exc).__name__}: {exc}"}

        from client_tpu.observability.events import journal

        section("journal", lambda: journal().export(
            limit=cfg.journal_tail))
        section("timeseries", lambda: engine.timeseries_export(
            since_wall=wall - cfg.window_s))
        section("profile", engine.profile_snapshot)
        section("memory", engine.memory_census)
        section("costs", engine.costs_snapshot)
        section("qos", engine.qos_snapshot)
        section("slo", engine.slo_snapshot)
        section("traces", lambda: self._worst_traces(engine))
        section("fingerprint", fingerprint)

        bundle = {
            "schema": 1,
            "id": bundle_id,
            "incident": incident,
            "trigger": trigger,
            "trigger_event": trigger_event,
            "note": note or "",
            "ts_wall": wall,
            "window_s": cfg.window_s,
            "post_window_s": cfg.post_window_s,
            "truncated": [],
            "sections": sections,
        }
        payload = self._bounded_payload(bundle)
        capture_ms = round((time.perf_counter() - t0) * 1e3, 3)
        meta = self.store.write(bundle_id, payload, {
            "incident": incident,
            "trigger": trigger,
            "ts_wall": wall,
            "capture_ms": capture_ms,
            "note": note or "",
            "truncated": bundle["truncated"],
        })
        total = self.store.total_bytes()
        with self._lock:
            self.captures += 1
            self.last_capture_ms = capture_ms
            self._last_bundle[trigger] = bundle_id
        if self._captures_total is not None:
            self._captures_total.inc(trigger=trigger)
        if self._bundle_bytes is not None:
            self._bundle_bytes.set(total)
        journal().emit(
            "blackbox", "captured",
            severity="WARNING" if auto else "INFO",
            trigger=trigger, bundle=bundle_id, incident=incident,
            bytes=meta["bytes"], capture_ms=capture_ms)
        return meta

    def _bounded_payload(self, bundle: dict) -> bytes:
        """Serialize under max_bundle_bytes, trimming the bulky
        sections (timeseries samples, journal tail, traces) before
        giving up on whole sections."""
        cap = self.config.max_bundle_bytes
        payload = json.dumps(bundle).encode("utf-8")
        trims = ("timeseries", "journal", "traces", "profile")
        for name in trims:
            if len(payload) <= cap:
                return payload
            sec = bundle["sections"].get(name)
            if isinstance(sec, dict):
                for key in ("samples", "events", "worst"):
                    if isinstance(sec.get(key), list) and sec[key]:
                        sec[key] = sec[key][-max(
                            1, len(sec[key]) // 4):]
            if name not in bundle["truncated"]:
                bundle["truncated"].append(name)
            payload = json.dumps(bundle).encode("utf-8")
        while len(payload) > cap and any(
                not isinstance(v, str)
                for v in bundle["sections"].values()):
            # Still over: drop the largest section wholesale.
            largest = max(
                (k for k, v in bundle["sections"].items()
                 if not isinstance(v, str)),
                key=lambda k: len(json.dumps(bundle["sections"][k])))
            bundle["sections"][largest] = "truncated"
            if largest not in bundle["truncated"]:
                bundle["truncated"].append(largest)
            payload = json.dumps(bundle).encode("utf-8")
        return payload

    def _worst_traces(self, engine) -> dict:
        """Stitched Chrome traces of the slowest recently completed
        requests (the requests an incident postmortem asks about)."""
        k = self.config.worst_requests
        if k <= 0:
            return {"worst": []}
        traces = engine.request_traces.snapshot()
        traces.sort(key=lambda t: t.wall_time_ms, reverse=True)
        worst = []
        for t in traces[:k]:
            entry = {
                "trace_id": t.trace_id,
                "model": t.model_name,
                "request_id": t.request_id,
                "wall_time_ms": t.wall_time_ms,
                "ok": t.ok,
            }
            if t.error:
                entry["error"] = t.error
            try:
                entry["chrome"] = engine.request_trace_export(t.trace_id)
            except Exception as exc:  # noqa: BLE001 — partial is fine
                entry["chrome"] = {"error": str(exc)}
            worst.append(entry)
        return {"worst": worst}

    # -- crash path -----------------------------------------------------------

    def crash_capture(self, error: str = "",
                      kind: str = "crash") -> dict | None:
        """Best-effort mini-bundle for a dying process: journal tail +
        fingerprint only (engine state may be the thing that broke).
        Never raises."""
        try:
            from client_tpu.observability.events import journal

            bundle_id = f"bb-{os.getpid()}-{_next_seq():04d}-{kind}"
            bundle = {
                "schema": 1,
                "id": bundle_id,
                "incident": f"inc-{uuid.uuid4().hex[:12]}",
                "trigger": "crash",
                "trigger_event": None,
                "note": error,
                # tpulint: allow[wall-clock] crash stamp for the bundle
                "ts_wall": time.time(),
                "truncated": [],
                "sections": {
                    "journal": journal().export(
                        limit=self.config.journal_tail),
                    "fingerprint": fingerprint(),
                },
            }
            payload = self._bounded_payload(bundle)
            return self.store.write(bundle_id, payload, {
                "incident": bundle["incident"],
                "trigger": "crash",
                "ts_wall": bundle["ts_wall"],
                "note": error,
                "truncated": bundle["truncated"],
            })
        except Exception:  # noqa: BLE001 — the process is dying; the
            _log.debug("blackbox crash capture failed", exc_info=True)
            return None     # hook chain must continue regardless

    # -- read surface ---------------------------------------------------------

    def bundles(self, bundle_id: str | None = None) -> dict:
        """``GET /v2/debug/bundles[/{id}]`` body. Raises KeyError for
        an unknown id, ValueError for a malformed id or corrupt file."""
        if bundle_id:
            return self.store.load(bundle_id)
        with self._lock:
            stats = {"captures": self.captures,
                     "suppressed": self.suppressed,
                     "failures": self.failures,
                     "last_capture_ms": self.last_capture_ms}
        return {
            "enabled": self.config.enabled,
            "dir": self.store.directory,
            "triggers": list(self.config.triggers),
            "bundles": self.store.list(),
            "total_bytes": self.store.total_bytes(),
            **stats,
        }

    def snapshot(self) -> dict:
        """Config + counters for debug surfaces and tests."""
        with self._lock:
            return {
                "enabled": self.config.enabled,
                "dir": self.store.directory,
                "triggers": list(self.config.triggers),
                "captures": self.captures,
                "suppressed": self.suppressed,
                "failures": self.failures,
                "pending": len(self._pending),
                "last_capture_ms": self.last_capture_ms,
            }


# -- crash hooks ---------------------------------------------------------------

_hooks_lock = threading.Lock()
_hooks_installed = False
_hook_recorders: list = []  # weakrefs to installed recorders
_atexit_done = False


def install_crash_hooks(recorder: BlackboxRecorder | None = None) -> None:
    """Arm the crash evidence path (idempotent):

    - ``faulthandler`` — fatal signals (SIGSEGV/SIGABRT/...) dump every
      thread's stack to stderr;
    - ``sys.excepthook`` — an unhandled exception writes the journal
      tail to stderr as one JSON line plus a best-effort mini-bundle to
      every live recorder's store, then chains to the previous hook;
    - ``atexit`` — a final journal tail lands on disk when the process
      exits with a recorder still armed, so even a quiet death leaves
      evidence.
    """
    global _hooks_installed
    with _hooks_lock:
        if recorder is not None:
            _hook_recorders.append(weakref.ref(recorder))
        if _hooks_installed:
            return
        _hooks_installed = True
    try:
        if not faulthandler.is_enabled():
            faulthandler.enable(file=sys.stderr)
    except Exception:  # noqa: BLE001 — stderr may be closed/invalid
        _log.debug("faulthandler.enable failed", exc_info=True)
    previous = sys.excepthook

    def _blackbox_excepthook(etype, value, tb):
        _crash_flush(f"{etype.__name__}: {value}", to_stderr=True)
        previous(etype, value, tb)

    sys.excepthook = _blackbox_excepthook
    atexit.register(_atexit_flush)


def _live_recorders() -> list:
    with _hooks_lock:
        refs = list(_hook_recorders)
    return [r for r in (ref() for ref in refs) if r is not None]


def _crash_flush(error: str, *, to_stderr: bool) -> None:
    """Write the final journal tail to stderr (one JSON line) and a
    mini-bundle per live recorder. Never raises."""
    tail = None
    try:
        from client_tpu.observability.events import journal

        tail = journal().export(limit=64)
    except Exception:  # noqa: BLE001 — dying process
        _log.debug("crash flush: journal export failed", exc_info=True)
    if to_stderr:
        try:
            line = json.dumps({
                "blackbox": "crash",
                "error": error,
                "journal_tail": (tail or {}).get("events", []),
            })
            print(line, file=sys.stderr, flush=True)
        except Exception:  # noqa: BLE001 — stderr may be gone
            _log.debug("crash flush: stderr write failed",
                       exc_info=True)
    for rec in _live_recorders():
        rec.crash_capture(error)


def _atexit_flush() -> None:
    """One final journal tail per recorder at interpreter exit (normal
    or post-exception). Never raises; runs at most once."""
    global _atexit_done
    with _hooks_lock:
        if _atexit_done:
            return
        _atexit_done = True
    recorders = _live_recorders()
    if not recorders:
        return
    try:
        from client_tpu.observability.events import journal

        tail = journal().export(limit=64)
    except Exception:  # noqa: BLE001 — dying process
        return
    if not tail.get("events"):
        return
    payload = json.dumps({
        "blackbox": "final",
        "journal_tail": tail,
        "fingerprint": fingerprint(),
    }).encode("utf-8")
    for rec in recorders:
        try:
            os.makedirs(rec.store.directory, exist_ok=True)
            path = os.path.join(rec.store.directory,
                                f"final_journal_{os.getpid()}.jsonl")
            with open(path, "wb") as f:
                f.write(payload + b"\n")
        except Exception:  # noqa: BLE001 — exit path stays silent
            _log.debug("atexit journal flush failed", exc_info=True)
