"""Per-model sliding-window SLO tracking with multi-window burn rates.

The serving-side analogue of perf_analyzer's windowed analysis: instead
of an offline report, the engine continuously scores itself against
configured objectives and surfaces the result live (``GET /v2/slo``,
``tpu_slo_*`` gauges, and DEGRADED on ``/v2/health/ready`` under fast
burn).

Two objective kinds per model:

* **availability** — fraction of requests that must succeed (errors are
  scheduler-level failures: injected 5xx, execution errors, deadline
  expiry). Admission sheds (429) are deliberate load management, not SLO
  violations, and do not count.
* **latency** — fraction of *successful* requests that must finish under
  ``latency_threshold_us`` (0 disables this objective).

Burn rate follows the SRE-workbook definition: the rate the error budget
is being consumed, normalised so 1.0 means "exactly on budget" —
``bad_fraction / (1 - target)``. Alerting is multi-window: fast burn is
declared only when BOTH the short (5 m) and long (1 h) windows exceed
``fast_burn_threshold`` (default 14.4 ≈ 2% of a 30-day budget per hour),
so a brief blip cannot flip health but a sustained failure does within
minutes.

Configuration mirrors ``CLIENT_TPU_ADMISSION``: the ``CLIENT_TPU_SLO``
environment variable holds inline JSON or ``@/path/to/slo.json``::

    CLIENT_TPU_SLO='{"availability": 0.999,
        "latency_threshold_us": 50000, "latency_target": 0.99,
        "models": {"bert_base": {"availability": 0.99}}}'

Unset means SLO tracking is off: recording is a no-op and health is
unaffected (tier-1 default).
"""

from __future__ import annotations

import json
import os
from client_tpu import config as envcfg
from client_tpu.utils import lockdep
import time
from dataclasses import dataclass, field

__all__ = [
    "ENV_VAR",
    "WINDOWS",
    "SloConfig",
    "SloTracker",
]

ENV_VAR = "CLIENT_TPU_SLO"

# Multi-window pair from the SRE workbook's fast-burn alert: the long
# window proves the burn is sustained, the short window makes the alert
# reset quickly once the problem stops.
WINDOWS = (("5m", 300), ("1h", 3600))
_LONG_WINDOW_S = max(s for _, s in WINDOWS)


@dataclass
class SloConfig:
    """Objectives; per-model overrides under ``models``."""

    # Target success fraction in (0, 1).
    availability: float = 0.999
    # Latency objective: `latency_target` of successful requests must
    # complete under this many microseconds; 0 disables the objective.
    latency_threshold_us: float = 0.0
    latency_target: float = 0.99
    # Both windows must burn at/above this to flip health to DEGRADED.
    fast_burn_threshold: float = 14.4
    models: dict[str, dict] = field(default_factory=dict)
    # False when CLIENT_TPU_SLO is unset: record() is a no-op and
    # fast_burn() never fires.
    enabled: bool = True

    _FIELDS = ("availability", "latency_threshold_us", "latency_target",
               "fast_burn_threshold")

    def __post_init__(self):
        if not 0.0 < self.availability < 1.0:
            raise ValueError("availability target must be in (0, 1)")
        if not 0.0 < self.latency_target < 1.0:
            raise ValueError("latency_target must be in (0, 1)")
        if self.latency_threshold_us < 0:
            raise ValueError("latency_threshold_us must be >= 0")
        if self.fast_burn_threshold <= 0:
            raise ValueError("fast_burn_threshold must be > 0")

    @classmethod
    def from_dict(cls, d: dict) -> "SloConfig":
        d = dict(d or {})
        models = d.pop("models", {}) or {}
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(f"unknown SLO config keys: {sorted(unknown)}")
        for name, override in models.items():
            bad = set(override) - set(cls._FIELDS)
            if bad:
                raise ValueError(
                    f"unknown SLO config keys for model '{name}': "
                    f"{sorted(bad)}")
        return cls(models=models, **d)

    @classmethod
    def from_env(cls, environ=os.environ) -> "SloConfig":
        raw = envcfg.env_text(ENV_VAR, environ)
        if not raw:
            return cls(enabled=False)
        if raw.startswith("@"):
            with open(raw[1:], "r", encoding="utf-8") as f:
                raw = f.read()
        return cls.from_dict(json.loads(raw))

    def for_model(self, name: str) -> "SloConfig":
        override = self.models.get(name)
        if not override:
            return self
        merged = {f: getattr(self, f) for f in self._FIELDS}
        merged.update(override)
        return SloConfig(enabled=self.enabled, **merged)


class _SecondRing:
    """Per-second (total, errors, slow) counters over the last hour.
    Fixed array indexed by ``second % size``; a slot whose stored second
    is stale is reset on write and skipped on read — O(1) record, O(size)
    window sum with plain ints."""

    __slots__ = ("size", "seconds", "total", "errors", "slow")

    def __init__(self, size: int = _LONG_WINDOW_S + 1):
        self.size = size
        self.seconds = [-1] * size
        self.total = [0] * size
        self.errors = [0] * size
        self.slow = [0] * size

    def record(self, sec: int, error: bool, slow: bool) -> None:
        i = sec % self.size
        if self.seconds[i] != sec:
            self.seconds[i] = sec
            self.total[i] = 0
            self.errors[i] = 0
            self.slow[i] = 0
        self.total[i] += 1
        if error:
            self.errors[i] += 1
        if slow:
            self.slow[i] += 1

    def window(self, now_sec: int, window_s: int) -> tuple[int, int, int]:
        lo = now_sec - window_s
        total = errors = slow = 0
        for i in range(self.size):
            s = self.seconds[i]
            if lo < s <= now_sec:
                total += self.total[i]
                errors += self.errors[i]
                slow += self.slow[i]
        return total, errors, slow


class _ModelSlo:
    __slots__ = ("cfg", "ring", "lock")

    def __init__(self, cfg: SloConfig):
        self.cfg = cfg
        self.ring = _SecondRing()
        self.lock = lockdep.Lock("observability.slo.model")


def _burn(bad: int, total: int, target: float) -> float:
    if total <= 0:
        return 0.0
    return (bad / total) / (1.0 - target)


class SloTracker:
    """Records request outcomes per model and scores the two windows.

    The engine calls :meth:`record` from the stats funnel (one call per
    finally-responded request), the health check calls :meth:`fast_burn`,
    and both ``GET /v2/slo`` and the metrics render call
    :meth:`snapshot` (which also refreshes the ``tpu_slo_*`` gauges).
    """

    def __init__(self, config: SloConfig | None = None, registry=None,
                 clock=time.monotonic):
        self.config = config or SloConfig(enabled=False)
        self._clock = clock
        self._lock = lockdep.Lock("observability.slo")
        self._models: dict[str, _ModelSlo] = {}
        self._burn_gauge = None
        self._fast_gauge = None
        self._target_gauge = None
        if registry is not None:
            self.bind_metrics(registry)

    @classmethod
    def from_env(cls, registry=None, environ=os.environ) -> "SloTracker":
        return cls(SloConfig.from_env(environ), registry=registry)

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def bind_metrics(self, registry) -> None:
        self._burn_gauge = registry.gauge(
            "tpu_slo_burn_rate",
            "SLO error-budget burn rate per model, objective "
            "(availability|latency) and window (1.0 = exactly on budget)",
            ("model", "objective", "window"))
        self._fast_gauge = registry.gauge(
            "tpu_slo_fast_burn",
            "1 while the model burns budget above fast_burn_threshold in "
            "BOTH windows (health reports DEGRADED)",
            ("model",))
        self._target_gauge = registry.gauge(
            "tpu_slo_objective_target",
            "Configured SLO target per model and objective",
            ("model", "objective"))

    # -- write path ----------------------------------------------------------

    def _model(self, name: str) -> _ModelSlo:
        m = self._models.get(name)
        if m is None:
            with self._lock:
                m = self._models.setdefault(
                    name, _ModelSlo(self.config.for_model(name)))
        return m

    def record(self, model: str, success: bool,
               duration_us: float | None = None) -> None:
        """One finally-responded request. ``duration_us`` feeds the
        latency objective (successes only; failures already count against
        availability)."""
        if not self.config.enabled:
            return
        m = self._model(model)
        slow = bool(
            success and m.cfg.latency_threshold_us > 0
            and duration_us is not None
            and duration_us > m.cfg.latency_threshold_us)
        sec = int(self._clock())
        with m.lock:
            m.ring.record(sec, error=not success, slow=slow)

    # -- read path -----------------------------------------------------------

    def _model_report(self, name: str, m: _ModelSlo, now_sec: int) -> dict:
        cfg = m.cfg
        windows = {}
        fast = {"availability": True,
                "latency": cfg.latency_threshold_us > 0}
        with m.lock:
            counts = {label: m.ring.window(now_sec, secs)
                      for label, secs in WINDOWS}
        for label, (total, errors, slow) in counts.items():
            avail_burn = _burn(errors, total, cfg.availability)
            lat_burn = (_burn(slow, total, cfg.latency_target)
                        if cfg.latency_threshold_us > 0 else 0.0)
            if avail_burn < cfg.fast_burn_threshold:
                fast["availability"] = False
            if lat_burn < cfg.fast_burn_threshold:
                fast["latency"] = False
            windows[label] = {
                "requests": total,
                "errors": errors,
                "slow": slow,
                "availability_burn_rate": round(avail_burn, 4),
                "latency_burn_rate": round(lat_burn, 4),
            }
        fast_burn = fast["availability"] or fast["latency"]
        return {
            "objectives": {
                "availability": cfg.availability,
                "latency_threshold_us": cfg.latency_threshold_us,
                "latency_target": cfg.latency_target,
                "fast_burn_threshold": cfg.fast_burn_threshold,
            },
            "windows": windows,
            "fast_burn": fast_burn,
        }

    def snapshot(self) -> dict:
        """The ``GET /v2/slo`` response; refreshes the gauges as a side
        effect so a scrape after a quiet period still reads current
        burn rates."""
        now_sec = int(self._clock())
        with self._lock:
            models = sorted(self._models.items())
        out_models = {}
        for name, m in models:
            report = self._model_report(name, m, now_sec)
            out_models[name] = report
            self._update_gauges(name, m, report)
        return {
            "enabled": self.config.enabled,
            "windows": {label: secs for label, secs in WINDOWS},
            "models": out_models,
        }

    def _update_gauges(self, name: str, m: _ModelSlo, report: dict) -> None:
        if self._burn_gauge is None:
            return
        for label, w in report["windows"].items():
            self._burn_gauge.set(w["availability_burn_rate"], model=name,
                                 objective="availability", window=label)
            if m.cfg.latency_threshold_us > 0:
                self._burn_gauge.set(w["latency_burn_rate"], model=name,
                                     objective="latency", window=label)
        self._fast_gauge.set(1 if report["fast_burn"] else 0, model=name)
        self._target_gauge.set(m.cfg.availability, model=name,
                               objective="availability")
        if m.cfg.latency_threshold_us > 0:
            self._target_gauge.set(m.cfg.latency_target, model=name,
                                   objective="latency")

    def fast_burn(self) -> list[str]:
        """Models currently fast-burning (both windows over threshold);
        empty when tracking is disabled or everything is on budget."""
        if not self.config.enabled:
            return []
        now_sec = int(self._clock())
        with self._lock:
            models = sorted(self._models.items())
        return [name for name, m in models
                if self._model_report(name, m, now_sec)["fast_burn"]]
