"""Prometheus-style metric primitives: Counter, Gauge, Histogram.

The engine's original ``/metrics`` endpoint exported only cumulative sums
(Triton nv_inference_* vocabulary) — enough for rates, useless for tails.
These primitives add explicit-bucket histograms (the p50/p99 the ROADMAP
north-star is judged by) and point-in-time gauges (queue depth, in-flight
batches, device HBM), rendered in text exposition format 0.0.4 alongside
the legacy counters by ``TpuEngine.prometheus_metrics``.

Design notes:
- ``MetricRegistry.histogram/gauge/counter`` are get-or-create (idempotent
  per name); re-declaring a name with a different type/labels raises.
- Child series (one per label combination) are created lazily via
  ``labels(...)`` and cached; hot-path ``observe``/``inc`` is a bisect plus
  a few adds under a per-family lock.
- Rendering emits HELP then TYPE then samples per family, label values
  escaped per the exposition spec, histogram buckets cumulative with a
  terminal ``+Inf`` equal to ``_count``.
- ``render(openmetrics=True)`` switches to OpenMetrics 1.0 exposition:
  counter samples take the ``_total`` suffix (family advertised by its
  base name), histogram buckets carry ``# {trace_id="..."} value``
  exemplars (the last observation that landed in each bucket, when the
  caller supplied one), and the body ends with the mandatory ``# EOF``.
"""

from __future__ import annotations

from client_tpu.utils import lockdep
from bisect import bisect_left

# Microsecond latency ladder: sub-ms queue hops through multi-second
# first-compile requests (16 finite buckets keeps series count modest).
DURATION_US_BUCKETS = (
    50, 100, 250, 500, 1000, 2500, 5000, 10_000, 25_000, 50_000,
    100_000, 250_000, 500_000, 1_000_000, 5_000_000, 30_000_000,
)
# Batch-size ladder matches power_buckets() padding (scheduler.py).
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)


def escape_label_value(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v) -> str:
    """Render a sample value: integral floats print as integers."""
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _label_str(labelnames, labelvalues) -> str:
    return ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in zip(labelnames, labelvalues))


class _Metric:
    """One metric family: name, help, a child per label-value combination."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, object] = {}
        self._lock = lockdep.Lock("metrics.family")

    def labels(self, *values, **kw):
        if kw:
            values = tuple(kw[k] for k in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"metric '{self.name}' takes labels {self.labelnames}, "
                f"got {values}")
        values = tuple(str(v) for v in values)
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    values, self._make_child())
        return child

    def _make_child(self):
        raise NotImplementedError

    def remove(self, **labels):
        """Drop one child series — for gauges tracking a resource that no
        longer exists (a detached shm ring, an unloaded model), where the
        last-set value would otherwise render stale forever."""
        values = tuple(str(labels[k]) for k in self.labelnames)
        with self._lock:
            self._children.pop(values, None)

    def _family_name(self, openmetrics: bool) -> str:
        return self.name

    def collect(self, openmetrics: bool = False) -> list[str]:
        fam = self._family_name(openmetrics)
        lines = [f"# HELP {fam} {escape_help(self.help)}",
                 f"# TYPE {fam} {self.kind}"]
        with self._lock:
            items = sorted(self._children.items())
        for values, child in items:
            lines.extend(self._render_child(values, child, openmetrics))
        return lines

    def _render_child(self, values, child,
                      openmetrics: bool = False) -> list[str]:
        raise NotImplementedError


class _Value:
    __slots__ = ("v", "lock")

    def __init__(self):
        self.v = 0.0
        self.lock = lockdep.Lock("metrics.value")


class _CounterValue:
    __slots__ = ("v", "exemplar", "lock")

    def __init__(self):
        self.v = 0.0
        # Last (increment, trace_id) supplied with an exemplar; rendered
        # on the OpenMetrics `_total` sample (the spec permits counter
        # exemplars) linking the series to /v2/trace/requests.
        self.exemplar: tuple[float, str] | None = None
        self.lock = lockdep.Lock("metrics.value")


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterValue()

    def inc(self, amount: float = 1.0, exemplar: str | None = None,
            **labels):
        """Add ``amount``; ``exemplar`` (a trace_id) is retained as the
        series' last exemplar for OpenMetrics rendering."""
        child = self.labels(**labels) if self.labelnames else self.labels()
        with child.lock:
            child.v += amount
            if exemplar:
                child.exemplar = (float(amount), str(exemplar))

    def _family_name(self, openmetrics: bool) -> str:
        # OpenMetrics advertises the counter by its base name and
        # suffixes every sample with `_total`.
        if openmetrics and self.name.endswith("_total"):
            return self.name[:-len("_total")]
        return self.name

    def _render_child(self, values, child,
                      openmetrics: bool = False) -> list[str]:
        ls = _label_str(self.labelnames, values)
        body = f"{{{ls}}}" if ls else ""
        name = self.name
        ex = ""
        if openmetrics:
            name = self._family_name(True) + "_total"
            if child.exemplar is not None:
                v, trace_id = child.exemplar
                ex = (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                      f"{format_value(v)}")
        return [f"{name}{body} {format_value(child.v)}{ex}"]


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self):
        return _Value()

    def set(self, value: float, **labels):
        child = self.labels(**labels) if self.labelnames else self.labels()
        with child.lock:
            child.v = float(value)

    def inc(self, amount: float = 1.0, **labels):
        child = self.labels(**labels) if self.labelnames else self.labels()
        with child.lock:
            child.v += amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def _render_child(self, values, child,
                      openmetrics: bool = False) -> list[str]:
        ls = _label_str(self.labelnames, values)
        body = f"{{{ls}}}" if ls else ""
        return [f"{self.name}{body} {format_value(child.v)}"]


class _HistValue:
    __slots__ = ("counts", "sum", "exemplars", "lock")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 for the +Inf bucket
        # Last (value, trace_id) that landed in each bucket; OpenMetrics
        # exemplars linking a bucket straight to /v2/trace/requests.
        self.exemplars: list[tuple[float, str] | None] = \
            [None] * (n_buckets + 1)
        self.sum = 0.0
        self.lock = lockdep.Lock("metrics.value")


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 labelnames: tuple[str, ...] = (),
                 buckets=DURATION_US_BUCKETS):
        super().__init__(name, help_text, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram '{name}' needs >= 1 finite bucket")
        self.buckets = tuple(bs)

    def _make_child(self):
        return _HistValue(len(self.buckets))

    def observe(self, value: float, exemplar: str | None = None, **labels):
        """Record ``value``; ``exemplar`` (a trace_id) is retained as the
        bucket's last exemplar for OpenMetrics rendering."""
        child = self.labels(**labels) if self.labelnames else self.labels()
        idx = bisect_left(self.buckets, value)
        with child.lock:
            child.counts[idx] += 1
            child.sum += value
            if exemplar:
                child.exemplars[idx] = (float(value), str(exemplar))

    def _render_child(self, values, child,
                      openmetrics: bool = False) -> list[str]:
        ls = _label_str(self.labelnames, values)
        with child.lock:
            counts = list(child.counts)
            exemplars = list(child.exemplars)
            total_sum = child.sum
        lines = []
        cum = 0
        sep = "," if ls else ""

        def _ex(i: int) -> str:
            if not openmetrics or exemplars[i] is None:
                return ""
            v, trace_id = exemplars[i]
            return (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                    f"{format_value(v)}")

        for i, (le, n) in enumerate(zip(self.buckets, counts)):
            cum += n
            lines.append(
                f'{self.name}_bucket{{{ls}{sep}le="{format_value(le)}"}} '
                f"{cum}{_ex(i)}")
        cum += counts[-1]
        lines.append(f'{self.name}_bucket{{{ls}{sep}le="+Inf"}} {cum}'
                     f"{_ex(len(counts) - 1)}")
        body = f"{{{ls}}}" if ls else ""
        lines.append(f"{self.name}_sum{body} {format_value(total_sum)}")
        lines.append(f"{self.name}_count{body} {cum}")
        return lines


class MetricRegistry:
    """Ordered collection of metric families with get-or-create semantics."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = lockdep.Lock("metrics.registry")

    def _get_or_create(self, cls, name, help_text, labelnames, **kw):
        labelnames = tuple(labelnames or ())
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != labelnames):
                    raise ValueError(
                        f"metric '{name}' already registered as "
                        f"{type(existing).__name__}{existing.labelnames}")
                return existing
            m = cls(name, help_text, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_text, labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text, labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name, help_text, labelnames=(),
                  buckets=DURATION_US_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labelnames,
                                   buckets=buckets)

    def render(self, openmetrics: bool = False) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.collect(openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + ("\n" if lines else "")


# Process-wide default registry for library users that want one aggregation
# point across engines; TpuEngine defaults to a private registry per
# instance (so concurrent engines in one process don't cross-pollute their
# /metrics) but accepts this via TpuEngine(metrics_registry=REGISTRY).
REGISTRY = MetricRegistry()


class ModelInstruments:
    """Per-model:version bound handles for the hot-path observations."""

    __slots__ = ("_em", "_labels")

    def __init__(self, em: "EngineMetrics", model: str, version: str):
        self._em = em
        self._labels = {"model": model, "version": version}

    def observe_request(self, total_ns: int, times,
                        trace_id: str | None = None,
                        tenant: str = "") -> None:
        em = self._em
        lab = self._labels
        em.request_duration_us.observe(max(0, total_ns) / 1e3,
                                       exemplar=trace_id,
                                       tenant=tenant or "default", **lab)
        em.phase_duration_us.observe(times.queue_ns / 1e3,
                                     phase="queue", **lab)
        em.phase_duration_us.observe(times.compute_input_ns / 1e3,
                                     phase="compute_input", **lab)
        em.phase_duration_us.observe(times.compute_infer_ns / 1e3,
                                     phase="compute_infer", **lab)
        em.phase_duration_us.observe(times.compute_output_ns / 1e3,
                                     phase="compute_output", **lab)

    def observe_execution(self, batch_size: int) -> None:
        self._em.batch_size.observe(batch_size, **self._labels)

    def record_rejection(self) -> None:
        self._em.queue_rejections.inc(**self._labels)

    def record_deadline_expired(self, stage: str) -> None:
        self._em.deadline_expirations.inc(stage=stage, **self._labels)

    def record_admission_rejection(self, reason: str,
                                   tenant: str = "") -> None:
        self._em.admission_rejections.inc(reason=reason,
                                          tenant=tenant or "default",
                                          **self._labels)


class EngineMetrics:
    """The engine's standard metric vocabulary on one registry.

    Histograms: tpu_request_duration_us, tpu_phase_duration_us{phase},
    tpu_batch_size. Gauges: tpu_queue_depth, tpu_inflight_batches,
    tpu_device_hbm_bytes_in_use, tpu_hbm_limit_bytes, tpu_hbm_peak_bytes,
    tpu_drain_duration_seconds. Counters: tpu_queue_rejections_total,
    tpu_admission_rejections_total{reason},
    tpu_deadline_expirations_total{stage}.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()
        r = self.registry
        self.request_duration_us = r.histogram(
            "tpu_request_duration_us",
            "End-to-end successful request duration (microseconds); "
            "tenant is the cost-ledger tag (bounded: registered tenants "
            "+ default + shadow, overflow folds to other)",
            ("model", "version", "tenant"))
        self.phase_duration_us = r.histogram(
            "tpu_phase_duration_us",
            "Per-phase request duration (microseconds)",
            ("model", "version", "phase"))
        self.batch_size = r.histogram(
            "tpu_batch_size",
            "Requests per model execution (batch size)",
            ("model", "version"), buckets=BATCH_SIZE_BUCKETS)
        self.queue_depth = r.gauge(
            "tpu_queue_depth",
            "Requests waiting in the scheduler queue",
            ("model", "version"))
        self.inflight_batches = r.gauge(
            "tpu_inflight_batches",
            "Batches currently executing on device",
            ("model", "version"))
        self.hbm_bytes = r.gauge(
            "tpu_device_hbm_bytes_in_use",
            "Device HBM bytes in use (0 when the platform does not report "
            "memory stats, e.g. CPU)",
            ("device",))
        self.hbm_limit_bytes = r.gauge(
            "tpu_hbm_limit_bytes",
            "Device HBM capacity limit (0 when the platform does not "
            "report memory stats, e.g. CPU)",
            ("device",))
        self.hbm_peak_bytes = r.gauge(
            "tpu_hbm_peak_bytes",
            "Peak device HBM bytes in use since process start (0 when the "
            "platform does not report memory stats, e.g. CPU)",
            ("device",))
        self.hbm_census_bytes = r.gauge(
            "tpu_hbm_census_bytes",
            "Live device-buffer bytes attributed to an owner by the HBM "
            "census (component: weights, kv_arena, embedding, rowcache, "
            "autotune_warm; the unattributed remainder rides with "
            "model=\"\", component=\"unattributed\")",
            ("model", "component"))
        self.hbm_plan_drift_bytes = r.gauge(
            "tpu_hbm_plan_drift_bytes",
            "Planner-reservation bytes minus census-actual bytes per "
            "owner (positive: the arena reserved more than is live; "
            "negative: live memory the plan never charged)",
            ("model", "component"))
        self.hbm_census_watermark_bytes = r.gauge(
            "tpu_hbm_census_watermark_bytes",
            "High-water committed device bytes observed by the census "
            "since process start")
        self.hbm_census_watermark_bytes.set(0)
        self.queue_rejections = r.counter(
            "tpu_queue_rejections_total",
            "Requests rejected at admission (backpressure, HTTP 429)",
            ("model", "version"))
        self.admission_rejections = r.counter(
            "tpu_admission_rejections_total",
            "Requests shed by the admission controller, by reason "
            "(queue_depth, estimated_wait, concurrency, throttled, "
            "draining) and cost-ledger tenant tag",
            ("model", "version", "reason", "tenant"))
        self.deadline_expirations = r.counter(
            "tpu_deadline_expirations_total",
            "Requests whose end-to-end deadline expired before the given "
            "stage ran (admission, queue, execute)",
            ("model", "version", "stage"))
        self.drain_duration = r.gauge(
            "tpu_drain_duration_seconds",
            "Wall time of the last graceful drain (0 until one runs)")
        self.drain_duration.set(0.0)
        self.qos_sheds = r.counter(
            "tpu_qos_sheds_total",
            "Requests shed by a per-class QoS gate, by class and reason "
            "(qos_inflight, qos_queue, qos_throttled)",
            ("qos_class", "reason"))
        self.qos_inflight = r.gauge(
            "tpu_qos_inflight",
            "Admitted-but-unfinished requests per QoS class",
            ("qos_class",))
        self.qos_throttle_ratio = r.gauge(
            "tpu_qos_throttle_ratio",
            "Governor throttle ratio per QoS class (1 = full configured "
            "token-bucket rate; the SLO-burn governor halves it per step)",
            ("qos_class",))
        self.qos_preemptions = r.counter(
            "tpu_qos_preemptions_total",
            "In-assembly batches split because a preempt-class request "
            "arrived (WFQ preemption)",
            ("model",))
        self._instruments: dict[tuple[str, str], ModelInstruments] = {}
        self._lock = lockdep.Lock("metrics.instruments")

    def model_instruments(self, model: str, version: str) -> ModelInstruments:
        key = (str(model), str(version))
        inst = self._instruments.get(key)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    inst = ModelInstruments(self, key[0], key[1])
                    # Copy-on-write: lock-free fast-path readers only ever
                    # see a fully-built dict, never one mid-mutation.
                    updated = dict(self._instruments)
                    updated[key] = inst
                    self._instruments = updated
        return inst

    def update_device_gauges(self, census=None) -> None:
        """Refresh per-device HBM usage, capacity and peak from the HBM
        census's device walk (:meth:`HbmCensus.device_stats` — the one
        device-memory source of truth); a private census is used when
        the caller doesn't pass one (standalone EngineMetrics). On
        platforms without memory stats (JAX_PLATFORMS=cpu) the gauges
        still render, pinned to 0 — byte-compatible with the pre-census
        ad-hoc ``memory_stats()`` scrape."""
        if census is None:
            from client_tpu.observability.memory import hbm_census

            census = hbm_census()
        devices = census.device_stats()
        for d in devices:
            self.hbm_bytes.set(d["bytes_in_use"], device=d["device"])
            self.hbm_limit_bytes.set(d["bytes_limit"], device=d["device"])
            self.hbm_peak_bytes.set(d["peak_bytes_in_use"],
                                    device=d["device"])
        if not devices:
            self.hbm_bytes.set(0, device="0")
            self.hbm_limit_bytes.set(0, device="0")
            self.hbm_peak_bytes.set(0, device="0")

    def update_census_gauges(self, report: dict) -> None:
        """Refresh the attribution gauges from one census report
        (:meth:`TpuEngine.memory_census`), called at scrape time like
        the device gauges above."""
        for row in report.get("owners", ()):
            self.hbm_census_bytes.set(row["bytes"], model=row["model"],
                                      component=row["component"])
            if "drift_bytes" in row:
                self.hbm_plan_drift_bytes.set(
                    row["drift_bytes"], model=row["model"],
                    component=row["component"])
        self.hbm_census_bytes.set(report.get("unattributed_bytes", 0),
                                  model="", component="unattributed")
        self.hbm_census_watermark_bytes.set(
            report.get("watermark_bytes", 0))

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics)


class RouterMetrics:
    """The L7 router's metric vocabulary (``tpu_router_*``).

    Lives on its own registry by default so a router co-located with
    engine replicas in one process scrapes only routing metrics from its
    ``/metrics`` (the engines keep their private registries). Balancing
    quality is read off ``tpu_router_requests_total{replica}`` — under
    uniform load the per-replica spread is the P2C acceptance check.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()
        r = self.registry
        self.requests = r.counter(
            "tpu_router_requests_total",
            "Requests forwarded, by replica and outcome (ok, error, "
            "pushback, unreachable)",
            ("replica", "outcome"))
        self.failovers = r.counter(
            "tpu_router_failovers_total",
            "Requests re-routed to another replica after the first "
            "candidate failed or pushed back, by failed replica",
            ("replica",))
        self.sheds = r.counter(
            "tpu_router_sheds_total",
            "Requests shed by the router itself: every candidate pushed "
            "back (all_pushback) or no replica was reachable (no_replica)",
            ("reason",))
        self.breaker_open = r.gauge(
            "tpu_router_breaker_open",
            "1 while the per-replica circuit breaker is open",
            ("replica",))
        self.replica_states = r.gauge(
            "tpu_router_replicas",
            "Replicas known to the router, by last observed health state",
            ("state",))
        self.request_duration_us = r.histogram(
            "tpu_router_request_duration_us",
            "Router-observed request duration including failovers "
            "(microseconds)",
            ("replica",))
        self.load_report_age = r.gauge(
            "tpu_router_load_report_age_seconds",
            "Seconds since each replica's load report was refreshed "
            "(piggyback or /v2/load poll)",
            ("replica",))
        self.affinity_routed = r.counter(
            "tpu_router_affinity_routed_total",
            "Requests pinned to a replica by sequence-id rendezvous "
            "affinity rather than P2C",
            ("replica",))
        self.drain_steps = r.counter(
            "tpu_router_drain_steps_total",
            "Rolling-drain steps executed, by replica and outcome "
            "(clean, dirty, timeout, skipped)",
            ("replica", "outcome"))
        self.fleet_drift_score = r.gauge(
            "tpu_fleet_drift_score",
            "Per-replica drift from the fleet median, by signal "
            "(duty_cycle, fill_ratio, wave_ms_p50, wait_s); unitless "
            "|v-median|/max(|median|,floor) skew",
            ("replica", "signal"))
        self.fleet_fetch_failures = r.counter(
            "tpu_fleet_fetch_failures_total",
            "Per-replica fetch failures while federating a fleet "
            "surface (events, profile, metrics, slo, trace)",
            ("replica", "surface"))

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics)
