"""TPU kernels for the hot ops (Pallas) and their reference fallbacks.

The reference stack has no counterpart — its compute lives behind the
dlopen'd server. Here the serving engine owns the compute path, so the ops
layer is where hand-written TPU kernels live: memory-bound or
fusion-resistant pieces XLA doesn't schedule optimally on its own.
"""

from client_tpu.ops.decode_kernel import (  # noqa: F401
    decode_wave_attention,
    reference_decode_attention,
)
from client_tpu.ops.flash_attention import flash_attention  # noqa: F401
