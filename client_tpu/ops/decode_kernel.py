"""Fused decode-wave kernel: KV scatter + masked single-query attention.

The generative engine's hot loop is the decode wave
(engine/generative.py): for every live stream, write the new token's K/V
row into the KV arena at ``(row, len)`` and attend the stream's query over
its valid prefix.  The reference path (models/generate.py ``decode_fn``)
does this as stacked XLA ops — ``arena.at[li, rows, lens].set`` followed by
``arena[li, rows]``, which materializes a fresh ``[B, S, H, D]`` gather of
every lane's row in HBM per layer per wave, then runs a dense masked
softmax over the static ``max_seq_len`` axis.

This kernel fuses the scatter and the attention into one Pallas grid so
the arena row is streamed through VMEM exactly once (arXiv 2308.15152's
shared-memory-footprint discipline): grid ``(B, S // block_s)`` with the
key-block index innermost, the lane's ``(row, len)`` pair arriving via
scalar prefetch (``PrefetchScalarGridSpec``) so the BlockSpec index maps
gather each lane's row directly out of the arena — no ``[B, S, H, D]``
intermediate exists anywhere.  The arena update is in place via
``input_output_aliases``: each visited block is copied through VMEM
unchanged except the scatter block, where the new K/V row is inserted at
``len % block_s`` with an iota mask (TPU vector stores want static
offsets).  Attention follows ``_fa_kernel``'s online-softmax carry
(ops/flash_attention.py:31) with a *strict* ``pos < len`` mask over the
old arena content; the new token's contribution (position ``len``, whose
value is exactly the k/v being scattered) is folded in at the finalize
step from registers — so the kernel never depends on reading back its own
scatter, and block write-back order cannot matter.

``interpret=True`` runs the same kernel on CPU; the tier-1 suite and
ci_check drive it that way (tests/test_ops.py parity suite).  The sharded
cross-chip variant wraps this kernel per shard — see
client_tpu/parallel/kv_shard.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def pick_block_s(seq_len: int, cap: int = 128) -> int:
    """Largest multiple-of-8 divisor of ``seq_len`` up to ``cap`` (falls
    back to ``seq_len`` itself when no aligned divisor exists) — the same
    rule the flash prefill path uses to keep TPU tiles (8, 128)-friendly
    while still exercising a multi-block grid at test sizes."""
    best = None
    for cand in range(8, min(cap, seq_len) + 1, 8):
        if seq_len % cand == 0:
            best = cand
    return best if best is not None else seq_len


def _decode_kernel(rows_ref, lens_ref,           # scalar prefetch
                   k_ref, v_ref, q_ref, kn_ref, vn_ref,   # inputs
                   ko_ref, vo_ref, o_ref,                 # outputs
                   m_ref, l_ref, acc_ref,                 # VMEM scratch
                   *, block_s: int, sm_scale: float):
    """One (lane, key-block) grid step; key blocks iterate innermost so the
    scratch carries the online-softmax state across one lane's row."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ik = pl.program_id(1)
    nk = pl.num_programs(1)
    length = lens_ref[b]                 # valid prefix length (strict)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_blk = k_ref[0, 0]                  # [block_s, H, D] (old content)
    v_blk = v_ref[0, 0]
    q = q_ref[0]                         # [H, D]
    kn = kn_ref[0]                       # [H, D]
    vn = vn_ref[0]

    # Copy-through scatter: every block writes back what it read, except
    # the scatter block inserts the new K/V row at position `length`.
    # Writing every block (out index map == in index map) keeps the
    # aliased arena well-defined under any block write-back schedule; a
    # write-once-at-the-scatter-block design would depend on unwritten
    # output windows preserving their aliased input, which Pallas does not
    # promise.
    off = length - (length // block_s) * block_s
    ins = (ik == length // block_s) & (jax.lax.broadcasted_iota(
        jnp.int32, (block_s, 1, 1), 0) == off)
    ko_ref[0, 0] = jnp.where(ins, kn[None], k_blk)
    vo_ref[0, 0] = jnp.where(ins, vn[None], v_blk)

    # Masked single-query scores over the OLD prefix content: strictly
    # pos < length (position `length` is the new token, folded below).
    s = jnp.sum(q[None] * k_blk, axis=-1) * sm_scale      # [block_s, H]
    pos = ik * block_s + jax.lax.broadcasted_iota(
        jnp.int32, (block_s, 1), 0)
    s = jnp.where(pos < length, s, _NEG_INF)

    m_prev = m_ref[:]                                     # [1, H]
    m_cur = jnp.max(s, axis=0, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    safe_m = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    p = jnp.exp(jnp.where(s <= _NEG_INF, -jnp.inf, s) - safe_m)
    corr = jnp.where(m_prev <= _NEG_INF, 0.0, jnp.exp(m_prev - safe_m))
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=0, keepdims=True)
    h = acc_ref.shape[0]
    acc_ref[:] = (acc_ref[:] * corr.reshape(h, 1)
                  + jnp.sum(p[:, :, None] * v_blk, axis=0))  # [H, D]

    @pl.when(ik == nk - 1)
    def _finalize():
        # Fold in the new token (position `length`, value kn/vn) from
        # registers — it is always valid, so the denominator is > 0 and
        # fully-masked-prefix lanes (length == 0, i.e. padded lanes on the
        # dummy row) come out as exactly vn instead of NaN.
        s_new = jnp.sum(q * kn, axis=-1)[None] * sm_scale  # [1, H]
        m_fin = jnp.maximum(m_ref[:], s_new)
        p_new = jnp.exp(s_new - m_fin)
        corr_f = jnp.where(m_ref[:] <= _NEG_INF, 0.0,
                           jnp.exp(m_ref[:] - m_fin))
        l_fin = l_ref[:] * corr_f + p_new
        acc_f = (acc_ref[:] * corr_f.reshape(h, 1)
                 + p_new.reshape(h, 1) * vn)
        o_ref[0] = (acc_f / l_fin.reshape(h, 1)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("layer", "block_s",
                                             "interpret"))
def decode_wave_attention(k_arena, v_arena, q, k_new, v_new, rows, lens, *,
                          layer: int, block_s: int | None = None,
                          interpret: bool = False):
    """One layer's fused decode wave over the KV arena.

    k_arena/v_arena: ``[L, R, S, H, D]``; q/k_new/v_new: ``[B, H, D]``;
    rows/lens: ``[B]`` int32 (lane → arena row, valid prefix length).
    Returns ``(k_arena, v_arena, o)`` with the new K/V scattered at
    ``(layer, rows[b], lens[b])`` in place (donation-friendly: the arena
    operands are aliased to the outputs) and ``o: [B, H, D]`` the
    attention read over positions ``0 .. lens[b]`` inclusive.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, _, s, h, d = k_arena.shape
    bsz = q.shape[0]
    if block_s is None:
        block_s = pick_block_s(s)
    if s % block_s:
        raise ValueError(f"block_s ({block_s}) must divide max_seq_len "
                         f"({s})")
    sm_scale = 1.0 / np.sqrt(d)
    grid = (bsz, s // block_s)

    def arena_map(b, ik, rows, lens):
        return (layer, rows[b], ik, 0, 0)

    def lane_map(b, ik, rows, lens):
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_s, h, d), arena_map),   # k arena
            pl.BlockSpec((1, 1, block_s, h, d), arena_map),   # v arena
            pl.BlockSpec((1, h, d), lane_map),                # q
            pl.BlockSpec((1, h, d), lane_map),                # k_new
            pl.BlockSpec((1, h, d), lane_map),                # v_new
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_s, h, d), arena_map),   # k arena out
            pl.BlockSpec((1, 1, block_s, h, d), arena_map),   # v arena out
            pl.BlockSpec((1, h, d), lane_map),                # o
        ],
        scratch_shapes=[
            pltpu.VMEM((1, h), jnp.float32),    # running max
            pltpu.VMEM((1, h), jnp.float32),    # running denominator
            pltpu.VMEM((h, d), jnp.float32),    # weighted accumulator
        ],
    )
    kernel = functools.partial(_decode_kernel, block_s=block_s,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_arena.shape, k_arena.dtype),
            jax.ShapeDtypeStruct(v_arena.shape, v_arena.dtype),
            jax.ShapeDtypeStruct((bsz, h, d), q.dtype),
        ],
        # Operand indices count the scalar-prefetch args: rows=0, lens=1,
        # k_arena=2, v_arena=3.
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(rows, lens, k_arena, v_arena, q, k_new, v_new)


def reference_decode_attention(k_arena, v_arena, q, k_new, v_new, rows,
                               lens, *, layer: int):
    """XLA oracle with the reference path's exact semantics (scatter the
    new K/V, gather the rows, dense masked softmax over ``pos <= len``) —
    the parity target for the fused kernel, kept next to it like
    ``reference_attention`` is for flash."""
    d = q.shape[-1]
    s = k_arena.shape[2]
    k_arena = k_arena.at[layer, rows, lens].set(k_new)
    v_arena = v_arena.at[layer, rows, lens].set(v_new)
    ck = k_arena[layer, rows]                       # [B, S, H, D]
    cv = v_arena[layer, rows]
    scores = jnp.einsum("bhd,bshd->bhs", q, ck) / np.sqrt(d)
    mask = jnp.arange(s)[None, :] <= lens[:, None]
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    o = jnp.einsum("bhs,bshd->bhd", jax.nn.softmax(scores), cv)
    return k_arena, v_arena, o
