"""Flash attention as a Pallas TPU kernel.

Why a kernel at all: XLA's stock attention materializes the [B, H, S, S]
score tensor in HBM — at seq 2048, BERT-base batch 8 that is 1.5 GB of
fp32 traffic per layer, strictly memory-bound. The flash formulation keeps
one (block_q × block_k) score tile in VMEM and carries the online-softmax
running max / denominator / weighted accumulator across key blocks, so HBM
traffic drops from O(S²) to O(S·D) and the MXU stays fed
(pallas_guide.md: VMEM ~16 MB/core, MXU 128×128 tiles).

The public layout is the serving models' native [B, S, H, D]; internally
the kernel runs on [B, H, S, D] (TPU block shapes tile the last two dims —
pallas requires them (8,128)-aligned or full); masking is an additive
[B, S_k] bias (0 keep / -inf drop, the
encoder padding-mask convention) plus an optional causal flag for decoder/
long-context LM use. ``interpret=True`` runs the same kernel on CPU for the
hermetic test suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
               m_ref, l_ref, acc_ref,
               *, block_q: int, block_k: int, causal: bool,
               sm_scale: float):
    """One (batch, head, q-block, k-block) grid step.

    Grid iterates k innermost (TPU grids run sequentially), so the VMEM
    scratch (m/l/acc) carries the online-softmax state across k blocks of
    one q block and is re-initialized when the k index wraps to 0.
    """
    from jax.experimental import pallas as pl

    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks arrive as [1, 1, block, d] / [1, 1, block] — drop unit axes.
    q = q_ref[0, 0]                            # [bq, d]
    k = k_ref[0, 0]                            # [bk, d]
    v = v_ref[0, 0]                            # [bk, d]
    bias = bias_ref[0, 0]                      # [bk]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)     # [bq, bk]
    s = s * sm_scale + bias[None, :].astype(jnp.float32)
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

    m_prev = m_ref[:]                          # [bq, 1]
    l_prev = l_ref[:]
    m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    # Guard fully-masked rows: exp(-inf - -inf) would be NaN.
    safe_m = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    p = jnp.exp(jnp.where(s <= _NEG_INF, -jnp.inf, s) - safe_m)  # [bq, bk]
    correction = jnp.where(m_prev <= _NEG_INF, 0.0,
                           jnp.exp(m_prev - safe_m))
    l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc_ref[:] * correction + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = m_new
    l_ref[:] = l_new
    acc_ref[:] = acc

    @pl.when(ik == nk - 1)
    def _finalize():
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "block_q", "block_k", "interpret"))
def flash_attention(q, k, v, bias=None, *, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Memory-efficient attention. q/k/v: [B, S, H, D] (same S for q and k
    here — encoder self-attention); bias: additive [B, S] key mask
    (0 = attend, -inf/-1e9 = masked) or None. Returns [B, S, H, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"block sizes ({block_q}/{block_k}) must divide the sequence "
            f"length {s}")
    if bias is None:
        bias = jnp.zeros((b, s), jnp.float32)
    sm_scale = 1.0 / np.sqrt(d)

    # Kernel-internal layout: [B, H, S, D] so blocks tile the (seq, head_dim)
    # trailing dims.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # [B, 1, S]: the unit middle dim makes the (1, 1, block_k) bias block a
    # legal TPU tile (trailing dims equal-or-aligned to the array's).
    bias3 = bias[:, None, :]

    grid = (b, h, s // block_q, s // block_k)
    kernel = functools.partial(
        _fa_kernel, block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, ki)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),   # weighted accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt, bias3)
    return out.transpose(0, 2, 1, 3)


def reference_attention(q, k, v, bias=None, *, causal: bool = False):
    """O(S²)-memory oracle for tests (same math, XLA-scheduled)."""
    b, s, h, d = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d)
    if bias is not None:
        scores = scores + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
