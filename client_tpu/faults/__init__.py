"""Deterministic server-side fault injection ("chaos") subsystem.

A process-global registry of named injection sites, each threaded through
one chokepoint of the serving stack:

* ``http.pre_read``      — HTTP frontend, before the request body is read
* ``grpc.pre_infer``     — gRPC frontend, on ModelInfer entry
* ``scheduler.enqueue``  — scheduler admission, before the queue put
* ``scheduler.dequeue``  — scheduler worker, after a request is popped
  (exercises the expiry-at-dequeue / shed paths with seeded determinism)
* ``model.execute``      — model execution, before device dispatch
* ``shmring.doorbell``   — shm ring span admission, on doorbell entry
  (explicit doorbells) and per reaper sweep of a non-empty reaped ring
  (exercises reaper error isolation)

Each site can inject added latency, a protocol error with a chosen
status, or a dropped connection, gated by a *seeded* Bernoulli draw —
``random.Random(seed)`` per site, so a given (seed, probability) produces
the same injection pattern on every run and chaos tests are tier-1
deterministic, not flaky.

Configuration is programmatic (``faults.configure({...})``) or via the
``CLIENT_TPU_FAULTS`` environment variable holding either inline JSON or
``@/path/to/profile.json``::

    CLIENT_TPU_FAULTS='{"http.pre_read":
        {"probability": 0.2, "seed": 42, "latency_ms": 50,
         "error_status": 503}}'

Injection counts are exported through the PR-1 metrics registry as
``tpu_fault_injections_total{site,kind}`` — the engine binds its registry
at construction, so counts show up in ``prometheus_metrics()``.
"""

from __future__ import annotations

import json
import os
from client_tpu import config as envcfg
import random
from client_tpu.utils import lockdep
import time
import weakref

__all__ = [
    "SITES",
    "FaultSpec",
    "FaultInjected",
    "FaultRegistry",
    "registry",
    "configure",
    "fire",
    "reset",
]

SITES = ("http.pre_read", "grpc.pre_infer", "scheduler.enqueue",
         "scheduler.dequeue", "model.execute", "shmring.doorbell")

ENV_VAR = "CLIENT_TPU_FAULTS"


class FaultInjected(Exception):
    """Raised at an injection site whose draw triggered an error or
    connection-drop action; the hosting layer translates it into its own
    protocol error (HTTP status / gRPC abort / EngineError)."""

    def __init__(self, site: str, kind: str, status: int | None = None):
        super().__init__(f"injected fault at {site} ({kind}"
                         + (f", status {status}" if status else "") + ")")
        self.site = site
        self.kind = kind  # "error" | "drop"
        self.status = status


class FaultSpec:
    """One site's injection behavior. Any combination of latency + one
    terminal action (error XOR drop); latency applies first so an injected
    503 still pays the injected delay, like a struggling real server."""

    def __init__(self, site: str, probability: float = 1.0, seed: int = 0,
                 latency_ms: float = 0.0, error_status: int | None = None,
                 drop: bool = False, max_injections: int | None = None):
        if site not in SITES:
            raise ValueError(
                f"unknown fault site '{site}' (valid: {', '.join(SITES)})")
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be within [0, 1]")
        if drop and error_status is not None:
            raise ValueError("a fault is either an error or a drop, not both")
        self.site = site
        self.probability = float(probability)
        self.seed = int(seed)
        self.latency_ms = float(latency_ms)
        self.error_status = (int(error_status)
                             if error_status is not None else None)
        self.drop = bool(drop)
        self.max_injections = (int(max_injections)
                               if max_injections is not None else None)

    @classmethod
    def from_dict(cls, site: str, d: dict) -> "FaultSpec":
        unknown = set(d) - {"probability", "seed", "latency_ms",
                            "error_status", "drop", "max_injections"}
        if unknown:
            raise ValueError(
                f"unknown fault spec keys for '{site}': {sorted(unknown)}")
        return cls(site, **d)


class _ActiveFault:
    """A spec armed with its own seeded RNG and injection budget."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.remaining = spec.max_injections
        self.lock = lockdep.Lock("faults.active")

    def draw(self) -> bool:
        with self.lock:
            if self.remaining == 0:
                return False
            if self.rng.random() >= self.spec.probability:
                return False
            if self.remaining is not None:
                self.remaining -= 1
            return True


class FaultRegistry:
    """Named injection sites + deterministic draws + injection counters."""

    def __init__(self):
        self._lock = lockdep.Lock("faults.registry")
        self._active: dict[str, _ActiveFault] = {}
        self._counts: dict[tuple[str, str], int] = {}
        # id(MetricRegistry) -> weakref to its bound counter. Keyed by
        # registry identity so rebinding replaces rather than appends, and
        # held weakly so counters of dead registries (engines long gone)
        # are pruned instead of incremented forever on the hot fire() path.
        self._metric_counters: dict[int, weakref.ref] = {}

    # -- configuration -------------------------------------------------------

    def configure(self, config: dict) -> None:
        """Replace all armed sites: {site: spec-dict} (env/JSON shape)."""
        active = {site: _ActiveFault(FaultSpec.from_dict(site, dict(d)))
                  for site, d in (config or {}).items()}
        with self._lock:
            self._active = active

    def install(self, spec: FaultSpec) -> None:
        with self._lock:
            self._active[spec.site] = _ActiveFault(spec)

    def clear(self) -> None:
        with self._lock:
            self._active = {}

    def configure_from_env(self, environ=os.environ) -> None:
        raw = envcfg.env_text(ENV_VAR, environ)
        if not raw:
            return
        if raw.startswith("@"):
            with open(raw[1:], "r", encoding="utf-8") as f:
                raw = f.read()
        self.configure(json.loads(raw))

    # -- metrics -------------------------------------------------------------

    def bind_metrics(self, metric_registry) -> None:
        """Export injection counts as tpu_fault_injections_total{site,kind}
        on the given PR-1 MetricRegistry (the engine binds its own at
        construction). Idempotent per registry; multiple engines may bind."""
        counter = metric_registry.counter(
            "tpu_fault_injections_total",
            "Injected faults by site and kind (chaos subsystem)",
            ("site", "kind"))
        with self._lock:
            self._metric_counters[id(metric_registry)] = weakref.ref(counter)

    def _count(self, site: str, kind: str) -> None:
        with self._lock:
            key = (site, kind)
            self._counts[key] = self._counts.get(key, 0) + 1
            counters = []
            for rid, ref in list(self._metric_counters.items()):
                c = ref()
                if c is None:
                    del self._metric_counters[rid]
                else:
                    counters.append(c)
        for c in counters:
            c.inc(site=site, kind=kind)
        # Journal the injection so chaos timelines interleave faults with
        # the breaker/admission/drain transitions they cause. Imported
        # lazily: observability must stay importable without faults.
        from client_tpu.observability.events import journal

        journal().emit("fault", "injected", site=site, kind=kind)

    def counts(self) -> dict:
        with self._lock:
            return {f"{site}:{kind}": n
                    for (site, kind), n in sorted(self._counts.items())}

    # -- the hot call --------------------------------------------------------

    def fire(self, site: str, sleep=time.sleep) -> None:
        """Evaluate the site; no-op when unarmed or the draw misses.
        Applies injected latency inline, then raises FaultInjected for
        error/drop actions (the caller translates)."""
        active = self._active.get(site)
        if active is None or not active.draw():
            return
        spec = active.spec
        if spec.latency_ms > 0:
            self._count(site, "latency")
            sleep(spec.latency_ms / 1000.0)
        if spec.drop:
            self._count(site, "drop")
            raise FaultInjected(site, "drop")
        if spec.error_status is not None:
            self._count(site, "error")
            raise FaultInjected(site, "error", spec.error_status)


# -- process-global default registry ----------------------------------------
#
# Sites live at chokepoints that have no constructor path from user code
# (scheduler workers, model execution), so like observability.REGISTRY the
# default registry is process-global; the env profile is applied once on
# first access.

_default: FaultRegistry | None = None
_default_lock = lockdep.Lock("faults.default")


def registry() -> FaultRegistry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                r = FaultRegistry()
                r.configure_from_env()
                _default = r
    return _default


def configure(config: dict) -> None:
    registry().configure(config)


def fire(site: str) -> None:
    registry().fire(site)


def reset() -> None:
    """Disarm every site (counters and metric bindings are kept)."""
    registry().clear()
