"""Support module for the in-process C API (`libtpuserver.so`).

The native shim (native/capi/tpu_server_capi.cc) embeds CPython and calls the
functions here — this file is the whole Python-side surface of the embedded
server, so the C code stays a thin marshalling layer. Plays the role the
reference delegates to the dlopen'd libtritonserver.so
(/root/reference/src/c++/perf_analyzer/client_backend/triton_c_api/
triton_loader.cc:251,899): an engine in the benchmark process, no network.

Contract with the C side:
- create_engine(models_csv) -> engine object (opaque PyObject to C)
- *_json helpers return JSON strings
- infer(engine, request_json, buffers) ->
  (response_json, [np.ndarray], [(name, datatype, shape)])
  where `buffers` are zero-copy memoryviews of caller-owned input bytes
  (valid only for the duration of the call), the returned arrays are
  C-contiguous and exposed back to C via the buffer protocol (zero-copy
  out), and the metadata tuples let the C side read names/dtypes/shapes
  without re-parsing the JSON on the hot path.
"""

from __future__ import annotations

import json
from client_tpu import config as envcfg

import numpy as np

from client_tpu.engine import InferRequest, TpuEngine
from client_tpu.engine.types import EngineError, OutputRequest
from client_tpu.models import build_repository
from client_tpu.protocol.codec import (
    deserialize_bytes_tensor,
    serialize_bytes_tensor,
)
from client_tpu.protocol.dtypes import np_to_wire_dtype, wire_to_np_dtype


def create_engine(models_csv: str = "") -> TpuEngine:
    # CLIENT_TPU_PLATFORM=cpu lets the embedded engine run hermetically
    # (tests, machines without a TPU). The image's sitecustomize pins the
    # platform before env vars are seen, so this must go through jax.config.
    platform = envcfg.env_str("CLIENT_TPU_PLATFORM")
    if platform:
        import jax

        try:
            jax.config.update("jax_platforms", platform)
        # tpulint: allow[swallowed-exception] backend already initialized
        except Exception:  # noqa: BLE001 — backend already initialized
            pass
    names = [n.strip() for n in models_csv.split(",") if n.strip()] or None
    # CLIENT_TPU_WARMUP=1: pre-compile every batch bucket at load so no
    # XLA compile ever lands inside a perf-harness measurement window
    # (pair with tpu_perf_analyzer --warmup-request-count for the
    # request-path caches).
    warmup = envcfg.env_flag("CLIENT_TPU_WARMUP")
    return TpuEngine(build_repository(names), warmup=warmup)


def shutdown_engine(engine: TpuEngine) -> None:
    engine.shutdown()


def model_metadata_json(engine: TpuEngine, name: str, version: str = "") -> str:
    return json.dumps(engine.model_metadata(name, version))


def model_config_json(engine: TpuEngine, name: str, version: str = "") -> str:
    return json.dumps(engine.model_config(name, version))


def model_statistics_json(engine: TpuEngine, name: str = "",
                          version: str = "") -> str:
    return json.dumps(engine.model_statistics(name, version))


def server_metadata_json(engine: TpuEngine) -> str:
    return json.dumps(engine.server_metadata())


def register_system_shm(engine: TpuEngine, name: str, key: str,
                        byte_size: int) -> None:
    engine.system_shm.register(name, key, 0, int(byte_size))


def unregister_system_shm(engine: TpuEngine, name: str = "") -> None:
    engine.system_shm.unregister(name or None)


def register_tpu_shm(engine: TpuEngine, name: str, raw_handle: bytes,
                     device_id: int, byte_size: int) -> None:
    engine.tpu_shm.register_handle(name, raw_handle, int(device_id),
                                   int(byte_size))


def unregister_tpu_shm(engine: TpuEngine, name: str = "") -> None:
    engine.tpu_shm.unregister(name or None)


def _read_shm_input(engine: TpuEngine, meta: dict) -> np.ndarray:
    p = meta.get("parameters") or {}
    if "shared_memory_region" not in p:
        # data=NULL is the C API's shm marker (tpu_server_capi.h); a NULL
        # buffer without the parameters is a caller wiring bug — surface it
        # as a clean 400, not a KeyError traceback.
        raise EngineError(
            f"input '{meta.get('name')}': NULL data pointer but no "
            "shared_memory_region/byte_size parameters", 400)
    return engine.read_shm_tensor(
        p["shared_memory_region"], int(p.get("shared_memory_offset", 0)),
        int(p.get("shared_memory_byte_size", 0)), meta["datatype"],
        meta["shape"])


def _input_array(meta: dict, buf) -> np.ndarray:
    dtype = meta["datatype"]
    shape = meta["shape"]
    if dtype == "BYTES":
        arr = deserialize_bytes_tensor(bytes(buf))
        return arr.reshape(shape)
    # Zero-copy view over caller memory; the engine's batcher copies on
    # concatenation, and the call is synchronous, so the view never outlives
    # the caller's buffer.
    return np.frombuffer(buf, dtype=wire_to_np_dtype(dtype)).reshape(shape)


def infer(engine: TpuEngine, request_json: str, buffers: list):
    req_d = json.loads(request_json)
    inputs_meta = req_d.get("inputs", [])
    if len(inputs_meta) != len(buffers):
        raise ValueError(
            f"{len(inputs_meta)} input descriptors but {len(buffers)} buffers")
    inputs = {}
    for m, b in zip(inputs_meta, buffers):
        if b is None or (m.get("parameters") or {}).get(
                "shared_memory_region"):
            inputs[m["name"]] = _read_shm_input(engine, m)
        else:
            inputs[m["name"]] = _input_array(m, b)
    outputs = []
    for o in req_d.get("outputs", []):
        p = o.get("parameters") or {}
        outputs.append(OutputRequest(
            name=o["name"],
            classification_count=int(o.get("classification", 0)),
            shm_region=p.get("shared_memory_region"),
            shm_offset=int(p.get("shared_memory_offset", 0)),
            shm_byte_size=int(p.get("shared_memory_byte_size", 0)),
        ))
    # True zero-copy output plane: if every requested output lands in a
    # device-resident tpu region, the scheduler skips the D2H fetch and the
    # shm write below stores the HBM-resident slice as-is.
    keep_on_device = bool(outputs) and all(
        o.shm_region and engine.tpu_shm.region_kind(o.shm_region) == "device"
        for o in outputs)
    req = InferRequest(
        model_name=req_d["model_name"],
        model_version=req_d.get("model_version", ""),
        request_id=req_d.get("id", ""),
        inputs=inputs,
        outputs=outputs,
        sequence_id=int(req_d.get("sequence_id", 0)),
        sequence_start=bool(req_d.get("sequence_start", False)),
        sequence_end=bool(req_d.get("sequence_end", False)),
        priority=int(req_d.get("priority", 0)),
        timeout_us=int(req_d.get("timeout_us", 0)),
        keep_outputs_on_device=keep_on_device,
    )
    timeout_s = req.timeout_us / 1e6 if req.timeout_us else None
    resp = engine.infer(req, timeout_s=timeout_s)

    out_meta = []
    out_arrays = []
    out_req = {o.name: o for o in outputs}
    for name, arr in resp.outputs.items():
        o = out_req.get(name)
        if o is not None and o.shm_region:
            # shm-placed output: write into the region, return parameters
            # instead of a data view (the caller owns the mapping).
            written = engine.write_shm_tensor(o.shm_region, o.shm_offset,
                                              o.shm_byte_size, arr)
            out_meta.append({
                "name": name,
                "datatype": np_to_wire_dtype(arr.dtype) or "BYTES",
                "shape": list(arr.shape),
                "parameters": {
                    "shared_memory_region": o.shm_region,
                    "shared_memory_offset": o.shm_offset,
                    "shared_memory_byte_size": written,
                },
            })
            out_arrays.append(None)
            continue
        wire = np_to_wire_dtype(arr.dtype)
        if wire is None or arr.dtype.kind in ("S", "U", "O"):
            data = np.frombuffer(serialize_bytes_tensor(arr), dtype=np.uint8)
            out_meta.append({"name": name, "datatype": "BYTES",
                             "shape": list(arr.shape)})
            out_arrays.append(data)
        else:
            out_meta.append({"name": name, "datatype": wire,
                             "shape": list(arr.shape)})
            out_arrays.append(np.ascontiguousarray(arr))
    response_json = json.dumps({
        "model_name": resp.model_name,
        "model_version": resp.model_version,
        "id": resp.request_id,
        "outputs": out_meta,
    })
    metas = [(m["name"], m["datatype"], m["shape"]) for m in out_meta]
    return response_json, out_arrays, metas
