"""KServe v2 datatype table and numpy mapping.

Behavioral contract mirrors the reference's dtype tables
(/root/reference/src/python/library/tritonclient/utils/__init__.py:127-186 and
/root/reference/src/c++/perf_analyzer/perf_utils.cc element-size helpers), but
adds BF16 as a first-class citizen because it is the native TPU matmul type.
"""

from __future__ import annotations

import numpy as np

try:  # ml_dtypes ships with jax; keeps this module importable without jax.
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None


class DataType:
    """String constants for the v2 wire datatypes."""

    BOOL = "BOOL"
    UINT8 = "UINT8"
    UINT16 = "UINT16"
    UINT32 = "UINT32"
    UINT64 = "UINT64"
    INT8 = "INT8"
    INT16 = "INT16"
    INT32 = "INT32"
    INT64 = "INT64"
    FP16 = "FP16"
    FP32 = "FP32"
    FP64 = "FP64"
    BYTES = "BYTES"
    BF16 = "BF16"

    ALL = (
        BOOL, UINT8, UINT16, UINT32, UINT64, INT8, INT16, INT32, INT64,
        FP16, FP32, FP64, BYTES, BF16,
    )


_NP_TO_WIRE = {
    np.dtype(np.bool_): DataType.BOOL,
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.uint32): DataType.UINT32,
    np.dtype(np.uint64): DataType.UINT64,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FP16,
    np.dtype(np.float32): DataType.FP32,
    np.dtype(np.float64): DataType.FP64,
    np.dtype(np.object_): DataType.BYTES,
    np.dtype(np.bytes_): DataType.BYTES,
}
if _BF16 is not None:
    _NP_TO_WIRE[_BF16] = DataType.BF16

_WIRE_TO_NP = {
    DataType.BOOL: np.bool_,
    DataType.UINT8: np.uint8,
    DataType.UINT16: np.uint16,
    DataType.UINT32: np.uint32,
    DataType.UINT64: np.uint64,
    DataType.INT8: np.int8,
    DataType.INT16: np.int16,
    DataType.INT32: np.int32,
    DataType.INT64: np.int64,
    DataType.FP16: np.float16,
    DataType.FP32: np.float32,
    DataType.FP64: np.float64,
    DataType.BYTES: np.object_,
}
if _BF16 is not None:
    _WIRE_TO_NP[DataType.BF16] = _BF16

# Fixed per-element byte sizes; BYTES is variable-length (-1 sentinel), matching
# the reference convention (perf_utils lets BYTES size come from the data).
_BYTE_SIZE = {
    DataType.BOOL: 1,
    DataType.UINT8: 1,
    DataType.UINT16: 2,
    DataType.UINT32: 4,
    DataType.UINT64: 8,
    DataType.INT8: 1,
    DataType.INT16: 2,
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.FP16: 2,
    DataType.BF16: 2,
    DataType.FP32: 4,
    DataType.FP64: 8,
    DataType.BYTES: -1,
}


def np_to_wire_dtype(np_dtype) -> str | None:
    """numpy dtype (or anything np.dtype accepts) -> v2 wire name, or None."""
    if np_dtype is bytes or np_dtype is str:
        return DataType.BYTES
    dt = np.dtype(np_dtype)
    if dt.kind in ("S", "U"):
        return DataType.BYTES
    return _NP_TO_WIRE.get(dt)


def wire_to_np_dtype(wire: str):
    """v2 wire name -> numpy dtype class (np.object_ for BYTES), or None."""
    return _WIRE_TO_NP.get(wire)


def dtype_byte_size(wire: str) -> int:
    """Per-element size in bytes; -1 for variable-length BYTES."""
    try:
        return _BYTE_SIZE[wire]
    except KeyError:
        raise ValueError(f"unknown datatype '{wire}'") from None


def element_count(shape) -> int:
    """Number of elements for a shape; 0-d means 1."""
    n = 1
    for d in shape:
        n *= int(d)
    return n


def tensor_byte_size(wire: str, shape) -> int:
    """Fixed-size tensor byte size; raises for BYTES (variable)."""
    per = dtype_byte_size(wire)
    if per < 0:
        raise ValueError("BYTES tensors have data-dependent size")
    return per * element_count(shape)
