"""Protobuf messages for the operational control-plane RPCs.

Companion to grpc_service_pb2 for the Events / SloStatus accessors
(``/v2/events`` and ``/v2/slo`` over gRPC). The runtime image has no
protoc/grpc_tools, and appending to grpc_service_pb2's serialized blob
by hand would be unmaintainable — so this module builds its
FileDescriptorProto programmatically, registers it in the default
descriptor pool, and lets the same generated-code builder materialise
the message classes. Wire-compatible with the equivalent .proto:

    syntax = "proto3"; package inference;
    message EventsRequest  { string model = 1; string severity = 2;
                             uint64 since_seq = 3; string category = 4;
                             uint32 limit = 5; double since_wall = 6;
                             double until_wall = 7; }
    message Event          { uint64 seq = 1; double ts_wall = 2;
                             uint64 ts_mono_ns = 3; string category = 4;
                             string name = 5; string severity = 6;
                             string model = 7; string version = 8;
                             string trace_id = 9; string detail_json = 10; }
    message EventsResponse { repeated Event events = 1;
                             uint64 next_seq = 2; uint64 dropped = 3; }
    message SloStatusRequest  { string model = 1; }
    message SloStatusResponse { string slo_json = 1; }
    message ProfileRequest    { string model = 1; }
    message ProfileResponse   { string profile_json = 1; }
    message RingRegisterRequest    { string name = 1; string key = 2;
                                     string spec_json = 3; }
    message RingRegisterResponse   {}
    message RingStatusRequest      { string name = 1; }
    message RingStatusResponse     { string status_json = 1; }
    message RingUnregisterRequest  { string name = 1; }
    message RingUnregisterResponse {}
    message RingDoorbellRequest    { string name = 1;
                                     string doorbell_json = 2; }
    message RingDoorbellResponse   { string result_json = 1; }
    message DatasetRegisterRequest    { string name = 1; string key = 2; }
    message DatasetRegisterResponse   {}
    message DatasetStatusRequest      { string name = 1; }
    message DatasetStatusResponse     { string status_json = 1; }
    message DatasetUnregisterRequest  { string name = 1; }
    message DatasetUnregisterResponse {}
    message TimeseriesRequest  { string signal = 1; string model = 2;
                                 uint64 since_seq = 3; uint32 limit = 4;
                                 double since_wall = 5;
                                 double until_wall = 6; }
    message TimeseriesResponse { string timeseries_json = 1; }
    message MemoryRequest      {}
    message MemoryResponse     { string memory_json = 1; }
    message CostsRequest       { string model = 1; }
    message CostsResponse      { string costs_json = 1; }
    message QosRequest         { string model = 1; }
    message QosResponse        { string qos_json = 1; }
    message BlackboxCaptureRequest  { string trigger = 1;
                                      string incident = 2;
                                      string note = 3; }
    message BlackboxCaptureResponse { string bundle_json = 1; }
    message BlackboxBundlesRequest  { string bundle_id = 1; }
    message BlackboxBundlesResponse { string bundles_json = 1; }

Event.detail_json / SloStatusResponse.slo_json /
ProfileResponse.profile_json carry the open-ended detail/report dicts as
JSON strings — same pattern the HTTP frontend uses, without freezing
their schema into the proto.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2 as _descriptor_pb2
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf.internal import builder as _builder

_F = _descriptor_pb2.FieldDescriptorProto

_FILE_NAME = "client_tpu_ops_service.proto"


def _file_proto() -> _descriptor_pb2.FileDescriptorProto:
    fdp = _descriptor_pb2.FileDescriptorProto()
    fdp.name = _FILE_NAME
    fdp.package = "inference"
    fdp.syntax = "proto3"

    def message(name: str):
        m = fdp.message_type.add()
        m.name = name
        return m

    def field(msg, name: str, number: int, ftype,
              label=_F.LABEL_OPTIONAL, type_name: str = ""):
        f = msg.field.add()
        f.name = name
        f.number = number
        f.type = ftype
        f.label = label
        if type_name:
            f.type_name = type_name

    m = message("EventsRequest")
    field(m, "model", 1, _F.TYPE_STRING)
    field(m, "severity", 2, _F.TYPE_STRING)
    field(m, "since_seq", 3, _F.TYPE_UINT64)
    field(m, "category", 4, _F.TYPE_STRING)
    field(m, "limit", 5, _F.TYPE_UINT32)
    field(m, "since_wall", 6, _F.TYPE_DOUBLE)
    field(m, "until_wall", 7, _F.TYPE_DOUBLE)

    m = message("Event")
    field(m, "seq", 1, _F.TYPE_UINT64)
    field(m, "ts_wall", 2, _F.TYPE_DOUBLE)
    field(m, "ts_mono_ns", 3, _F.TYPE_UINT64)
    field(m, "category", 4, _F.TYPE_STRING)
    field(m, "name", 5, _F.TYPE_STRING)
    field(m, "severity", 6, _F.TYPE_STRING)
    field(m, "model", 7, _F.TYPE_STRING)
    field(m, "version", 8, _F.TYPE_STRING)
    field(m, "trace_id", 9, _F.TYPE_STRING)
    field(m, "detail_json", 10, _F.TYPE_STRING)

    m = message("EventsResponse")
    field(m, "events", 1, _F.TYPE_MESSAGE, label=_F.LABEL_REPEATED,
          type_name=".inference.Event")
    field(m, "next_seq", 2, _F.TYPE_UINT64)
    field(m, "dropped", 3, _F.TYPE_UINT64)

    m = message("SloStatusRequest")
    field(m, "model", 1, _F.TYPE_STRING)

    m = message("SloStatusResponse")
    field(m, "slo_json", 1, _F.TYPE_STRING)

    m = message("ProfileRequest")
    field(m, "model", 1, _F.TYPE_STRING)

    m = message("ProfileResponse")
    field(m, "profile_json", 1, _F.TYPE_STRING)

    # shm slot-ring control plane (register-by-key + batched doorbell;
    # the doorbell span spec and status tables ride as JSON, matching
    # the HTTP bodies byte for byte).
    m = message("RingRegisterRequest")
    field(m, "name", 1, _F.TYPE_STRING)
    field(m, "key", 2, _F.TYPE_STRING)
    field(m, "spec_json", 3, _F.TYPE_STRING)

    message("RingRegisterResponse")

    m = message("RingStatusRequest")
    field(m, "name", 1, _F.TYPE_STRING)

    m = message("RingStatusResponse")
    field(m, "status_json", 1, _F.TYPE_STRING)

    m = message("RingUnregisterRequest")
    field(m, "name", 1, _F.TYPE_STRING)

    message("RingUnregisterResponse")

    m = message("RingDoorbellRequest")
    field(m, "name", 1, _F.TYPE_STRING)
    field(m, "doorbell_json", 2, _F.TYPE_STRING)

    m = message("RingDoorbellResponse")
    field(m, "result_json", 1, _F.TYPE_STRING)

    # Staged-dataset control plane (many-producer fan-in; the status
    # table rides as JSON, matching the HTTP body byte for byte).
    m = message("DatasetRegisterRequest")
    field(m, "name", 1, _F.TYPE_STRING)
    field(m, "key", 2, _F.TYPE_STRING)

    message("DatasetRegisterResponse")

    m = message("DatasetStatusRequest")
    field(m, "name", 1, _F.TYPE_STRING)

    m = message("DatasetStatusResponse")
    field(m, "status_json", 1, _F.TYPE_STRING)

    m = message("DatasetUnregisterRequest")
    field(m, "name", 1, _F.TYPE_STRING)

    message("DatasetUnregisterResponse")

    # Flight recorder + HBM census (the /v2/timeseries and /v2/memory
    # bodies ride as JSON strings, same pattern as slo/profile).
    m = message("TimeseriesRequest")
    field(m, "signal", 1, _F.TYPE_STRING)
    field(m, "model", 2, _F.TYPE_STRING)
    field(m, "since_seq", 3, _F.TYPE_UINT64)
    field(m, "limit", 4, _F.TYPE_UINT32)
    field(m, "since_wall", 5, _F.TYPE_DOUBLE)
    field(m, "until_wall", 6, _F.TYPE_DOUBLE)

    m = message("TimeseriesResponse")
    field(m, "timeseries_json", 1, _F.TYPE_STRING)

    message("MemoryRequest")

    m = message("MemoryResponse")
    field(m, "memory_json", 1, _F.TYPE_STRING)

    # Per-tenant cost ledger (the /v2/costs body rides as JSON, same
    # pattern as slo/profile/memory).
    m = message("CostsRequest")
    field(m, "model", 1, _F.TYPE_STRING)

    m = message("CostsResponse")
    field(m, "costs_json", 1, _F.TYPE_STRING)

    # Tenant QoS status (the /v2/qos body rides as JSON, same pattern
    # as slo/profile/memory/costs).
    m = message("QosRequest")
    field(m, "model", 1, _F.TYPE_STRING)

    m = message("QosResponse")
    field(m, "qos_json", 1, _F.TYPE_STRING)

    # Incident blackbox (the /v2/debug/bundles and /v2/debug/capture
    # bodies ride as JSON, same pattern as slo/profile/memory/costs).
    m = message("BlackboxCaptureRequest")
    field(m, "trigger", 1, _F.TYPE_STRING)
    field(m, "incident", 2, _F.TYPE_STRING)
    field(m, "note", 3, _F.TYPE_STRING)

    m = message("BlackboxCaptureResponse")
    field(m, "bundle_json", 1, _F.TYPE_STRING)

    m = message("BlackboxBundlesRequest")
    field(m, "bundle_id", 1, _F.TYPE_STRING)

    m = message("BlackboxBundlesResponse")
    field(m, "bundles_json", 1, _F.TYPE_STRING)

    return fdp


_pool = _descriptor_pool.Default()
try:
    DESCRIPTOR = _pool.Add(_file_proto())
except Exception:  # noqa: BLE001 — already registered (re-import/reload)
    DESCRIPTOR = _pool.FindFileByName(_FILE_NAME)

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(
    DESCRIPTOR, "client_tpu.protocol.ops_pb2", globals())

__all__ = [
    "EventsRequest",
    "Event",
    "EventsResponse",
    "SloStatusRequest",
    "SloStatusResponse",
    "ProfileRequest",
    "ProfileResponse",
    "RingRegisterRequest",
    "RingRegisterResponse",
    "RingStatusRequest",
    "RingStatusResponse",
    "RingUnregisterRequest",
    "RingUnregisterResponse",
    "RingDoorbellRequest",
    "RingDoorbellResponse",
    "DatasetRegisterRequest",
    "DatasetRegisterResponse",
    "DatasetStatusRequest",
    "DatasetStatusResponse",
    "DatasetUnregisterRequest",
    "DatasetUnregisterResponse",
    "TimeseriesRequest",
    "TimeseriesResponse",
    "MemoryRequest",
    "MemoryResponse",
    "CostsRequest",
    "CostsResponse",
    "QosRequest",
    "QosResponse",
    "BlackboxCaptureRequest",
    "BlackboxCaptureResponse",
    "BlackboxBundlesRequest",
    "BlackboxBundlesResponse",
]
