"""numpy ↔ gRPC protobuf tensor conversion.

Two encodings, as in the v2 protocol: ``raw_*_contents`` (packed little-endian
bytes, the fast path the reference uses for everything,
grpc_client.cc:1084-1222) and typed ``InferTensorContents`` fields (used by
the explicit-content example clients, e.g.
/root/reference/src/python/examples/grpc_explicit_int_content_client.py).
"""

from __future__ import annotations

import numpy as np

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol.codec import deserialize_tensor, serialize_tensor
from client_tpu.protocol.dtypes import DataType, wire_to_np_dtype

# typed-contents field per wire dtype (BYTES handled separately)
_CONTENT_FIELD = {
    DataType.BOOL: "bool_contents",
    DataType.INT8: "int_contents",
    DataType.INT16: "int_contents",
    DataType.INT32: "int_contents",
    DataType.INT64: "int64_contents",
    DataType.UINT8: "uint_contents",
    DataType.UINT16: "uint_contents",
    DataType.UINT32: "uint_contents",
    DataType.UINT64: "uint64_contents",
    DataType.FP32: "fp32_contents",
    DataType.FP64: "fp64_contents",
    DataType.BYTES: "bytes_contents",
}


def set_param(param_map, key, value) -> None:
    p = param_map[key]
    if isinstance(value, bool):
        p.bool_param = value
    elif isinstance(value, int):
        p.int64_param = value
    elif isinstance(value, float):
        p.double_param = value
    else:
        p.string_param = str(value)


def param_value(p: "pb.InferParameter"):
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


def params_to_dict(param_map) -> dict:
    return {k: param_value(v) for k, v in param_map.items()}


def fill_contents(contents: "pb.InferTensorContents", arr: np.ndarray,
                  datatype: str) -> None:
    """Populate the typed contents field from a numpy array."""
    field = _CONTENT_FIELD.get(datatype)
    if field is None:
        raise ValueError(
            f"datatype {datatype} has no typed contents field; use raw")
    if datatype == DataType.BYTES:
        flat = np.ravel(arr, order="C")
        contents.bytes_contents.extend(
            x if isinstance(x, bytes) else
            bytes(x) if isinstance(x, (bytearray, np.bytes_)) else
            str(x).encode("utf-8")
            for x in flat)
    else:
        getattr(contents, field).extend(
            np.ravel(arr, order="C").tolist())


def contents_to_ndarray(contents: "pb.InferTensorContents", datatype: str,
                        shape) -> np.ndarray:
    field = _CONTENT_FIELD.get(datatype)
    if field is None:
        raise ValueError(f"datatype {datatype} not representable as contents")
    shape = tuple(int(d) for d in shape)
    if datatype == DataType.BYTES:
        arr = np.array(list(contents.bytes_contents), dtype=np.object_)
    else:
        arr = np.array(getattr(contents, field),
                       dtype=wire_to_np_dtype(datatype))
    return arr.reshape(shape)


def tensor_to_ndarray(tensor, raw: bytes | None) -> np.ndarray:
    """InferInputTensor/InferOutputTensor (+ its raw slice) -> ndarray."""
    if raw is not None:
        return deserialize_tensor(raw, tensor.datatype, tensor.shape)
    return contents_to_ndarray(tensor.contents, tensor.datatype, tensor.shape)


def ndarray_to_raw(arr: np.ndarray, datatype: str) -> bytes:
    return serialize_tensor(arr, datatype)


def tensor_has_contents(tensor) -> bool:
    """True if any typed ``InferTensorContents`` field is populated — such a
    tensor does not consume a ``raw_*_contents`` slot (client and server must
    agree on this rule or raw slots mis-assign)."""
    c = tensor.contents
    return any(len(getattr(c, f.name)) for f in c.DESCRIPTOR.fields)
