"""Wire-level schema for the KServe v2 inference protocol.

Pure, dependency-light building blocks shared by clients and servers:

- :mod:`client_tpu.protocol.dtypes` — the v2 datatype table and numpy mapping.
- :mod:`client_tpu.protocol.codec` — BYTES tensor codec and raw tensor
  (de)serialization.
- :mod:`client_tpu.protocol.rest` — HTTP/REST JSON + binary-extension framing.

Everything here is fully unit-testable with no server (SURVEY.md §7 step 1).
"""

from client_tpu.protocol.dtypes import (  # noqa: F401
    DataType,
    dtype_byte_size,
    np_to_wire_dtype,
    wire_to_np_dtype,
)
