"""Retry-After pushback: ONE parse/format pair for every transport.

Before this module each surface had its own formatter/parser and they
disagreed on sub-second handling: the HTTP server printed ``"%.3f"``
(so a 0.4ms clip floor became ``"0.000"``, which the HTTP client parsed
back as an *immediate* retry), while the gRPC server's integral
``retry-pushback-ms`` mirror truncated (``int(s * 1000)``) instead of
rounding, so 9.9999s read back as 9.999s on one channel and 10.000s on
the other. Both servers and both clients now route through here:

* :func:`format_retry_after_s` — fractional-seconds text for the HTTP
  ``Retry-After`` header and the gRPC ``retry-after`` trailing metadata.
  3-decimal fixed point, rounded half-up; positive values floor at
  0.001 so pushback can never collapse to "retry now".
* :func:`format_retry_pushback_ms` — integral milliseconds for the gRPC
  ``retry-pushback-ms`` mirror (some proxies strip fractional values).
  Rounded, floored at 1ms for positive input — always within 0.5ms of
  the seconds form.
* :func:`parse_retry_after` — text -> seconds. Fractional or integral
  seconds; None on absent/unparsable/negative (callers treat None as
  "no pushback", never as "retry immediately").
* :func:`parse_pushback_metadata` — the gRPC client's trailing-metadata
  view (``retry-after`` preferred, ``retry-pushback-ms`` fallback).
* :func:`format_slot_error` / :func:`parse_slot_error_retry_after` —
  the shm-ring slot channel. A shed slot carries only an error string
  (there is no header/metadata side channel in the segment), so the
  pushback rides as a machine-parseable ``[retry-after=1.500s]`` suffix
  producers strip back out. Same 3-decimal canonical text as the HTTP
  header.
"""

from __future__ import annotations

import re

__all__ = [
    "RETRY_AFTER_HEADER",
    "RETRY_AFTER_METADATA_KEY",
    "RETRY_PUSHBACK_MS_METADATA_KEY",
    "format_retry_after_s",
    "format_retry_pushback_ms",
    "parse_retry_after",
    "parse_pushback_metadata",
    "format_slot_error",
    "parse_slot_error_retry_after",
]

RETRY_AFTER_HEADER = "Retry-After"
RETRY_AFTER_METADATA_KEY = "retry-after"
RETRY_PUSHBACK_MS_METADATA_KEY = "retry-pushback-ms"


def format_retry_after_s(seconds: float) -> str:
    """Canonical wire text for a pushback interval in seconds.

    Negative input clamps to 0 ("retry now" is only ever deliberate);
    any positive interval renders as at least ``"0.001"`` so rounding
    cannot silently erase the server's request to back off.
    """
    s = float(seconds)
    if s <= 0.0:
        return "0.000"
    # round() half-even at the 3rd decimal, then re-floor: 0.0004 must
    # not round down to zero.
    return f"{max(round(s, 3), 0.001):.3f}"


def format_retry_pushback_ms(seconds: float) -> str:
    """Integral-millisecond mirror of :func:`format_retry_after_s`.

    Rounds (the old formatter truncated, so the two encodings of one
    interval disagreed by up to 1ms); positive input floors at 1ms.
    """
    s = float(seconds)
    if s <= 0.0:
        return "0"
    return str(max(1, round(s * 1000)))


def parse_retry_after(raw) -> float | None:
    """Text (or None) -> pushback seconds, or None when the value is
    absent, unparsable, or negative. Accepts the integral-seconds form
    plain proxies rewrite to; HTTP-date is not used in this ecosystem."""
    if raw is None:
        return None
    try:
        value = float(raw)
    except (TypeError, ValueError):
        return None
    # Non-finite values ("inf", "nan") must read as "no pushback", not
    # "wait forever".
    return value if 0 <= value < float("inf") else None


def parse_pushback_metadata(meta) -> float | None:
    """gRPC trailing metadata (any mapping with lowercase keys, or an
    iterable of (key, value)) -> pushback seconds, or None.

    ``retry-after`` (fractional seconds) wins over ``retry-pushback-ms``
    — the ms mirror exists for consumers that drop fractional text."""
    if meta is None:
        return None
    if not hasattr(meta, "get"):
        meta = {str(k).lower(): v for k, v in meta}
    value = parse_retry_after(meta.get(RETRY_AFTER_METADATA_KEY))
    if value is not None:
        return value
    ms = parse_retry_after(meta.get(RETRY_PUSHBACK_MS_METADATA_KEY))
    return ms / 1000.0 if ms is not None else None


_SLOT_RETRY_AFTER_RE = re.compile(r" \[retry-after=(\d+(?:\.\d+)?)s\]$")


def format_slot_error(message: str, retry_after_s: float | None) -> str:
    """Fold a pushback interval into a shm-ring slot error string."""
    if retry_after_s is None:
        return message
    return f"{message} [retry-after={format_retry_after_s(retry_after_s)}s]"


def parse_slot_error_retry_after(error) -> float | None:
    """Pushback seconds from a slot error string, or None when the error
    carries no ``[retry-after=...s]`` suffix (non-admission failures)."""
    if not error:
        return None
    m = _SLOT_RETRY_AFTER_RE.search(str(error))
    return parse_retry_after(m.group(1)) if m else None
