"""gRPC stubs for inference.GRPCInferenceService.

Hand-written equivalent of the ``*_pb2_grpc.py`` file grpc_tools would
generate (the runtime image ships grpcio + protoc but not grpc_tools).
Method table mirrors the service definition in protos/grpc_service.proto;
the fully-qualified method paths match the reference protocol, so these
stubs interoperate with any v2 gRPC peer.
"""

from __future__ import annotations

import grpc

from client_tpu.protocol import grpc_service_pb2 as pb
from client_tpu.protocol import ops_pb2 as ops

_SERVICE = "inference.GRPCInferenceService"

# (method name, request message, response message, is_streaming)
_METHODS = [
    ("ServerLive", pb.ServerLiveRequest, pb.ServerLiveResponse, False),
    ("ServerReady", pb.ServerReadyRequest, pb.ServerReadyResponse, False),
    ("ModelReady", pb.ModelReadyRequest, pb.ModelReadyResponse, False),
    ("ServerMetadata", pb.ServerMetadataRequest, pb.ServerMetadataResponse, False),
    ("ModelMetadata", pb.ModelMetadataRequest, pb.ModelMetadataResponse, False),
    ("ModelInfer", pb.ModelInferRequest, pb.ModelInferResponse, False),
    ("ModelStreamInfer", pb.ModelInferRequest, pb.ModelStreamInferResponse, True),
    ("ModelConfig", pb.ModelConfigRequest, pb.ModelConfigResponse, False),
    ("ModelStatistics", pb.ModelStatisticsRequest, pb.ModelStatisticsResponse, False),
    ("RepositoryIndex", pb.RepositoryIndexRequest, pb.RepositoryIndexResponse, False),
    ("RepositoryModelLoad", pb.RepositoryModelLoadRequest,
     pb.RepositoryModelLoadResponse, False),
    ("RepositoryModelUnload", pb.RepositoryModelUnloadRequest,
     pb.RepositoryModelUnloadResponse, False),
    ("SystemSharedMemoryStatus", pb.SystemSharedMemoryStatusRequest,
     pb.SystemSharedMemoryStatusResponse, False),
    ("SystemSharedMemoryRegister", pb.SystemSharedMemoryRegisterRequest,
     pb.SystemSharedMemoryRegisterResponse, False),
    ("SystemSharedMemoryUnregister", pb.SystemSharedMemoryUnregisterRequest,
     pb.SystemSharedMemoryUnregisterResponse, False),
    ("CudaSharedMemoryStatus", pb.CudaSharedMemoryStatusRequest,
     pb.CudaSharedMemoryStatusResponse, False),
    ("CudaSharedMemoryRegister", pb.CudaSharedMemoryRegisterRequest,
     pb.CudaSharedMemoryRegisterResponse, False),
    ("CudaSharedMemoryUnregister", pb.CudaSharedMemoryUnregisterRequest,
     pb.CudaSharedMemoryUnregisterResponse, False),
    ("TpuSharedMemoryStatus", pb.TpuSharedMemoryStatusRequest,
     pb.TpuSharedMemoryStatusResponse, False),
    ("TpuSharedMemoryRegister", pb.TpuSharedMemoryRegisterRequest,
     pb.TpuSharedMemoryRegisterResponse, False),
    ("TpuSharedMemoryUnregister", pb.TpuSharedMemoryUnregisterRequest,
     pb.TpuSharedMemoryUnregisterResponse, False),
    # Operational control plane (gRPC mirrors of /v2/events and /v2/slo;
    # messages hand-built in ops_pb2 — the image carries no protoc).
    ("Events", ops.EventsRequest, ops.EventsResponse, False),
    ("SloStatus", ops.SloStatusRequest, ops.SloStatusResponse, False),
    ("Profile", ops.ProfileRequest, ops.ProfileResponse, False),
    # shm slot-ring data plane (engine.shmring): register-by-key,
    # status, and the batched doorbell.
    ("RingRegister", ops.RingRegisterRequest, ops.RingRegisterResponse,
     False),
    ("RingStatus", ops.RingStatusRequest, ops.RingStatusResponse, False),
    ("RingUnregister", ops.RingUnregisterRequest,
     ops.RingUnregisterResponse, False),
    ("RingDoorbell", ops.RingDoorbellRequest, ops.RingDoorbellResponse,
     False),
    # Staged-dataset control plane (engine.staged): register-by-key the
    # shared read-only segment ring descriptors reference.
    ("DatasetRegister", ops.DatasetRegisterRequest,
     ops.DatasetRegisterResponse, False),
    ("DatasetStatus", ops.DatasetStatusRequest,
     ops.DatasetStatusResponse, False),
    ("DatasetUnregister", ops.DatasetUnregisterRequest,
     ops.DatasetUnregisterResponse, False),
    # Flight recorder ring + HBM census report.
    ("Timeseries", ops.TimeseriesRequest, ops.TimeseriesResponse, False),
    ("MemoryCensus", ops.MemoryRequest, ops.MemoryResponse, False),
    # Per-tenant cost ledger (gRPC mirror of /v2/costs).
    ("Costs", ops.CostsRequest, ops.CostsResponse, False),
    # Tenant QoS status (gRPC mirror of /v2/qos).
    ("Qos", ops.QosRequest, ops.QosResponse, False),
    # Incident blackbox (gRPC mirrors of /v2/debug/capture and
    # /v2/debug/bundles).
    ("BlackboxCapture", ops.BlackboxCaptureRequest,
     ops.BlackboxCaptureResponse, False),
    ("BlackboxBundles", ops.BlackboxBundlesRequest,
     ops.BlackboxBundlesResponse, False),
]


class GRPCInferenceServiceStub:
    """Client-side stub; one callable per RPC, plus Async variants exposed
    via the callables' ``.future`` (grpcio's standard mechanism)."""

    def __init__(self, channel: grpc.Channel):
        for name, req_t, resp_t, streaming in _METHODS:
            path = f"/{_SERVICE}/{name}"
            if streaming:
                call = channel.stream_stream(
                    path,
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                )
            else:
                call = channel.unary_unary(
                    path,
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                )
            setattr(self, name, call)


class GRPCInferenceServiceServicer:
    """Server-side base class; override the RPCs the server implements."""


def _make_unimplemented(name):
    def method(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details(f"{name} is not implemented")
        raise NotImplementedError(name)

    return method


for _name, _req, _resp, _streaming in _METHODS:
    setattr(GRPCInferenceServiceServicer, _name, _make_unimplemented(_name))


def add_GRPCInferenceServiceServicer_to_server(servicer, server):  # noqa: N802
    handlers = {}
    for name, req_t, resp_t, streaming in _METHODS:
        fn = getattr(servicer, name)
        if streaming:
            handlers[name] = grpc.stream_stream_rpc_method_handler(
                fn,
                request_deserializer=req_t.FromString,
                response_serializer=resp_t.SerializeToString,
            )
        else:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_t.FromString,
                response_serializer=resp_t.SerializeToString,
            )
    generic = grpc.method_handlers_generic_handler(_SERVICE, handlers)
    server.add_generic_rpc_handlers((generic,))
