"""HTTP/REST framing for the KServe v2 inference protocol.

Implements the JSON + binary-tensor-extension body format used by the
reference on both directions of ``POST /v2/models/<m>[/versions/<v>]/infer``:

- request: JSON head (inputs/outputs metadata), each input may carry a
  ``binary_data_size`` parameter and append its raw bytes, in input order,
  after the JSON head (reference http_client.cc:301-434,
  python http/__init__.py:81-128).
- the ``Inference-Header-Content-Length`` header carries the JSON head length
  so the peer can split head from binary tail (http_client.cc:1396-1407).
- response: mirrored — outputs with ``binary_data_size`` parameters are mapped
  by walking offsets in parameter order (http_client.cc:752-835,
  python http/__init__.py:1768-1962).

These builders/parsers are shared by the Python client, the HTTP server
frontend, and the conformance tests, so a single implementation defines the
wire contract.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from client_tpu.protocol.codec import deserialize_tensor, serialize_tensor
from client_tpu.protocol.dtypes import DataType

HEADER_INFERENCE_CONTENT_LENGTH = "Inference-Header-Content-Length"


@dataclass
class WireTensor:
    """One input/output tensor as it appears on the wire."""

    name: str
    datatype: str | None = None
    shape: list[int] | None = None
    parameters: dict[str, Any] = field(default_factory=dict)
    # Exactly one of the following is populated:
    data: list | None = None      # JSON-inline representation
    raw: bytes | None = None      # binary extension payload

    def to_numpy(self) -> np.ndarray:
        if self.raw is not None:
            return deserialize_tensor(self.raw, self.datatype, self.shape)
        if self.data is None:
            raise ValueError(f"tensor '{self.name}' carries no data")
        if self.datatype == DataType.BYTES:
            flat = _flatten(self.data)
            arr = np.array(
                [x.encode("utf-8") if isinstance(x, str) else bytes(x) for x in flat],
                dtype=np.object_,
            )
            return arr.reshape(self.shape)
        from client_tpu.protocol.dtypes import wire_to_np_dtype

        return np.array(self.data, dtype=wire_to_np_dtype(self.datatype)).reshape(
            self.shape
        )


def _flatten(lst):
    out = []
    stack = [lst]
    while stack:
        item = stack.pop()
        if isinstance(item, list):
            stack.extend(reversed(item))
        else:
            out.append(item)
    return out


def _json_safe(arr: np.ndarray, datatype: str) -> list:
    if datatype == DataType.BYTES:
        flat = np.ravel(arr, order="C")
        return [
            x.decode("utf-8", errors="replace") if isinstance(x, (bytes, np.bytes_)) else str(x)
            for x in flat
        ]
    return np.ravel(arr, order="C").tolist()


def build_tensor_json(
    name: str,
    arr: np.ndarray | None,
    datatype: str,
    shape,
    *,
    binary: bool = True,
    parameters: dict | None = None,
) -> tuple[dict, bytes | None]:
    """Build the JSON dict + optional binary payload for one request input."""
    entry: dict[str, Any] = {
        "name": name,
        "datatype": datatype,
        "shape": [int(d) for d in shape],
    }
    params = dict(parameters or {})
    raw = None
    if arr is not None:
        if binary:
            raw = serialize_tensor(arr, datatype)
            params["binary_data_size"] = len(raw)
        else:
            entry["data"] = _json_safe(arr, datatype)
    if params:
        entry["parameters"] = params
    return entry, raw


def build_infer_request_body(
    inputs: list[tuple[dict, bytes | None]],
    outputs: list[dict] | None = None,
    request_id: str = "",
    parameters: dict | None = None,
) -> tuple[bytes, int]:
    """Assemble the full request body.

    Returns ``(body, json_length)``; when any input has a binary payload the
    caller must send the ``Inference-Header-Content-Length: json_length``
    header, matching the reference contract.
    """
    head: dict[str, Any] = {}
    if request_id:
        head["id"] = request_id
    if parameters:
        head["parameters"] = parameters
    head["inputs"] = [entry for entry, _ in inputs]
    if outputs is not None:
        head["outputs"] = outputs
    json_bytes = json.dumps(head, separators=(",", ":")).encode("utf-8")
    tails = [raw for _, raw in inputs if raw is not None]
    body = json_bytes + b"".join(tails)
    return body, len(json_bytes)


def split_body(body: bytes, header_json_length: int | None) -> tuple[dict, bytes]:
    """Split a v2 body into (parsed JSON head, binary tail)."""
    if header_json_length is None:
        return json.loads(body.decode("utf-8")), b""
    head = json.loads(body[:header_json_length].decode("utf-8"))
    return head, body[header_json_length:]


def parse_tensors(head_list: list[dict], tail: bytes) -> list[WireTensor]:
    """Walk tensors in declared order, slicing binary payloads by offset —
    the reference's binary-offset output mapping (http_client.cc:752-835)."""
    tensors: list[WireTensor] = []
    offset = 0
    for entry in head_list or []:
        t = WireTensor(
            name=entry["name"],
            datatype=entry.get("datatype"),
            shape=entry.get("shape"),
            parameters=entry.get("parameters", {}) or {},
        )
        size = t.parameters.get("binary_data_size")
        if size is not None:
            if offset + size > len(tail):
                raise ValueError(
                    f"binary payload for '{t.name}' ({size}B at {offset}) "
                    f"overruns body tail of {len(tail)}B"
                )
            t.raw = tail[offset : offset + size]
            offset += size
        elif "data" in entry:
            t.data = entry["data"]
        tensors.append(t)
    return tensors


def build_infer_response_body(
    outputs: list[tuple[dict, bytes | None]],
    model_name: str,
    model_version: str,
    request_id: str = "",
    parameters: dict | None = None,
) -> tuple[bytes, int]:
    """Server-side mirror of :func:`build_infer_request_body`."""
    head: dict[str, Any] = {
        "model_name": model_name,
        "model_version": str(model_version),
    }
    if request_id:
        head["id"] = request_id
    if parameters:
        head["parameters"] = parameters
    head["outputs"] = [entry for entry, _ in outputs]
    json_bytes = json.dumps(head, separators=(",", ":")).encode("utf-8")
    tails = [raw for _, raw in outputs if raw is not None]
    return json_bytes + b"".join(tails), len(json_bytes)
