"""Tensor (de)serialization codecs.

Two codecs, both wire-compatible with the reference implementations:

- BYTES tensors: each element is a 4-byte little-endian length prefix followed
  by the raw bytes (reference:
  /root/reference/src/python/library/tritonclient/utils/__init__.py:187-271,
  /root/reference/src/c++/perf_analyzer/perf_utils.h:122-129,
  /root/reference/src/java/.../BinaryProtocol.java:92-104).
- Fixed-size tensors: row-major raw bytes in the tensor's natural dtype.

Plus base64 helpers used for device-handle transport over JSON control planes
(the reference base64-encodes ``cudaIpcMemHandle_t`` for HTTP registration,
/root/reference/src/python/library/tritonclient/utils/cuda_shared_memory/
cuda_shared_memory.cc:100-123; we do the same for TPU buffer handles).
"""

from __future__ import annotations

import base64
import struct

import numpy as np

from client_tpu.protocol.dtypes import DataType, np_to_wire_dtype, wire_to_np_dtype


def serialize_bytes_tensor(tensor: np.ndarray) -> bytes:
    """Serialize a BYTES tensor (dtype object/bytes/str) to the 4B-LE-prefixed
    flattened wire form. Row-major ('C') element order."""
    if tensor.size == 0:
        return b""
    flat = np.ravel(tensor, order="C")
    out = bytearray()
    for item in flat:
        if isinstance(item, (bytes, bytearray)):
            raw = bytes(item)
        elif isinstance(item, str):
            raw = item.encode("utf-8")
        elif isinstance(item, np.bytes_):
            raw = bytes(item)
        else:
            raw = str(item).encode("utf-8")
        out += struct.pack("<I", len(raw))
        out += raw
    return bytes(out)


def deserialize_bytes_tensor(encoded: bytes, count: int | None = None) -> np.ndarray:
    """Inverse of :func:`serialize_bytes_tensor` -> 1-D object ndarray of bytes.

    The caller reshapes to the wire shape. ``count`` (if given) bounds the
    number of elements parsed (used when a shm region is larger than the
    tensor, reference shared_memory/__init__.py:211-227).
    """
    items = []
    offset = 0
    n = len(encoded)
    while offset + 4 <= n:
        if count is not None and len(items) >= count:
            break
        (length,) = struct.unpack_from("<I", encoded, offset)
        offset += 4
        if offset + length > n:
            raise ValueError(
                f"malformed BYTES tensor: element length {length} at offset "
                f"{offset - 4} overruns buffer of {n} bytes"
            )
        items.append(encoded[offset : offset + length])
        offset += length
    return np.array(items, dtype=np.object_)


def serialize_tensor(tensor: np.ndarray, wire_dtype: str | None = None) -> bytes:
    """Any tensor -> raw wire bytes (BYTES codec or row-major raw)."""
    if wire_dtype is None:
        wire_dtype = np_to_wire_dtype(tensor.dtype)
    if wire_dtype == DataType.BYTES:
        return serialize_bytes_tensor(tensor)
    want = wire_to_np_dtype(wire_dtype)
    arr = np.ascontiguousarray(tensor, dtype=want)
    return arr.tobytes()


def deserialize_tensor(raw: bytes, wire_dtype: str, shape) -> np.ndarray:
    """Raw wire bytes -> ndarray of the given v2 dtype and shape."""
    shape = tuple(int(d) for d in shape)
    if wire_dtype == DataType.BYTES:
        n = 1
        for d in shape:
            n *= d
        arr = deserialize_bytes_tensor(raw, count=n)
        return arr.reshape(shape)
    np_dtype = wire_to_np_dtype(wire_dtype)
    if np_dtype is None:
        raise ValueError(f"unknown datatype '{wire_dtype}'")
    arr = np.frombuffer(raw, dtype=np_dtype)
    return arr.reshape(shape)


def b64_encode_handle(raw: bytes) -> str:
    """Opaque device/shm handle -> base64 ascii for JSON transport."""
    return base64.b64encode(raw).decode("ascii")


def b64_decode_handle(encoded: str) -> bytes:
    return base64.b64decode(encoded)
