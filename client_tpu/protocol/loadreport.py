"""Per-replica load report: the router's steady-state routing signal.

One replica summarises its instantaneous load as a tiny flat record —
EWMA-derived queue-wait estimate, in-flight count, queue depth, health
state, SLO fast-burn — served two ways by both frontends:

* pull: ``GET /v2/load`` (JSON, via :func:`to_json_dict`) for bootstrap,
  background refresh, and human inspection;
* piggyback: the ``X-Tpu-Load`` response header (HTTP) / ``x-tpu-load``
  trailing metadata (gRPC) on every inference response, via
  :func:`encode_header`, so a router that is already forwarding traffic
  learns each replica's load for free — zero extra RPCs in steady state.

The header form is deliberately key=value (not JSON): it must survive
header-value character rules, stay short (~60 bytes), and parse without
allocation-heavy json in the router's hot path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

__all__ = ["LOAD_HEADER", "LOAD_METADATA_KEY", "LoadReport",
           "encode_header", "decode_header"]

LOAD_HEADER = "X-Tpu-Load"
LOAD_METADATA_KEY = "x-tpu-load"

_STATES = ("READY", "DEGRADED", "DRAINING")


@dataclass
class LoadReport:
    """Snapshot of one replica's load. ``wait_s`` is the engine's EWMA
    queue-wait estimate (queue_depth x EWMA service time / instances,
    summed over models) — the same signal admission control sheds on."""

    state: str = "READY"
    inflight: int = 0
    queue_depth: int = 0
    active_batches: int = 0
    wait_s: float = 0.0
    slo_fast_burn: bool = False
    models: tuple = ()
    ts: float = field(default_factory=time.time)

    @property
    def draining(self) -> bool:
        return self.state == "DRAINING"

    def score(self) -> float:
        """Routing cost: smaller is better. In-flight + queued work plus
        the wait estimate scaled so 1ms of predicted queueing outweighs a
        tie but never a whole queued request."""
        return (self.inflight + self.queue_depth
                + min(self.wait_s, 30.0) * 0.9)

    def to_json_dict(self) -> dict:
        return {
            "state": self.state,
            "inflight": self.inflight,
            "queue_depth": self.queue_depth,
            "active_batches": self.active_batches,
            "wait_s": round(self.wait_s, 6),
            "slo_fast_burn": self.slo_fast_burn,
            "models": list(self.models),
            "ts": self.ts,
        }

    @classmethod
    def from_json_dict(cls, d: dict) -> "LoadReport":
        return cls(
            state=str(d.get("state", "READY")),
            inflight=int(d.get("inflight", 0)),
            queue_depth=int(d.get("queue_depth", 0)),
            active_batches=int(d.get("active_batches", 0)),
            wait_s=float(d.get("wait_s", 0.0)),
            slo_fast_burn=bool(d.get("slo_fast_burn", False)),
            models=tuple(d.get("models", ()) or ()),
            ts=float(d.get("ts", 0.0) or 0.0),
        )


def encode_header(report: LoadReport) -> str:
    """Compact header form: ``s=READY;i=3;q=1;b=1;w=0.004;f=0``.

    Model list stays out of the header (unbounded length); routers that
    need it pull ``/v2/load``.
    """
    return (f"s={report.state};i={report.inflight};q={report.queue_depth};"
            f"b={report.active_batches};w={report.wait_s:.4f};"
            f"f={int(report.slo_fast_burn)}")


def decode_header(raw) -> LoadReport | None:
    """Parse the header form; None on absent or malformed input (a
    router must never fail a request over a bad telemetry header)."""
    if not raw:
        return None
    fields: dict[str, str] = {}
    for part in str(raw).split(";"):
        k, sep, v = part.partition("=")
        if sep:
            fields[k.strip()] = v.strip()
    # The state key is mandatory: without it the input is not a load
    # header at all (otherwise any stray string would decode to a
    # default READY report).
    state = fields.get("s")
    if state not in _STATES:
        return None
    try:
        return LoadReport(
            state=state,
            inflight=int(fields.get("i", 0)),
            queue_depth=int(fields.get("q", 0)),
            active_batches=int(fields.get("b", 0)),
            wait_s=max(0.0, float(fields.get("w", 0.0))),
            slo_fast_burn=fields.get("f", "0") not in ("0", "", "false"),
        )
    except (TypeError, ValueError):
        return None
