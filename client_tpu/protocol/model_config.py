"""ModelConfig protobuf ↔ engine-config-dict conversion + pbtxt loading.

Lets the engine serve its JSON-native configs over the gRPC ModelConfig RPC,
and lets users load Triton-style ``config.pbtxt`` files (like the reference's
in-tree /root/reference/models/ssd_mobilenet_v2_coco_quantized/config.pbtxt)
via protobuf text_format.
"""

from __future__ import annotations

from google.protobuf import text_format

from client_tpu.protocol import grpc_service_pb2 as pb


def config_dict_to_proto(d: dict) -> "pb.ModelConfig":
    cfg = pb.ModelConfig(
        name=d.get("name", ""),
        platform=d.get("platform", ""),
        backend=d.get("backend", ""),
        max_batch_size=int(d.get("max_batch_size", 0)),
    )
    for io_key, holder in (("input", cfg.input), ("output", cfg.output)):
        for t in d.get(io_key, []):
            entry = holder.add(name=t["name"],
                               dims=[int(x) for x in t["dims"]])
            dt = t.get("data_type", "TYPE_INVALID")
            if not dt.startswith("TYPE_"):
                dt = "TYPE_" + ("STRING" if dt == "BYTES" else dt)
            entry.data_type = pb.DataType.Value(dt)
    if "dynamic_batching" in d:
        db = d["dynamic_batching"] or {}
        cfg.dynamic_batching.preferred_batch_size.extend(
            int(x) for x in db.get("preferred_batch_size", []))
        cfg.dynamic_batching.max_queue_delay_microseconds = int(
            db.get("max_queue_delay_microseconds", 0))
        cfg.dynamic_batching.preserve_ordering = bool(
            db.get("preserve_ordering", False))
        cfg.dynamic_batching.priority_levels = int(
            db.get("priority_levels", 0))
        cfg.dynamic_batching.default_priority_level = int(
            db.get("default_priority_level", 0))

        def fill_policy(dst, src: dict) -> None:
            dst.timeout_action = pb.ModelQueuePolicy.TimeoutAction.Value(
                str(src.get("timeout_action", "REJECT")).upper())
            dst.default_timeout_microseconds = int(
                src.get("default_timeout_microseconds", 0))
            dst.allow_timeout_override = bool(
                src.get("allow_timeout_override", True))
            dst.max_queue_size = int(src.get("max_queue_size", 0))

        if db.get("default_queue_policy"):
            fill_policy(cfg.dynamic_batching.default_queue_policy,
                        db["default_queue_policy"])
        for level, policy in (db.get("priority_queue_policy") or {}).items():
            fill_policy(
                cfg.dynamic_batching.priority_queue_policy[int(level)],
                policy)
    if "sequence_batching" in d:
        sb = d["sequence_batching"] or {}
        if sb.get("strategy") == "oldest":
            oldest = sb.get("oldest") or {}
            cfg.sequence_batching.oldest.SetInParent()
            cfg.sequence_batching.oldest.max_candidate_sequences = int(
                oldest.get("max_candidate_sequences",
                           sb.get("max_candidate_sequences", 0)))
            cfg.sequence_batching.oldest.max_queue_delay_microseconds = int(
                oldest.get("max_queue_delay_microseconds",
                           sb.get("max_queue_delay_microseconds", 0)))
        else:
            cfg.sequence_batching.direct.SetInParent()
        cfg.sequence_batching.max_sequence_idle_microseconds = int(
            sb.get("max_sequence_idle_microseconds", 0))
    if d.get("ensemble_scheduling"):
        for s in d["ensemble_scheduling"].get("step", []):
            step = cfg.ensemble_scheduling.step.add(
                model_name=s["model_name"],
                model_version=int(s.get("model_version", -1)))
            step.input_map.update(s.get("input_map", {}))
            step.output_map.update(s.get("output_map", {}))
    for g in d.get("instance_group", []) or []:
        cfg.instance_group.add(count=int(g.get("count", 1)))
    if (d.get("model_transaction_policy") or {}).get("decoupled"):
        cfg.model_transaction_policy.decoupled = True
    for key, val in (d.get("parameters") or {}).items():
        if isinstance(val, (str, int, float, bool)):
            cfg.parameters[key].string_value = str(val)
    return cfg


def proto_to_config_dict(cfg: "pb.ModelConfig") -> dict:
    d: dict = {
        "name": cfg.name,
        "platform": cfg.platform or cfg.backend or "jax",
        "max_batch_size": cfg.max_batch_size,
        "input": [],
        "output": [],
    }
    for t in cfg.input:
        entry = {
            "name": t.name,
            "data_type": pb.DataType.Name(t.data_type),
            "dims": list(t.dims),
        }
        if t.reshape.shape:
            entry["reshape"] = {"shape": list(t.reshape.shape)}
        if t.optional:
            entry["optional"] = True
        d["input"].append(entry)
    for t in cfg.output:
        entry = {
            "name": t.name,
            "data_type": pb.DataType.Name(t.data_type),
            "dims": list(t.dims),
        }
        if t.reshape.shape:
            entry["reshape"] = {"shape": list(t.reshape.shape)}
        if t.label_filename:
            entry["label_filename"] = t.label_filename
        d["output"].append(entry)
    if cfg.HasField("dynamic_batching"):
        db = cfg.dynamic_batching
        d["dynamic_batching"] = {
            "preferred_batch_size": list(db.preferred_batch_size),
            "max_queue_delay_microseconds":
                db.max_queue_delay_microseconds,
        }

        def policy_dict(qp) -> dict:
            return {
                "timeout_action":
                    pb.ModelQueuePolicy.TimeoutAction.Name(qp.timeout_action),
                "default_timeout_microseconds":
                    qp.default_timeout_microseconds,
                "allow_timeout_override": qp.allow_timeout_override,
                "max_queue_size": qp.max_queue_size,
            }

        if db.preserve_ordering:
            d["dynamic_batching"]["preserve_ordering"] = True
        if db.priority_levels:
            d["dynamic_batching"]["priority_levels"] = db.priority_levels
            d["dynamic_batching"]["default_priority_level"] = \
                db.default_priority_level
        if db.HasField("default_queue_policy"):
            d["dynamic_batching"]["default_queue_policy"] = policy_dict(
                db.default_queue_policy)
        if db.priority_queue_policy:
            d["dynamic_batching"]["priority_queue_policy"] = {
                int(k): policy_dict(v)
                for k, v in db.priority_queue_policy.items()}
    if cfg.HasField("sequence_batching"):
        sb: dict = {"max_sequence_idle_microseconds":
                    cfg.sequence_batching.max_sequence_idle_microseconds
                    or 1_000_000_000}
        if cfg.sequence_batching.WhichOneof("strategy_choice") == "oldest":
            sb["strategy"] = "oldest"
            oldest = cfg.sequence_batching.oldest
            sb["oldest"] = {
                "max_candidate_sequences":
                    oldest.max_candidate_sequences or 64,
                "max_queue_delay_microseconds":
                    oldest.max_queue_delay_microseconds or 1000,
            }
        d["sequence_batching"] = sb
    if cfg.ensemble_scheduling.step:
        d["ensemble_scheduling"] = {
            "step": [
                {
                    "model_name": s.model_name,
                    "model_version": s.model_version,
                    "input_map": dict(s.input_map),
                    "output_map": dict(s.output_map),
                }
                for s in cfg.ensemble_scheduling.step
            ]
        }
    if cfg.instance_group:
        d["instance_group"] = [{"count": g.count or 1}
                               for g in cfg.instance_group]
    if cfg.model_transaction_policy.decoupled:
        d["model_transaction_policy"] = {"decoupled": True}
    if cfg.parameters:
        d["parameters"] = {k: v.string_value
                           for k, v in cfg.parameters.items()}
    return d


def load_pbtxt(path: str) -> dict:
    """Parse a Triton-style config.pbtxt into an engine config dict."""
    with open(path) as f:
        cfg = text_format.Parse(f.read(), pb.ModelConfig())
    return proto_to_config_dict(cfg)
