"""Client-side POSIX system shared-memory utilities.

API mirrors the reference's ``tritonclient.utils.shared_memory``
(/root/reference/src/python/library/tritonclient/utils/shared_memory/
__init__.py:94-270, whose C ext does shm_open/ftruncate/mmap —
shared_memory.cc). On Linux, ``/dev/shm/<key>`` + mmap is the same POSIX shm
object without needing a C extension; the native C++ implementation lives in
src/cpp for the C++ client library.
"""

from __future__ import annotations

import mmap
import os

import numpy as np

from client_tpu.protocol.codec import serialize_tensor
from client_tpu.protocol.dtypes import np_to_wire_dtype
from client_tpu.utils import deserialize_bytes_tensor


class SharedMemoryException(Exception):
    pass


class SharedMemoryRegion:
    """Handle for a created-or-attached POSIX shm region."""

    def __init__(self, triton_shm_name: str, shm_key: str, byte_size: int,
                 fd: int, map_: mmap.mmap, created: bool):
        self.triton_shm_name = triton_shm_name
        self.shm_key = shm_key
        self.byte_size = byte_size
        self._fd = fd
        self._map = map_
        self._created = created
        self._closed = False


_mapped_regions: dict[str, SharedMemoryRegion] = {}


def _key_path(shm_key: str) -> str:
    return "/dev/shm/" + shm_key.lstrip("/")


def create_shared_memory_region(triton_shm_name, shm_key, byte_size,
                                create_only=False) -> SharedMemoryRegion:
    path = _key_path(shm_key)
    existed = os.path.exists(path)
    if create_only and existed:
        raise SharedMemoryException(
            f"shared memory region '{shm_key}' already exists")
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
    try:
        if not existed or os.fstat(fd).st_size < byte_size:
            os.ftruncate(fd, byte_size)
        map_ = mmap.mmap(fd, byte_size)
    except Exception:
        os.close(fd)
        raise
    region = SharedMemoryRegion(triton_shm_name, shm_key, byte_size, fd,
                                map_, created=not existed)
    _mapped_regions[triton_shm_name] = region
    return region


def set_shared_memory_region(shm_handle: SharedMemoryRegion, input_values,
                             offset=0) -> None:
    """Copy a list of numpy tensors into the region, back to back."""
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException(
            "input_values must be a list/tuple of numpy arrays")
    pos = offset
    for arr in input_values:
        raw = serialize_tensor(np.asarray(arr),
                               np_to_wire_dtype(np.asarray(arr).dtype))
        if pos + len(raw) > shm_handle.byte_size:
            raise SharedMemoryException(
                f"tensors exceed region size {shm_handle.byte_size}")
        shm_handle._map[pos:pos + len(raw)] = raw
        pos += len(raw)


def get_contents_as_numpy(shm_handle: SharedMemoryRegion, datatype, shape,
                          offset=0) -> np.ndarray:
    """Map region contents to a numpy array of (datatype, shape)."""
    shape = tuple(int(d) for d in shape)
    if datatype in (np.object_, bytes, "BYTES") or datatype == np.object_:
        n = 1
        for d in shape:
            n *= d
        raw = bytes(shm_handle._map[offset:shm_handle.byte_size])
        arr = deserialize_bytes_tensor(raw)[:n]
        return arr.reshape(shape)
    np_dtype = np.dtype(datatype)
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * np_dtype.itemsize
    view = memoryview(shm_handle._map)[offset:offset + nbytes]
    return np.frombuffer(view, dtype=np_dtype).reshape(shape)


def mapped_shared_memory_regions():
    return list(_mapped_regions.keys())


def destroy_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    if shm_handle._closed:
        return
    shm_handle._closed = True
    _mapped_regions.pop(shm_handle.triton_shm_name, None)
    try:
        shm_handle._map.close()
    except BufferError:
        # numpy views from get_contents_as_numpy still reference the mapping;
        # GC unmaps once the last view dies
        shm_handle._map = None
    os.close(shm_handle._fd)
    if shm_handle._created:
        try:
            os.unlink(_key_path(shm_handle.shm_key))
        except FileNotFoundError:
            pass
