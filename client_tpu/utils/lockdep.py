"""lockdep — named locks with runtime lock-order and blocking checking.

The engine runs many concurrent daemon loops (scheduler workers,
autotuner, flight recorder, fleet monitor, SLO ring, load pollers, shm
reapers) over dozens of lock sites, and historically every deadlock was
found the expensive way: a flaky e2e timeout. This module makes the
locking *observable*. Under ``CLIENT_TPU_LOCKDEP`` (tests/CI only), the
:func:`Lock`/:func:`RLock`/:func:`Condition` factories return
instrumented primitives that

* record per-thread acquisition chains into one process-global
  **lock-order graph** keyed by lock *name* (a name identifies a class
  of locks — every ``metrics.family`` instance shares a node);
* raise :class:`LockOrderViolation` the moment a thread's acquisition
  would close a cycle in that graph (an A→B edge exists and some thread
  now takes B→A — a potential deadlock even if it didn't deadlock this
  run), with the stacks of **both** edges in the message;
* raise on a same-instance re-acquire of a non-reentrant lock (certain
  self-deadlock);
* enforce the **declared ordering** below: every name carries an
  optional integer rank; acquiring a lower-ranked lock while holding a
  higher-ranked one raises even before any cycle exists;
* patch ``time.sleep`` so a sleep performed while any lockdep lock is
  held raises :class:`BlockingUnderLock` (the runtime counterpart of
  tpulint's static ``blocking-under-lock`` check). Legitimate
  exceptions wrap the call in :func:`allow_blocking`.

With the env unset (production default) the factories return plain
``threading`` primitives — zero overhead beyond one function call at
construction, nothing patched, no graph.

Naming convention: ``<subsystem>.<role>`` (``scheduler.queue``,
``metrics.family``). Ranks live in :data:`DECLARED_ORDER`, lowest =
outermost; see docs/ANALYSIS.md for the conventions and how to extend
them. Locks created *before* :func:`enable` (e.g. module-level locks in
already-imported modules) stay plain — enable lockdep via the
environment variable so it is active at import time.
"""

from __future__ import annotations

import os
import threading
import time
import traceback

from client_tpu import config as _config

__all__ = [
    "Lock",
    "RLock",
    "Condition",
    "LockOrderViolation",
    "BlockingUnderLock",
    "DECLARED_ORDER",
    "enable",
    "disable",
    "enabled",
    "reset",
    "allow_blocking",
    "held_names",
]


class LockOrderViolation(RuntimeError):
    """Two lock names were acquired in both orders (potential deadlock),
    or a declared rank was violated, or a non-reentrant lock was
    re-acquired by its holder."""


class BlockingUnderLock(RuntimeError):
    """A known-blocking call (``time.sleep``) ran while holding a lock."""


# Declared ordering: rank of each lock name, lowest = outermost (taken
# first). Acquiring a name with a LOWER rank while holding a HIGHER rank
# raises. Names absent from this table are unranked: they participate in
# cycle detection only. Keep ranks sparse so layers can be inserted.
DECLARED_ORDER: dict[str, int] = {
    # control plane (model lifecycle) — outermost
    "engine.engine": 100,
    # Per-name load serializer is taken BEFORE the repository state
    # lock (repository.load holds it across _load_serialized, which
    # re-enters the state lock for each phase).
    "engine.repository.load": 150,
    "engine.repository": 200,
    # QoS controller: admission-side class gates + governor. Taken
    # before any scheduler queue lock (classification happens at admit,
    # never under a queue condition); holds admission.bucket (unranked)
    # across governor rate retargets.
    "qos.controller": 250,
    # data plane (request flow)
    "scheduler.queue": 300,
    "scheduler.order": 310,
    "sequence.slots": 320,
    "sequence.arena": 330,
    "engine.model": 400,
    # shared resources below the schedulers
    "engine.arena": 500,
    "shm.system": 510,
    "shm.device": 510,
    # Staged datasets sit between the plain shm managers and the ring
    # plane: ring completion paths may resolve staged descriptors but
    # never the reverse.
    "shmstaged.manager": 515,
    "shmring.manager": 520,
    "shmring.ring": 530,
    "engine.rowcache": 540,
    # telemetry: leaf locks — safe to take under anything above
    "engine.stats": 600,
    "observability.profiler": 700,
    "observability.slo": 700,
    "observability.slo.model": 710,
    "observability.events": 720,
    "metrics.registry": 800,
    "metrics.family": 810,
    "metrics.value": 820,
    # Blackbox trigger matcher: runs inside journal sinks, i.e. on the
    # emitting thread AFTER observability.events is released but while
    # the emitter may still hold any lock above — so it ranks innermost.
    # Neither lock is ever held across an emit or a metrics update (the
    # capture thread journals blackbox.captured with no locks held).
    "observability.blackbox": 830,
    "observability.blackbox.store": 840,
}

_enabled = False
_graph_lock = threading.Lock()
# name -> {successor_name: formatted stack captured when the edge was
# first recorded}. Edge A->B means "some thread held A while taking B".
_graph: dict[str, dict[str, str]] = {}
_tls = threading.local()
_real_sleep = time.sleep


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def held_names() -> tuple[str, ...]:
    """Names of the lockdep locks the calling thread currently holds
    (outermost first). Empty when disabled."""
    return tuple(lk._name for lk in _held())


def _blocking_depth() -> int:
    return getattr(_tls, "allow_blocking", 0)


class allow_blocking:
    """Context manager marking a region where blocking while holding a
    lock is intentional and reviewed (the runtime analogue of the
    ``# tpulint: allow[blocking-under-lock]`` annotation)."""

    def __enter__(self):
        _tls.allow_blocking = _blocking_depth() + 1
        return self

    def __exit__(self, *exc):
        _tls.allow_blocking = _blocking_depth() - 1
        return False


def _checked_sleep(seconds):
    if _held() and _blocking_depth() == 0:
        raise BlockingUnderLock(
            f"time.sleep({seconds!r}) while holding lockdep lock(s) "
            f"{list(held_names())} — sleeping under a lock stalls every "
            "other thread contending for it; move the sleep outside the "
            "critical section (or wrap in lockdep.allow_blocking() if "
            "reviewed)\n" + "".join(traceback.format_stack(limit=8)))
    _real_sleep(seconds)


def _stack() -> str:
    return "".join(traceback.format_stack(limit=12)[:-3])


def _find_path(start: str, goal: str) -> list[str] | None:
    """DFS for a path start→…→goal in the order graph (caller holds
    ``_graph_lock``)."""
    stack = [(start, [start])]
    seen = {start}
    while stack:
        node, path = stack.pop()
        for succ in _graph.get(node, ()):
            if succ == goal:
                return path + [succ]
            if succ not in seen:
                seen.add(succ)
                stack.append((succ, path + [succ]))
    return None


def _record_edges(new_lock) -> None:
    """Called with the acquisition *about to happen*: check ranks and
    cycles against every lock the thread already holds, then record the
    edges."""
    held = _held()
    if not held:
        return
    new_name = new_lock._name
    for prior in held:
        prior_name = prior._name
        if prior_name == new_name:
            # Two same-named instances nested (e.g. parent/child rings).
            # Instance-level ordering of one class is out of scope for
            # the name-keyed graph; the self-deadlock case (same
            # *instance*) is raised separately in _DepLock.acquire.
            continue
        if (prior._order is not None and new_lock._order is not None
                and new_lock._order < prior._order):
            raise LockOrderViolation(
                f"declared-order violation: acquiring '{new_name}' "
                f"(rank {new_lock._order}) while holding '{prior_name}' "
                f"(rank {prior._order}) — lower ranks are outermost and "
                "must be taken first\n--- acquisition stack ---\n"
                + _stack())
        with _graph_lock:
            reverse = _find_path(new_name, prior_name)
            if reverse is not None:
                chain = " -> ".join(reverse)
                stacks = []
                for a, b in zip(reverse, reverse[1:]):
                    stacks.append(
                        f"--- earlier edge {a} -> {b} recorded at ---\n"
                        + _graph[a][b])
                raise LockOrderViolation(
                    f"lock-order inversion: this thread holds "
                    f"'{prior_name}' and is acquiring '{new_name}', but "
                    f"the opposite order {chain} was already observed "
                    "(potential deadlock)\n"
                    + "".join(stacks)
                    + "--- this acquisition ---\n" + _stack())
            edges = _graph.setdefault(prior_name, {})
            if new_name not in edges:
                edges[new_name] = _stack()


class _DepLock:
    """Instrumented non-reentrant lock."""

    _reentrant = False

    def __init__(self, name: str, order: int | None):
        self._name = name
        self._order = order
        self._inner = self._make_inner()
        self._count = 0          # recursion depth (RLock subclass)

    def _make_inner(self):
        return threading.Lock()

    def _owned_by_me(self) -> bool:
        return any(lk is self for lk in _held())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._owned_by_me():
            if not self._reentrant:
                raise LockOrderViolation(
                    f"self-deadlock: thread re-acquiring non-reentrant "
                    f"lock '{self._name}' it already holds\n" + _stack())
        else:
            _record_edges(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._count += 1
            if self._count == 1 or not self._reentrant:
                _held().append(self)
        return ok

    def release(self):
        self._count -= 1
        if self._count == 0 or not self._reentrant:
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep.{type(self).__name__} {self._name!r}>"


class _DepRLock(_DepLock):
    _reentrant = True

    def _make_inner(self):
        return threading.RLock()


class _DepCondition(_DepLock):
    """Instrumented condition variable. Acquire/release are tracked like
    a lock; ``wait``/``wait_for`` delegate to the real Condition (the
    thread is parked there, so the held-stack needs no adjustment — a
    blocked thread makes no acquisitions)."""

    def __init__(self, name: str, order: int | None):
        super().__init__(name, order)
        self._cond = threading.Condition(self._inner)

    def wait(self, timeout: float | None = None):
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()


def Lock(name: str = "anon", order: int | None = None):  # noqa: N802
    """A named lock: plain ``threading.Lock`` unless lockdep is enabled.
    ``order`` overrides the :data:`DECLARED_ORDER` rank for this name."""
    if not _enabled:
        return threading.Lock()
    return _DepLock(name, DECLARED_ORDER.get(name) if order is None
                    else order)


def RLock(name: str = "anon", order: int | None = None):  # noqa: N802
    if not _enabled:
        return threading.RLock()
    return _DepRLock(name, DECLARED_ORDER.get(name) if order is None
                     else order)


def Condition(name: str = "anon", order: int | None = None):  # noqa: N802
    if not _enabled:
        return threading.Condition()
    return _DepCondition(name, DECLARED_ORDER.get(name) if order is None
                         else order)


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn checking on for locks created *after* this call and patch
    ``time.sleep``. Prefer setting ``CLIENT_TPU_LOCKDEP=1`` before the
    process imports client_tpu so module-level locks are covered too."""
    global _enabled
    _enabled = True
    time.sleep = _checked_sleep


def disable() -> None:
    global _enabled
    _enabled = False
    time.sleep = _real_sleep


def reset() -> None:
    """Forget every recorded edge (test isolation)."""
    with _graph_lock:
        _graph.clear()


def graph() -> dict[str, list[str]]:
    """Snapshot of the observed order graph (name -> successors)."""
    with _graph_lock:
        return {k: sorted(v) for k, v in _graph.items()}


if _config.env_flag("CLIENT_TPU_LOCKDEP", os.environ):
    enable()
