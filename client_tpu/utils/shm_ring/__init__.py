"""Client-side zero-copy shm slot ring (TensorSocket-style data plane).

A single POSIX shm segment holds a lock-free single-producer /
single-consumer ring of fixed-size tensor slots (PAPERS.md, arXiv
2409.18749): the co-located client serializes request tensors straight
into a slot, rings one **batched doorbell** over the ordinary HTTP/gRPC
control channel for a whole span of FILLED slots, and then polls the
slot state words in shm for completion — the engine writes each
response back into the slot's response region, so neither request nor
response bytes ever cross a socket.

Segment layout (all word fields are aligned little-endian uint64, so
single-word loads/stores are atomic under the GIL)::

    [ header page, HEADER_BYTES ]
      0   magic           RING_MAGIC ("TPURING1")
      8   version         RING_VERSION
      16  slot_count
      24  slot_bytes      request payload capacity per slot
      32  resp_bytes      response capacity per slot
      40  producer_pid    liveness word (written once at create)
      64  head            producer cursor (cumulative slots published)
      128 tail            producer cursor (cumulative slots released)
      192 heartbeat       producer-owned activity counter (own cache line)
    [ state area: slot_count words at STATE_STRIDE spacing ]
      per-slot state word: FREE -> FILLED -> IN_FLIGHT -> DONE -> FREE
    [ payload area: slot_count x (slot_bytes + resp_bytes) ]

head and tail sit on separate cache lines and are written ONLY by the
producer, so the full/empty check never races the server; the server
owns the FILLED->IN_FLIGHT->DONE state transitions. Release/acquire
ordering is by program order under the GIL: the producer writes the
payload before storing FILLED, the server stores DONE only after the
response bytes land, and each side reads the state word before touching
the payload it guards.
"""

from __future__ import annotations

import json
import mmap
import os
import time

import numpy as np

from client_tpu.protocol.codec import deserialize_tensor, serialize_tensor
from client_tpu.protocol.dtypes import np_to_wire_dtype, wire_to_np_dtype

RING_MAGIC = 0x31474E4952555054          # b"TPURING1" little-endian
RING_VERSION = 1
HEADER_BYTES = 4096
STATE_STRIDE = 64                        # one cache line per state word

OFF_MAGIC = 0
OFF_VERSION = 8
OFF_SLOT_COUNT = 16
OFF_SLOT_BYTES = 24
OFF_RESP_BYTES = 32
OFF_PRODUCER_PID = 40
OFF_HEAD = 64
OFF_TAIL = 128
OFF_HEARTBEAT = 192

SLOT_FREE = 0
SLOT_FILLED = 1
SLOT_IN_FLIGHT = 2
SLOT_DONE = 3

STATE_NAMES = {SLOT_FREE: "FREE", SLOT_FILLED: "FILLED",
               SLOT_IN_FLIGHT: "IN_FLIGHT", SLOT_DONE: "DONE"}


class ShmRingError(Exception):
    pass


def _align64(n: int) -> int:
    return (int(n) + 63) & ~63


def ring_total_bytes(slot_count: int, slot_bytes: int,
                     resp_bytes: int) -> int:
    return (HEADER_BYTES + slot_count * STATE_STRIDE
            + slot_count * (slot_bytes + resp_bytes))


def _key_path(shm_key: str) -> str:
    return "/dev/shm/" + shm_key.lstrip("/")


class RingBuffer:
    """The mapped ring segment; producer-side cursor/state accessors.

    Word accessors go through a uint64 numpy view over the (8-aligned)
    header+state prefix of the mapping — aligned single-word loads and
    stores, which is the atomicity the SPSC protocol needs.
    """

    def __init__(self, key: str, fd: int, map_: mmap.mmap, *,
                 created: bool):
        self.key = key
        self._fd = fd
        self._map = map_
        self._created = created
        self._closed = False
        words = np.frombuffer(self._map, dtype="<u8",
                              count=HEADER_BYTES // 8)
        if int(words[OFF_MAGIC // 8]) != RING_MAGIC:
            raise ShmRingError(f"'{key}' is not a ring segment (bad magic)")
        if int(words[OFF_VERSION // 8]) != RING_VERSION:
            raise ShmRingError(
                f"ring '{key}': unsupported version "
                f"{int(words[OFF_VERSION // 8])}")
        self.slot_count = int(words[OFF_SLOT_COUNT // 8])
        self.slot_bytes = int(words[OFF_SLOT_BYTES // 8])
        self.resp_bytes = int(words[OFF_RESP_BYTES // 8])
        self.total_bytes = ring_total_bytes(
            self.slot_count, self.slot_bytes, self.resp_bytes)
        if len(self._map) < self.total_bytes:
            raise ShmRingError(
                f"ring '{key}': segment truncated "
                f"({len(self._map)} < {self.total_bytes})")
        self._words = np.frombuffer(
            self._map, dtype="<u8",
            count=(HEADER_BYTES + self.slot_count * STATE_STRIDE) // 8)

    # -- creation / attachment ----------------------------------------------

    @classmethod
    def create(cls, shm_key: str, slot_count: int, slot_bytes: int,
               resp_bytes: int) -> "RingBuffer":
        """Create (or re-initialize) the segment and write a fresh header."""
        if slot_count < 1:
            raise ShmRingError("slot_count must be >= 1")
        slot_bytes = _align64(slot_bytes)
        resp_bytes = _align64(resp_bytes)
        total = ring_total_bytes(slot_count, slot_bytes, resp_bytes)
        path = _key_path(shm_key)
        existed = os.path.exists(path)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            map_ = mmap.mmap(fd, total)
        except Exception:
            os.close(fd)
            raise
        header = np.frombuffer(map_, dtype="<u8", count=HEADER_BYTES // 8)
        header[:] = 0
        header[OFF_SLOT_COUNT // 8] = slot_count
        header[OFF_SLOT_BYTES // 8] = slot_bytes
        header[OFF_RESP_BYTES // 8] = resp_bytes
        header[OFF_VERSION // 8] = RING_VERSION
        # state words before the magic: an attacher that sees the magic
        # must see a fully initialized ring
        states = np.frombuffer(
            map_, dtype="<u8", offset=HEADER_BYTES,
            count=slot_count * STATE_STRIDE // 8)
        states[:] = 0
        # Liveness word: the engine-side reaper probes this pid to fail
        # and detach rings whose producer died mid-fill.
        header[OFF_PRODUCER_PID // 8] = os.getpid()
        header[OFF_MAGIC // 8] = RING_MAGIC
        return cls(shm_key, fd, map_, created=not existed)

    @classmethod
    def attach(cls, shm_key: str) -> "RingBuffer":
        path = _key_path(shm_key)
        if not os.path.exists(path):
            raise ShmRingError(f"ring segment '{shm_key}' does not exist")
        fd = os.open(path, os.O_RDWR)
        try:
            map_ = mmap.mmap(fd, 0)
        except Exception:
            os.close(fd)
            raise
        try:
            return cls(shm_key, fd, map_, created=False)
        except Exception:
            try:
                map_.close()
            except BufferError:
                pass  # a validation-path numpy view still holds the map
            os.close(fd)
            raise

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self._words = None
        try:
            self._map.close()
        except BufferError:
            self._map = None   # outstanding views; GC unmaps later
        if self._fd >= 0:
            fd, self._fd = self._fd, -1
            os.close(fd)
        if unlink and self._created:
            try:
                os.unlink(_key_path(self.key))
            except FileNotFoundError:
                pass

    # -- word accessors ------------------------------------------------------

    @property
    def head(self) -> int:
        return int(self._words[OFF_HEAD // 8])

    @property
    def tail(self) -> int:
        return int(self._words[OFF_TAIL // 8])

    @property
    def occupancy(self) -> int:
        return self.head - self.tail

    @property
    def producer_pid(self) -> int:
        return int(self._words[OFF_PRODUCER_PID // 8])

    @property
    def heartbeat(self) -> int:
        return int(self._words[OFF_HEARTBEAT // 8])

    def beat(self) -> None:
        """Bump the producer activity counter (producer-owned word)."""
        self._words[OFF_HEARTBEAT // 8] += 1

    def _bump(self, word_off: int) -> None:
        self._words[word_off // 8] += 1

    def state(self, slot: int) -> int:
        return int(self._words[(HEADER_BYTES
                                + slot * STATE_STRIDE) // 8])

    def set_state(self, slot: int, value: int) -> None:
        self._words[(HEADER_BYTES + slot * STATE_STRIDE) // 8] = value

    # -- payload windows -----------------------------------------------------

    def _payload_base(self) -> int:
        return HEADER_BYTES + self.slot_count * STATE_STRIDE

    def request_offset(self, slot: int) -> int:
        """Byte offset of the slot's request region within the segment."""
        return self._payload_base() + slot * (self.slot_bytes
                                              + self.resp_bytes)

    def response_offset(self, slot: int) -> int:
        return self.request_offset(slot) + self.slot_bytes

    def request_view(self, slot: int) -> memoryview:
        off = self.request_offset(slot)
        return memoryview(self._map)[off:off + self.slot_bytes]

    def response_view(self, slot: int) -> memoryview:
        off = self.response_offset(slot)
        return memoryview(self._map)[off:off + self.resp_bytes]

    # -- producer protocol ---------------------------------------------------

    def acquire(self) -> int | None:
        """Next free slot index, or None when the ring is full."""
        if self.head - self.tail >= self.slot_count:
            return None
        slot = self.head % self.slot_count
        if self.state(slot) != SLOT_FREE:
            return None
        return slot

    def fill(self, inputs: dict) -> tuple[int, list] | None:
        """Serialize ``{name: ndarray}`` back-to-back into the next free
        slot and publish it (state FILLED, head+1). Returns
        ``(slot, meta)`` where meta is the per-input placement list the
        doorbell carries, or None on backpressure (ring full)."""
        slot = self.acquire()
        if slot is None:
            return None
        view = self.request_view(slot)
        meta = []
        pos = 0
        for name, arr in inputs.items():
            arr = np.asarray(arr)
            raw = serialize_tensor(arr, np_to_wire_dtype(arr.dtype))
            if pos + len(raw) > self.slot_bytes:
                raise ShmRingError(
                    f"inputs exceed slot_bytes ({self.slot_bytes})")
            view[pos:pos + len(raw)] = raw
            meta.append({"name": name,
                         "datatype": np_to_wire_dtype(arr.dtype),
                         "shape": list(arr.shape),
                         "offset": pos, "byte_size": len(raw)})
            pos += len(raw)
        self.set_state(slot, SLOT_FILLED)   # payload before state: release
        self._bump(OFF_HEAD)
        return slot, meta

    def fill_staged(self, dataset, refs: dict) -> tuple[int, list] | None:
        """Stage one request by *reference*: write a 24-byte
        ``(tensor, row_start, row_count)`` descriptor per input instead
        of tensor bytes. ``dataset`` is an attached
        :class:`~client_tpu.utils.shm_ring.staged.StagedDataset` and
        ``refs`` maps ``{input_name: (tensor_name, row_start,
        row_count)}``. Returns ``(slot, meta)`` or None when the ring is
        full."""
        from client_tpu.utils.shm_ring.staged import DESCRIPTOR_BYTES

        slot = self.acquire()
        if slot is None:
            return None
        view = self.request_view(slot)
        meta = []
        pos = 0
        for input_name, (tensor, row_start, row_count) in refs.items():
            desc = dataset.descriptor(tensor, row_start, row_count)
            if pos + DESCRIPTOR_BYTES > self.slot_bytes:
                raise ShmRingError(
                    f"descriptors exceed slot_bytes ({self.slot_bytes})")
            view[pos:pos + DESCRIPTOR_BYTES] = desc
            meta.append({"name": input_name, "staged": True,
                         "offset": pos, "byte_size": DESCRIPTOR_BYTES})
            pos += DESCRIPTOR_BYTES
        self.set_state(slot, SLOT_FILLED)   # payload before state: release
        self._bump(OFF_HEAD)
        return slot, meta

    def poll(self, timeout_s: float = 10.0,
             spin_sleep_s: float | None = None) -> int:
        """Block until the OLDEST outstanding slot completes; returns its
        index. Release order is ring order, which keeps head/tail exact.

        ``spin_sleep_s=None`` (default) spins a short bounded burst and
        then backs off to 100 us sleeps: the producer shares a machine —
        and under an in-process server, a GIL — with the engine, so an
        unbounded pure spin slows down the very completions it is waiting
        for. Pass ``0.0`` to force a pure spin (dedicated-core setups) or
        an explicit sleep interval to fix the backoff."""
        if self.head == self.tail:
            raise ShmRingError("poll() with no outstanding slots")
        slot = self.tail % self.slot_count
        deadline = time.monotonic() + timeout_s
        spins = 0
        while self.state(slot) != SLOT_DONE:
            if time.monotonic() >= deadline:
                raise ShmRingError(
                    f"slot {slot} not DONE after {timeout_s}s "
                    f"(state {STATE_NAMES.get(self.state(slot))})")
            if spin_sleep_s is None:
                spins += 1
                if spins > 256:
                    time.sleep(100e-6)
            elif spin_sleep_s:
                time.sleep(spin_sleep_s)
        return slot

    def read_response(self, slot: int, copy: bool = True):
        """Decode a DONE slot's response region ->
        ``(outputs: {name: ndarray}, error: str | None)``. With
        ``copy=False`` fixed-size outputs are zero-copy views valid only
        until :meth:`release`."""
        view = self.response_view(slot)
        hlen = int(np.frombuffer(view[:8], dtype="<u8")[0])
        if hlen <= 0 or 8 + hlen > self.resp_bytes:
            raise ShmRingError(
                f"slot {slot}: corrupt response header ({hlen}B)")
        header = json.loads(bytes(view[8:8 + hlen]).decode("utf-8"))
        if header.get("error"):
            return {}, header["error"]
        outputs = {}
        pos = 8 + hlen
        for out in header.get("outputs", []):
            raw = view[pos:pos + int(out["byte_size"])]
            if out["datatype"] == "BYTES":
                arr = deserialize_tensor(bytes(raw), "BYTES", out["shape"])
            else:
                arr = np.frombuffer(
                    raw, dtype=wire_to_np_dtype(out["datatype"])
                ).reshape(tuple(int(d) for d in out["shape"]))
                if copy:
                    arr = arr.copy()
            outputs[out["name"]] = arr
            pos += int(out["byte_size"])
        return outputs, None

    def release(self, slot: int) -> None:
        """Hand a consumed DONE slot back to the pool (state FREE,
        tail+1). Must be called in poll() order."""
        if slot != self.tail % self.slot_count:
            raise ShmRingError(
                f"release out of order: slot {slot}, expected "
                f"{self.tail % self.slot_count}")
        self.set_state(slot, SLOT_FREE)
        self._bump(OFF_TAIL)


class RingProducer:
    """Context manager pairing a :class:`RingBuffer` with a client's ring
    control surface (``register_shm_ring`` / ``ring_doorbell`` /
    ``unregister_shm_ring`` — both Python clients provide these)::

        with RingProducer(client, "ring0", "/tpu_ring0",
                          slot_count=64, slot_bytes=1 << 20) as prod:
            prod.fill({"INPUT": img})
            prod.doorbell("resnet50")
            slot, outputs, err = prod.reap()

    ``fill`` accumulates a pending span; ``doorbell`` submits it in one
    control-channel round trip; ``reap`` polls shm for the oldest
    completion. One producer per ring (SPSC) — many producers per host
    mean many rings, multiplexed server-side by the reaper.

    Fan-in extensions:

    * ``dataset=`` (an attached :class:`staged.StagedDataset`) +
      ``dataset_name=`` (its server-registered name) arm
      :meth:`fill_staged`, which stages 24-byte row descriptors instead
      of tensor bytes;
    * ``spec=`` registers the ring in **reaped mode**: the span spec
      (``model_name``, ``inputs`` metadata, optional
      ``outputs``/``timeout_ms``/``priority``/``dataset``) is fixed at
      register time, the engine-side reaper sweeps FILLED slots without
      any doorbell call, and :meth:`doorbell` becomes invalid.
    """

    def __init__(self, client, name: str, shm_key: str, *,
                 slot_count: int = 64, slot_bytes: int = 1 << 20,
                 resp_bytes: int | None = None, dataset=None,
                 dataset_name: str | None = None,
                 spec: dict | None = None):
        self._client = client
        self.name = name
        self.shm_key = shm_key
        self._slot_count = slot_count
        self._slot_bytes = slot_bytes
        self._resp_bytes = (slot_bytes + 4096 if resp_bytes is None
                            else resp_bytes)
        self._dataset = dataset
        self._dataset_name = dataset_name
        self._spec = dict(spec) if spec is not None else None
        self.ring: RingBuffer | None = None
        self._pending: list[int] = []
        self._meta: list | None = None

    @property
    def reaped(self) -> bool:
        return self._spec is not None

    def __enter__(self) -> "RingProducer":
        self.ring = RingBuffer.create(
            self.shm_key, self._slot_count, self._slot_bytes,
            self._resp_bytes)
        try:
            if self._spec is not None:
                self._client.register_shm_ring(self.name, self.shm_key,
                                               spec=self._spec)
            else:
                self._client.register_shm_ring(self.name, self.shm_key)
        except Exception:
            self.ring.close(unlink=True)
            self.ring = None
            raise
        return self

    def __exit__(self, *exc) -> None:
        try:
            self._client.unregister_shm_ring(self.name)
        # tpulint: allow[swallowed-exception] reviewed fail-open
        except Exception:
            pass
        if self.ring is not None:
            self.ring.close(unlink=True)
            self.ring = None

    def fill(self, inputs: dict) -> int | None:
        """Stage one request into the next free slot; None = ring full
        (reap completions, then retry). All requests in one doorbell span
        must share tensor names/shapes/dtypes."""
        filled = self.ring.fill(inputs)
        if filled is None:
            return None
        slot, meta = filled
        if self._spec is None:
            # doorbell mode: accumulate the span (a reaped ring's spans
            # are swept server-side; nothing to accumulate)
            if self._meta is None:
                self._meta = meta
            self._pending.append(slot)
        self.ring.beat()
        return slot

    def fill_staged(self, refs: dict) -> int | None:
        """Stage one request by staged-dataset reference:
        ``{input_name: (tensor_name, row_start, row_count)}`` against
        the producer's ``dataset=``. None = ring full."""
        if self._dataset is None:
            raise ShmRingError(
                "fill_staged needs RingProducer(dataset=...)")
        filled = self.ring.fill_staged(self._dataset, refs)
        if filled is None:
            return None
        slot, meta = filled
        if self._spec is None:
            if self._meta is None:
                self._meta = meta
            self._pending.append(slot)
        self.ring.beat()
        return slot

    def doorbell(self, model_name: str, model_version: str = "", *,
                 outputs=None, timeout_ms: float = 0.0,
                 priority: int = 0, tenant: str = "",
                 headers=None) -> dict:
        """Submit the pending span in one control-channel round trip."""
        if self._spec is not None:
            raise ShmRingError(
                f"ring '{self.name}' is reaped — the engine sweeps "
                "FILLED slots; no doorbell needed")
        if not self._pending:
            return {"admitted": 0, "rejected": 0}
        spec = {
            "start": self._pending[0],
            "count": len(self._pending),
            "model_name": model_name,
            "model_version": model_version,
            "inputs": self._meta,
        }
        if any(m.get("staged") for m in self._meta):
            if not self._dataset_name:
                raise ShmRingError(
                    "staged fills need RingProducer(dataset_name=...) — "
                    "the server-registered dataset name")
            spec["dataset"] = self._dataset_name
        if outputs:
            spec["outputs"] = list(outputs)
        if timeout_ms:
            spec["timeout_ms"] = float(timeout_ms)
        if priority:
            spec["priority"] = int(priority)
        if tenant:
            # Cost-ledger tenant tag — the shm analogue of the HTTP
            # X-Tpu-Tenant header (rides in the span spec slot header).
            spec["tenant"] = str(tenant)
        self._pending = []
        self._meta = None
        return self._client.ring_doorbell(self.name, spec, headers=headers)

    def reap(self, timeout_s: float = 10.0, copy: bool = True,
             spin_sleep_s: float | None = None):
        """Wait for the oldest outstanding slot; returns
        ``(slot, outputs, error)`` with the slot released.
        ``spin_sleep_s`` is forwarded to :meth:`RingBuffer.poll` —
        background/shadow producers should pass a coarse interval
        (milliseconds): they don't need reap latency, and a fleet of
        them at the default 100 us backoff measurably steals host CPU
        from the live plane it is supposed to shadow."""
        slot = self.ring.poll(timeout_s=timeout_s,
                              spin_sleep_s=spin_sleep_s)
        outputs, error = self.ring.read_response(slot, copy=copy)
        self.ring.release(slot)
        self.ring.beat()
        return slot, outputs, error

    @property
    def pending(self) -> int:
        return len(self._pending)

    @property
    def outstanding(self) -> int:
        """Slots published but not yet released (includes pending)."""
        return self.ring.occupancy if self.ring is not None else 0


def staged_inputs_meta(refs: dict) -> list[dict]:
    """The ``inputs`` metadata a span of :meth:`RingBuffer.fill_staged`
    fills with the same ``refs`` structure will carry — for building a
    reaped-mode register ``spec`` before the first fill."""
    from client_tpu.utils.shm_ring.staged import DESCRIPTOR_BYTES

    return [{"name": input_name, "staged": True,
             "offset": i * DESCRIPTOR_BYTES,
             "byte_size": DESCRIPTOR_BYTES}
            for i, input_name in enumerate(refs)]


__all__ = [
    "HEADER_BYTES", "RING_MAGIC", "RING_VERSION", "STATE_STRIDE",
    "OFF_PRODUCER_PID", "OFF_HEARTBEAT",
    "SLOT_FREE", "SLOT_FILLED", "SLOT_IN_FLIGHT", "SLOT_DONE",
    "RingBuffer", "RingProducer", "ShmRingError", "ring_total_bytes",
    "staged_inputs_meta",
]
