"""Client-side staged-dataset segment (the shared read-only data half
of the many-producer shm fan-in plane).

A staged dataset is a POSIX shm segment created ONCE per host that holds
a manifest of named tensors: the dtype/shape/offset table lives in the
header region and the raw tensor payloads are packed behind it. Any
number of co-located producers attach the same segment read-only and
reference rows of its tensors by ``(dataset, tensor, offset)``
descriptors in their ring slots (24 bytes per input) instead of copying
tensor bytes into the slot — the TensorSocket sharing model (PAPERS.md,
arXiv 2409.18749): one copy of the dataset in memory no matter how many
producers replay it.

Segment layout (word fields are aligned little-endian uint64)::

    [ header words ]
      0   magic           DSET_MAGIC ("TPUDSET1")
      8   version         DSET_VERSION
      16  tensor_count
      24  manifest_bytes  length of the JSON manifest at byte 64
      32  payload_base    byte offset of the packed payload (page aligned)
      40  total_bytes     full segment size
    [ manifest JSON at byte 64: [{"name","datatype","shape","offset",
      "byte_size"}, ...], offsets relative to payload_base ]
    [ payload: tensors back-to-back, each 64-byte aligned ]

The magic is written last, so an attacher that sees it sees a complete
manifest and payload. The segment is immutable after build — producers
and the engine map it read-only in spirit; nothing ever writes past
creation, which is what makes the one-copy sharing safe without locks.

Descriptor wire format (one per staged input, in the ring slot's
request region)::

    [uint64 tensor_index][uint64 row_start][uint64 row_count]

resolved server-side as a zero-copy row-slice view of the manifest
tensor: shape ``[row_count, *tensor.shape[1:]]``.
"""

from __future__ import annotations

import json
import mmap
import os

import numpy as np

from client_tpu.protocol.dtypes import np_to_wire_dtype, wire_to_np_dtype

DSET_MAGIC = 0x3154455344555054         # b"TPUDSET1" little-endian
DSET_VERSION = 1
DSET_MANIFEST_OFF = 64                  # JSON manifest starts here

OFF_DSET_MAGIC = 0
OFF_DSET_VERSION = 8
OFF_DSET_TENSOR_COUNT = 16
OFF_DSET_MANIFEST_BYTES = 24
OFF_DSET_PAYLOAD_BASE = 32
OFF_DSET_TOTAL_BYTES = 40

DESCRIPTOR_BYTES = 24                   # [tensor_idx][row_start][row_count]


class StagedDatasetError(Exception):
    pass


def _align(n: int, a: int) -> int:
    return (int(n) + a - 1) & ~(a - 1)


def _key_path(shm_key: str) -> str:
    return "/dev/shm/" + shm_key.lstrip("/")


def pack_descriptor(tensor_index: int, row_start: int,
                    row_count: int) -> bytes:
    return np.asarray([tensor_index, row_start, row_count],
                      dtype="<u8").tobytes()


def unpack_descriptor(raw) -> tuple[int, int, int]:
    words = np.frombuffer(bytes(raw[:DESCRIPTOR_BYTES]), dtype="<u8")
    if words.size != 3:
        raise StagedDatasetError(
            f"descriptor must be {DESCRIPTOR_BYTES} bytes")
    return int(words[0]), int(words[1]), int(words[2])


def build_staged_dataset(shm_key: str,
                         tensors: dict[str, np.ndarray]) -> "StagedDataset":
    """Create the segment and pack ``{name: ndarray}`` behind a manifest.

    Tensors must be fixed-dtype (no BYTES/object arrays — row slicing
    needs a constant row stride) and at least rank 1 (axis 0 is the row
    axis producers index).
    """
    if not tensors:
        raise StagedDatasetError("staged dataset needs at least one tensor")
    packed: list[tuple[dict, np.ndarray]] = []
    pos = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype == object:
            raise StagedDatasetError(
                f"tensor '{name}': BYTES/object tensors cannot be staged "
                "(no fixed row stride)")
        if arr.ndim < 1:
            raise StagedDatasetError(
                f"tensor '{name}': staged tensors need a row axis "
                "(rank >= 1)")
        pos = _align(pos, 64)
        packed.append((
            {"name": str(name),
             "datatype": np_to_wire_dtype(arr.dtype),
             "shape": list(arr.shape),
             "offset": pos,
             "byte_size": int(arr.nbytes)}, arr))
        pos += int(arr.nbytes)
    manifest = json.dumps([m for m, _ in packed]).encode("utf-8")
    payload_base = _align(DSET_MANIFEST_OFF + len(manifest), 4096)
    total = payload_base + pos
    path = _key_path(shm_key)
    existed = os.path.exists(path)
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
    try:
        os.ftruncate(fd, total)
        map_ = mmap.mmap(fd, total)
    except Exception:
        os.close(fd)
        raise
    words = np.frombuffer(map_, dtype="<u8", count=DSET_MANIFEST_OFF // 8)
    words[:] = 0
    words[OFF_DSET_VERSION // 8] = DSET_VERSION
    words[OFF_DSET_TENSOR_COUNT // 8] = len(packed)
    words[OFF_DSET_MANIFEST_BYTES // 8] = len(manifest)
    words[OFF_DSET_PAYLOAD_BASE // 8] = payload_base
    words[OFF_DSET_TOTAL_BYTES // 8] = total
    map_[DSET_MANIFEST_OFF:DSET_MANIFEST_OFF + len(manifest)] = manifest
    for meta, arr in packed:
        start = payload_base + meta["offset"]
        map_[start:start + meta["byte_size"]] = arr.tobytes()
    # magic last: an attacher that sees it sees a complete dataset
    words[OFF_DSET_MAGIC // 8] = DSET_MAGIC
    return StagedDataset(shm_key, fd, map_, created=not existed)


class StagedDataset:
    """A mapped staged-dataset segment: manifest lookups, zero-copy
    tensor views, and descriptor packing for producers."""

    def __init__(self, key: str, fd: int, map_: mmap.mmap, *,
                 created: bool):
        self.key = key
        self._fd = fd
        self._map = map_
        self._created = created
        self._closed = False
        words = np.frombuffer(map_, dtype="<u8",
                              count=DSET_MANIFEST_OFF // 8)
        if int(words[OFF_DSET_MAGIC // 8]) != DSET_MAGIC:
            raise StagedDatasetError(
                f"'{key}' is not a staged-dataset segment (bad magic)")
        if int(words[OFF_DSET_VERSION // 8]) != DSET_VERSION:
            raise StagedDatasetError(
                f"dataset '{key}': unsupported version "
                f"{int(words[OFF_DSET_VERSION // 8])}")
        manifest_bytes = int(words[OFF_DSET_MANIFEST_BYTES // 8])
        self.payload_base = int(words[OFF_DSET_PAYLOAD_BASE // 8])
        self.total_bytes = int(words[OFF_DSET_TOTAL_BYTES // 8])
        raw = bytes(map_[DSET_MANIFEST_OFF:
                         DSET_MANIFEST_OFF + manifest_bytes])
        self.manifest: list[dict] = json.loads(raw.decode("utf-8"))
        self._index = {m["name"]: i for i, m in enumerate(self.manifest)}

    @classmethod
    def attach(cls, shm_key: str) -> "StagedDataset":
        path = _key_path(shm_key)
        if not os.path.exists(path):
            raise StagedDatasetError(
                f"staged dataset '{shm_key}' does not exist")
        fd = os.open(path, os.O_RDWR)
        try:
            map_ = mmap.mmap(fd, 0)
        except Exception:
            os.close(fd)
            raise
        try:
            return cls(shm_key, fd, map_, created=False)
        except Exception:
            try:
                map_.close()
            except BufferError:
                pass
            os.close(fd)
            raise

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._map.close()
        except BufferError:
            self._map = None   # outstanding tensor views; GC unmaps later
        if self._fd >= 0:
            fd, self._fd = self._fd, -1
            os.close(fd)
        if unlink and self._created:
            try:
                os.unlink(_key_path(self.key))
            except FileNotFoundError:
                pass

    def __enter__(self) -> "StagedDataset":
        return self

    def __exit__(self, *exc) -> None:
        self.close(unlink=True)

    # -- manifest lookups ----------------------------------------------------

    @property
    def names(self) -> list[str]:
        return [m["name"] for m in self.manifest]

    def index(self, tensor: str) -> int:
        idx = self._index.get(tensor)
        if idx is None:
            raise StagedDatasetError(
                f"dataset '{self.key}' has no tensor '{tensor}' "
                f"(has: {', '.join(self._index)})")
        return idx

    def rows(self, tensor: str) -> int:
        return int(self.manifest[self.index(tensor)]["shape"][0])

    def tensor(self, tensor: str) -> np.ndarray:
        """Zero-copy view of a whole manifest tensor."""
        m = self.manifest[self.index(tensor)]
        start = self.payload_base + int(m["offset"])
        view = memoryview(self._map)[start:start + int(m["byte_size"])]
        return np.frombuffer(view, dtype=wire_to_np_dtype(m["datatype"])
                             ).reshape(tuple(int(d) for d in m["shape"]))

    def descriptor(self, tensor: str, row_start: int,
                   row_count: int) -> bytes:
        """Pack (and bounds-check) one staged-input descriptor."""
        idx = self.index(tensor)
        n_rows = int(self.manifest[idx]["shape"][0])
        if row_start < 0 or row_count < 1 \
                or row_start + row_count > n_rows:
            raise StagedDatasetError(
                f"rows [{row_start}, {row_start + row_count}) outside "
                f"tensor '{tensor}' ({n_rows} rows)")
        return pack_descriptor(idx, row_start, row_count)


__all__ = [
    "DESCRIPTOR_BYTES",
    "DSET_MAGIC",
    "DSET_MANIFEST_OFF",
    "DSET_VERSION",
    "StagedDataset",
    "StagedDatasetError",
    "build_staged_dataset",
    "pack_descriptor",
    "unpack_descriptor",
]
