"""Client-facing utility API.

Function names/signatures intentionally match the reference's
``tritonclient.utils`` (np_to_triton_dtype / triton_to_np_dtype /
serialize_byte_tensor / deserialize_bytes_tensor / InferenceServerException,
/root/reference/src/python/library/tritonclient/utils/__init__.py:65-271) so
reference users can switch imports without code changes. Implementations
delegate to :mod:`client_tpu.protocol`.
"""

from __future__ import annotations

import numpy as np

from client_tpu.protocol import codec as _codec
from client_tpu.protocol import dtypes as _dtypes


class InferenceServerException(Exception):
    """Exception raised by client APIs; carries optional status + debug details."""

    def __init__(self, msg, status=None, debug_details=None):
        self._msg = msg
        self._status = status
        self._debug_details = debug_details
        super().__init__(msg)

    def __str__(self):
        msg = super().__str__() if self._msg is None else self._msg
        if self._status is not None:
            msg = "[" + str(self._status) + "] " + msg
        return msg

    def message(self):
        return self._msg

    def status(self):
        return self._status

    def debug_details(self):
        return self._debug_details


def raise_error(msg):
    raise InferenceServerException(msg=msg)


def np_to_triton_dtype(np_dtype):
    return _dtypes.np_to_wire_dtype(np_dtype)


def triton_to_np_dtype(dtype):
    return _dtypes.wire_to_np_dtype(dtype)


def serialize_byte_tensor(input_tensor: np.ndarray):
    """BYTES tensor -> flat uint8-viewable array of the 4B-LE-prefixed wire
    form (returned as np array to match the reference's return type)."""
    if input_tensor.size == 0:
        return np.empty([0], dtype=np.uint8)
    raw = _codec.serialize_bytes_tensor(input_tensor)
    return np.frombuffer(raw, dtype=np.uint8)


def serialized_byte_size(tensor_value: np.ndarray) -> int:
    if tensor_value.size == 0:
        return 0
    return len(_codec.serialize_bytes_tensor(tensor_value))


def deserialize_bytes_tensor(encoded_tensor) -> np.ndarray:
    if isinstance(encoded_tensor, np.ndarray):
        encoded_tensor = encoded_tensor.tobytes()
    return _codec.deserialize_bytes_tensor(bytes(encoded_tensor))


def deserialize_bf16_tensor(encoded_tensor) -> np.ndarray:
    """Raw little-endian BF16 bytes -> ml_dtypes.bfloat16 ndarray (flat)."""
    if isinstance(encoded_tensor, np.ndarray):
        encoded_tensor = encoded_tensor.tobytes()
    return np.frombuffer(bytes(encoded_tensor),
                         dtype=_dtypes.wire_to_np_dtype("BF16"))
