"""Client-side TPU shared-memory utilities — the CUDA-shm replacement.

API mirrors the reference's ``tritonclient.utils.cuda_shared_memory``
(/root/reference/src/python/library/tritonclient/utils/cuda_shared_memory/
__init__.py:46-270): create a region, get an opaque raw handle to register
with the server, set/get tensors. The reference's handle is a
base64-serializable ``cudaIpcMemHandle_t``; ours is a serialized descriptor
of the region's host staging buffer (see
:mod:`client_tpu.engine.shm` for the server-side semantics — cross-process
HBM export is not a public libtpu capability, so the region contract is
"zero network bytes, one host↔HBM DMA", with true zero-copy on the
in-process path).
"""

from __future__ import annotations

import os
import uuid

import numpy as np

from client_tpu.engine.shm import make_tpu_handle
from client_tpu.protocol.codec import b64_encode_handle
from client_tpu.utils import shared_memory as _sysshm


class TpuSharedMemoryException(Exception):
    pass


class TpuSharedMemoryRegion:
    def __init__(self, triton_shm_name: str, byte_size: int, device_id: int,
                 staging: "_sysshm.SharedMemoryRegion"):
        self.triton_shm_name = triton_shm_name
        self.byte_size = byte_size
        self.device_id = device_id
        self._staging = staging


_regions: dict[str, TpuSharedMemoryRegion] = {}


def create_shared_memory_region(triton_shm_name, byte_size,
                                device_id=0) -> TpuSharedMemoryRegion:
    key = f"/tpushm_{uuid.uuid4().hex[:12]}"
    staging = _sysshm.create_shared_memory_region(
        f"{triton_shm_name}__staging", key, byte_size)
    region = TpuSharedMemoryRegion(triton_shm_name, byte_size, device_id,
                                   staging)
    _regions[triton_shm_name] = region
    return region


def get_raw_handle(shm_handle: TpuSharedMemoryRegion) -> bytes:
    """Opaque handle bytes for Register calls (raw in gRPC proto; the HTTP
    client base64-wraps them, mirroring the reference's handle transport)."""
    return make_tpu_handle(shm_handle._staging.shm_key,
                           shm_handle.byte_size, shm_handle.device_id)


def get_raw_handle_b64(shm_handle: TpuSharedMemoryRegion) -> str:
    return b64_encode_handle(get_raw_handle(shm_handle))


def set_shared_memory_region(shm_handle: TpuSharedMemoryRegion, input_values,
                             offset=0) -> None:
    _sysshm.set_shared_memory_region(shm_handle._staging, input_values,
                                     offset=offset)


def get_contents_as_numpy(shm_handle: TpuSharedMemoryRegion, datatype, shape,
                          offset=0) -> np.ndarray:
    return _sysshm.get_contents_as_numpy(shm_handle._staging, datatype,
                                         shape, offset=offset)


def allocated_shared_memory_regions():
    return list(_regions.keys())


def destroy_shared_memory_region(shm_handle: TpuSharedMemoryRegion) -> None:
    _regions.pop(shm_handle.triton_shm_name, None)
    _sysshm.destroy_shared_memory_region(shm_handle._staging)
