"""client_tpu — a TPU-native inference client/serving ecosystem.

A brand-new framework with the capabilities of the Triton Inference Server
client stack (reference: /root/reference, hmahadik/client): C++/Python client
libraries speaking the KServe v2 protocol over HTTP and gRPC (sync, async,
bidirectional streaming), a shared-memory zero-copy tensor I/O data plane in
which CUDA-IPC regions are replaced by XLA/PjRt TPU-HBM buffer handles
(``tpu_shared_memory``), an in-process TPU serving engine (JAX/XLA/pjit/Pallas)
taking the place of the dlopen'd ``libtritonserver.so``, and a perf_analyzer
equivalent load/latency benchmarking harness.

Package map (mirrors the reference's layer map, SURVEY.md §1):

- ``client_tpu.protocol``  — L1/L2 wire schema: dtypes, BYTES codec, HTTP
  binary framing, gRPC protos.
- ``client_tpu.engine``    — L0 in-process TPU serving engine (the
  ``libtritonserver.so`` equivalent, TPU-first).
- ``client_tpu.models``    — model zoo (simple add/sub, ResNet50, DenseNet,
  BERT, SSD-MobileNet, MoE) as JAX/flax modules.
- ``client_tpu.server``    — HTTP and gRPC network frontends over the engine.
- ``client_tpu.http`` / ``client_tpu.grpc`` — L3 Python client libraries
  (API-compatible in spirit with ``tritonclient.http`` / ``tritonclient.grpc``).
- ``client_tpu.utils``     — dtype helpers, BYTES tensor codec,
  ``shared_memory`` (POSIX) and ``tpu_shared_memory`` (HBM) utilities.
- ``client_tpu.perf``      — L5 benchmarking harness (perf_analyzer
  equivalent: concurrency / request-rate / custom-interval load managers and
  the stability-searched inference profiler).
- ``client_tpu.parallel``  — device mesh + sharding helpers for multi-chip
  serving (tp/dp/sp over ``jax.sharding.Mesh``).
"""

__version__ = "0.1.0"
