"""Python gRPC client library.

API mirrors the reference's ``tritonclient.grpc``
(/root/reference/src/python/library/tritonclient/grpc/__init__.py:146-1445):
``InferenceServerClient`` with the full control plane, unary ``infer``,
future-based ``async_infer``, and bidirectional streaming
(``start_stream`` / ``async_stream_infer`` / ``stop_stream``). Mechanisms
carried over from the reference design: a process-global channel cache keyed
by URL (grpc_client.cc:48-123) and request-proto reuse across calls
(grpc_client.cc:1113-1210).
"""

from __future__ import annotations

import base64
import itertools
import json
import queue
import threading
from client_tpu.utils import lockdep
import time

import grpc
import numpy as np

import logging

from client_tpu.observability.client_stats import InferStat
from client_tpu.observability.tracing import TraceContext
from client_tpu.protocol import grpc_codec, grpc_service_pb2 as pb
from client_tpu.protocol.pushback import parse_pushback_metadata
from client_tpu.resilience import run_with_resilience
from client_tpu.protocol.codec import serialize_tensor
from client_tpu.protocol.dtypes import np_to_wire_dtype
from client_tpu.protocol.grpc_stub import GRPCInferenceServiceStub
from client_tpu.utils import InferenceServerException, raise_error
from client_tpu.utils.shm_ring import RingProducer  # noqa: F401 — re-export

service_pb2 = pb  # re-export, as the reference re-exports its generated pb2

_log = logging.getLogger("client_tpu")

_channel_cache: dict[tuple, tuple[grpc.Channel, GRPCInferenceServiceStub]] = {}
_channel_cache_lock = lockdep.Lock("grpcclient.channel_cache")


class KeepAliveOptions:
    """gRPC keepalive knobs (reference grpc/__init__.py:104-144)."""

    def __init__(self, keepalive_time_ms=7200000, keepalive_timeout_ms=20000,
                 keepalive_permit_without_calls=False,
                 http2_max_pings_without_data=2):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data


def _grpc_error(exc: grpc.RpcError) -> InferenceServerException:
    try:
        err = InferenceServerException(
            msg=exc.details(), status=str(exc.code()))
    except Exception:  # noqa: BLE001
        return InferenceServerException(msg=str(exc))
    # Server pushback rides in trailing metadata (admission sheds / drain:
    # `retry-after` in fractional seconds, `retry-pushback-ms` integral) —
    # surfaced as retry_after_s so resilience.retry_after_of finds it and
    # RetryPolicy waits exactly as long as the server asked. Parsing is
    # shared with the HTTP Retry-After path (client_tpu.protocol.pushback)
    # so both transports agree on sub-second handling.
    try:
        retry_after_s = parse_pushback_metadata(exc.trailing_metadata())
        if retry_after_s is not None:
            err.retry_after_s = retry_after_s
    # tpulint: allow[swallowed-exception] pushback is best-effort
    except Exception:  # noqa: BLE001 — pushback is best-effort
        pass
    return err


class InferInput:
    """Input tensor; data goes in raw_input_contents (fast path) by default,
    or typed contents via set_data_from_numpy(..., use_contents=True)."""

    def __init__(self, name, shape, datatype):
        self._input = pb.ModelInferRequest.InferInputTensor(
            name=name, datatype=datatype, shape=[int(d) for d in shape])
        self._raw = None

    def name(self):
        return self._input.name

    def datatype(self):
        return self._input.datatype

    def shape(self):
        return list(self._input.shape)

    def set_shape(self, shape):
        del self._input.shape[:]
        self._input.shape.extend(int(d) for d in shape)
        return self

    def set_data_from_numpy(self, input_tensor, use_contents=False):
        if not isinstance(input_tensor, np.ndarray):
            raise_error("input_tensor must be a numpy array")
        dtype = np_to_wire_dtype(input_tensor.dtype)
        expected = self._input.datatype
        if expected != dtype and not (expected == "BYTES" and dtype is None):
            raise_error(
                f"got unexpected datatype {dtype}, expected {expected}")
        if list(input_tensor.shape) != list(self._input.shape):
            raise_error(
                f"got unexpected numpy array shape "
                f"[{list(input_tensor.shape)}], expected "
                f"[{list(self._input.shape)}]")
        self._input.parameters.pop("shared_memory_region", None)
        self._input.parameters.pop("shared_memory_byte_size", None)
        self._input.parameters.pop("shared_memory_offset", None)
        if use_contents:
            self._raw = None
            self._input.contents.Clear()
            grpc_codec.fill_contents(self._input.contents, input_tensor,
                                     expected)
        else:
            self._input.contents.Clear()
            self._raw = serialize_tensor(input_tensor, expected)
        return self

    def set_shared_memory(self, region_name, byte_size, offset=0):
        self._raw = None
        self._input.contents.Clear()
        grpc_codec.set_param(self._input.parameters, "shared_memory_region",
                             region_name)
        grpc_codec.set_param(self._input.parameters,
                             "shared_memory_byte_size", byte_size)
        if offset:
            grpc_codec.set_param(self._input.parameters,
                                 "shared_memory_offset", offset)
        return self

    def _get_tensor(self):
        return self._input, self._raw


class InferRequestedOutput:
    def __init__(self, name, class_count=0):
        self._output = pb.ModelInferRequest.InferRequestedOutputTensor(
            name=name)
        if class_count:
            grpc_codec.set_param(self._output.parameters, "classification",
                                 class_count)

    def name(self):
        return self._output.name

    def set_shared_memory(self, region_name, byte_size, offset=0):
        grpc_codec.set_param(self._output.parameters, "shared_memory_region",
                             region_name)
        grpc_codec.set_param(self._output.parameters,
                             "shared_memory_byte_size", byte_size)
        if offset:
            grpc_codec.set_param(self._output.parameters,
                                 "shared_memory_offset", offset)
        return self

    def unset_shared_memory(self):
        self._output.parameters.pop("shared_memory_region", None)
        self._output.parameters.pop("shared_memory_byte_size", None)
        self._output.parameters.pop("shared_memory_offset", None)
        return self

    def _get_tensor(self):
        return self._output


class InferResult:
    """Zero-copy-ish view over ModelInferResponse: as_numpy slices
    raw_output_contents by output index (reference InferResultGrpc,
    grpc_client.cc:144-365)."""

    def __init__(self, result: "pb.ModelInferResponse"):
        self._result = result

    def _response_params(self) -> dict:
        return grpc_codec.params_to_dict(self._result.parameters)

    def trace_id(self):
        """The W3C trace id this request ran under (32 hex chars), echoed
        as the ``traceparent`` response parameter when the request sent
        one; None otherwise."""
        tp = self._response_params().get("traceparent") or ""
        parts = tp.split("-")
        return parts[1] if len(parts) >= 3 else None

    def server_timing(self):
        """Server-side phase durations in microseconds
        ({queue, compute_input, compute_infer, compute_output}, plus
        ``compile`` when this request paid an XLA compile), from the
        ``server_*_us`` response parameters; empty if absent."""
        params = self._response_params()
        out = {}
        for phase in ("queue", "compute_input", "compute_infer",
                      "compute_output", "compile"):
            v = params.get(f"server_{phase}_us")
            if v is not None:
                out[phase] = float(v)
        return out

    def as_numpy(self, name):
        raw_idx = 0
        for tensor in self._result.outputs:
            # shm-placed outputs carry no payload at all — they must not
            # consume a raw_output_contents slot
            is_shm = "shared_memory_region" in tensor.parameters
            has_raw = not is_shm and not grpc_codec.tensor_has_contents(tensor)
            if tensor.name == name:
                if is_shm:
                    return None
                if has_raw:
                    if raw_idx < len(self._result.raw_output_contents):
                        return grpc_codec.tensor_to_ndarray(
                            tensor,
                            self._result.raw_output_contents[raw_idx])
                    return None
                return grpc_codec.tensor_to_ndarray(tensor, None)
            if has_raw:
                raw_idx += 1
        return None

    def get_output(self, name, as_json=False):
        for tensor in self._result.outputs:
            if tensor.name == name:
                if as_json:
                    from google.protobuf import json_format

                    return json_format.MessageToDict(
                        tensor, preserving_proto_field_name=True)
                return tensor
        return None

    def get_response(self, as_json=False):
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(
                self._result, preserving_proto_field_name=True)
        return self._result


class CallContext:
    """Cancellable handle returned by async_infer."""

    def __init__(self, future):
        self._future = future

    def cancel(self):
        return self._future.cancel()


class _InferStream:
    """Single bidi stream: request queue feeds the stream-stream call; a
    reader thread dispatches responses to the user callback (reference
    _InferStream + _RequestIterator, grpc/__init__.py:1802-1933)."""

    def __init__(self, stub, callback, stream_timeout=None, headers=None):
        self._q: queue.Queue = queue.Queue()
        self._callback = callback
        self._closed = False
        metadata = list(headers.items()) if headers else None
        self._call = stub.ModelStreamInfer(
            self._request_iterator(), timeout=stream_timeout,
            metadata=metadata)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _request_iterator(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            yield item

    def _read_loop(self):
        try:
            for response in self._call:
                # A user callback that raises must not kill the reader
                # thread — later responses on the stream would be silently
                # dropped (same guard the unary async path applies).
                try:
                    if response.error_message:
                        self._callback(
                            None, InferenceServerException(
                                response.error_message))
                    else:
                        self._callback(
                            InferResult(response.infer_response), None)
                # tpulint: allow[swallowed-exception] user callback fault
                except Exception:  # noqa: BLE001 — user callback fault
                    pass
        except grpc.RpcError as exc:
            if not self._closed:
                try:
                    self._callback(None, _grpc_error(exc))
                # tpulint: allow[swallowed-exception] reviewed fail-open
                except Exception:  # noqa: BLE001
                    pass

    def send(self, request):
        if self._closed:
            raise_error("stream is closed")
        self._q.put(request)

    def close(self, cancel_requests=False):
        if self._closed:
            return
        self._closed = True
        if cancel_requests:
            self._call.cancel()
        self._q.put(None)
        self._reader.join(timeout=10)
        if not self._reader.is_alive():
            return
        # The reader is wedged (server stopped sending without closing the
        # stream, or a response is stuck in flow control). Cancelling the
        # call unblocks the response iterator; a silent return here would
        # leak the thread AND the RPC.
        _log.warning("stream reader did not terminate within 10s; "
                     "cancelling the call")
        self._call.cancel()
        self._reader.join(timeout=2)
        if self._reader.is_alive():
            raise_error("stream reader did not terminate within 10s "
                        "(call cancelled; reader thread leaked)")


class InferenceServerClient:
    def __init__(self, url, verbose=False, ssl=False, root_certificates=None,
                 private_key=None, certificate_chain=None, creds=None,
                 keepalive_options=None, channel_args=None,
                 retry_policy=None, circuit_breaker=None):
        if ssl:
            raise InferenceServerException(
                "ssl is not supported by this transport yet")
        options = list(channel_args or [])
        options += [
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
        ]
        if keepalive_options is not None:
            options += [
                ("grpc.keepalive_time_ms",
                 keepalive_options.keepalive_time_ms),
                ("grpc.keepalive_timeout_ms",
                 keepalive_options.keepalive_timeout_ms),
                ("grpc.keepalive_permit_without_calls",
                 int(keepalive_options.keepalive_permit_without_calls)),
                ("grpc.http2.max_pings_without_data",
                 keepalive_options.http2_max_pings_without_data),
            ]
        # Router-aware URL handling: a comma-separated string (or list) of
        # URLs round-robins calls across N replicas, each on its own
        # cached channel, with the per-call breaker host tracking the
        # replica actually dialed. A single URL behaves exactly as before.
        urls = ([u.strip() for u in url.split(",") if u.strip()]
                if isinstance(url, str) else [str(u) for u in url])
        if not urls:
            raise InferenceServerException("no server url given")
        self._endpoints: list[tuple[str, grpc.Channel,
                                    GRPCInferenceServiceStub]] = []
        for u in urls:
            key = (u, tuple(sorted(options)))
            # Process-global channel/stub reuse keyed by URL+options, the
            # same allocation hygiene as the reference's channel cache.
            with _channel_cache_lock:
                cached = _channel_cache.get(key)
                if cached is None:
                    channel = grpc.insecure_channel(u, options=options)
                    stub = GRPCInferenceServiceStub(channel)
                    _channel_cache[key] = (channel, stub)
                else:
                    channel, stub = cached
            self._endpoints.append((u, channel, stub))
        url = self._endpoints[0][0]
        self._channel = self._endpoints[0][1]
        self._rr = itertools.count()
        self._local = threading.local()
        self._verbose = verbose
        self._stream: _InferStream | None = None
        self._stats = InferStat()
        # Opt-in resilience: when a RetryPolicy is set, a call's
        # `client_timeout` becomes the end-to-end deadline budget across
        # all attempts (each attempt's RPC deadline shrinks to what
        # remains). Streaming retries connection establishment only.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker
        self._async_executor = None
        self._async_executor_lock = lockdep.Lock("grpcclient.async_executor")

    @property
    def _client_stub(self):
        """The stub for the next call. Multi-URL clients rotate here — the
        stub is bound at the call site (``self._client_stub.ModelInfer``),
        so one rotation covers all of that call's retry attempts — and the
        thread records which endpoint it dialed for breaker attribution."""
        if len(self._endpoints) == 1:
            return self._endpoints[0][2]
        url, _, stub = self._endpoints[next(self._rr)
                                      % len(self._endpoints)]
        self._local.host = url
        return stub

    @property
    def _breaker_host(self):
        if len(self._endpoints) == 1:
            return self._endpoints[0][0]
        return getattr(self._local, "host", self._endpoints[0][0])

    def get_infer_stat(self):
        """Cumulative client-side inference statistics (round-trip time
        plus the server-reported phase breakdown) — the InferStat
        equivalent of the reference client."""
        return self._stats.get()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self.stop_stream()
        if self._async_executor is not None:
            self._async_executor.shutdown(wait=False)
        # channel stays cached for other clients of the same URL

    # -- health / metadata ---------------------------------------------------

    @staticmethod
    def _md(headers):
        return list(headers.items()) if headers else None

    def _unary(self, rpc, request, metadata, client_timeout, trace_id=None,
               **rpc_kwargs):
        """One unary RPC under the configured retry/breaker/deadline.
        With a retry policy, ``client_timeout`` is the total budget across
        attempts and each attempt's RPC deadline is the remaining slice."""

        def attempt(remaining_s):
            try:
                return rpc(request, metadata=metadata,
                           timeout=(remaining_s if remaining_s is not None
                                    else client_timeout),
                           **rpc_kwargs)
            except grpc.RpcError as exc:
                raise _grpc_error(exc) from None

        if self._retry_policy is None and self._breaker is None:
            return attempt(None)
        return run_with_resilience(
            attempt,
            policy=self._retry_policy,
            breaker=self._breaker,
            deadline_s=(client_timeout
                        if self._retry_policy is not None else None),
            host=self._breaker_host,
            on_retry=lambda n, exc, delay: self._stats.record_retry(),
            on_breaker_reject=self._stats.record_breaker_rejection,
            trace_id=trace_id)

    def _call(self, method, request, headers=None, as_json=False,
              client_timeout=None):
        response = self._unary(method, request, self._md(headers),
                               client_timeout)
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(
                response, preserving_proto_field_name=True)
        return response

    def is_server_live(self, headers=None, client_timeout=None):
        return self._call(self._client_stub.ServerLive,
                          pb.ServerLiveRequest(), headers,
                          client_timeout=client_timeout).live

    def is_server_ready(self, headers=None, client_timeout=None):
        return self._call(self._client_stub.ServerReady,
                          pb.ServerReadyRequest(), headers,
                          client_timeout=client_timeout).ready

    def is_model_ready(self, model_name, model_version="", headers=None,
                       client_timeout=None):
        return self._call(
            self._client_stub.ModelReady,
            pb.ModelReadyRequest(name=model_name, version=model_version),
            headers, client_timeout=client_timeout).ready

    def get_server_metadata(self, headers=None, as_json=False,
                            client_timeout=None):
        return self._call(self._client_stub.ServerMetadata,
                          pb.ServerMetadataRequest(), headers, as_json,
                          client_timeout)

    def get_model_metadata(self, model_name, model_version="", headers=None,
                           as_json=False, client_timeout=None):
        return self._call(
            self._client_stub.ModelMetadata,
            pb.ModelMetadataRequest(name=model_name, version=model_version),
            headers, as_json, client_timeout)

    def get_model_config(self, model_name, model_version="", headers=None,
                         as_json=False, client_timeout=None):
        return self._call(
            self._client_stub.ModelConfig,
            pb.ModelConfigRequest(name=model_name, version=model_version),
            headers, as_json, client_timeout)

    def get_model_repository_index(self, headers=None, as_json=False,
                                   client_timeout=None):
        return self._call(self._client_stub.RepositoryIndex,
                          pb.RepositoryIndexRequest(), headers, as_json,
                          client_timeout)

    def load_model(self, model_name, headers=None, config=None, files=None,
                   client_timeout=None):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        for path, content in (files or {}).items():
            request.parameters[path].string_param = base64.b64encode(
                content).decode("ascii")
        self._call(self._client_stub.RepositoryModelLoad, request,
                   headers, client_timeout=client_timeout)

    def unload_model(self, model_name, headers=None,
                     unload_dependents=False, client_timeout=None):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        if unload_dependents:
            request.parameters["unload_dependents"].bool_param = True
        self._call(self._client_stub.RepositoryModelUnload, request,
                   headers, client_timeout=client_timeout)

    def get_inference_statistics(self, model_name="", model_version="",
                                 headers=None, as_json=False,
                                 client_timeout=None):
        return self._call(
            self._client_stub.ModelStatistics,
            pb.ModelStatisticsRequest(name=model_name,
                                      version=model_version),
            headers, as_json, client_timeout)

    def _events_via(self, stub, model_name="", severity="", category="",
                    since_seq=None, limit=None, headers=None,
                    client_timeout=None, since_wall=None,
                    until_wall=None):
        from client_tpu.protocol import ops_pb2 as ops

        request = ops.EventsRequest(
            model=model_name, severity=severity, category=category,
            since_seq=int(since_seq) if since_seq else 0,
            since_wall=float(since_wall) if since_wall else 0.0,
            until_wall=float(until_wall) if until_wall else 0.0,
            limit=int(limit) if limit else 0)
        response = self._unary(stub.Events, request,
                               self._md(headers), client_timeout)
        events = []
        for e in response.events:
            ev = {"seq": e.seq, "ts_wall": e.ts_wall,
                  "ts_mono_ns": e.ts_mono_ns, "category": e.category,
                  "name": e.name, "severity": e.severity}
            if e.model:
                ev["model"] = e.model
            if e.version:
                ev["version"] = e.version
            if e.trace_id:
                ev["trace_id"] = e.trace_id
            if e.detail_json:
                ev["detail"] = json.loads(e.detail_json)
            events.append(ev)
        return {"events": events, "next_seq": response.next_seq,
                "dropped": response.dropped}

    def get_events(self, model_name="", severity="", category="",
                   since_seq=None, since_wall=None, until_wall=None,
                   limit=None, headers=None, client_timeout=None):
        """Structured event journal (gRPC mirror of ``GET /v2/events``).
        Returns the same dict shape as the HTTP endpoint: ``events`` (each
        with its ``detail`` decoded from JSON), ``next_seq``, ``dropped``.
        ``since_wall``/``until_wall`` bound the events by epoch-seconds
        wall stamp (exclusive lower, inclusive upper)."""
        return self._events_via(self._client_stub, model_name, severity,
                                category, since_seq, limit, headers,
                                client_timeout, since_wall=since_wall,
                                until_wall=until_wall)

    def get_slo_status(self, model_name="", headers=None,
                       client_timeout=None):
        """SLO burn-rate snapshot (gRPC mirror of ``GET /v2/slo``)."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.SloStatus,
            ops.SloStatusRequest(model=model_name),
            self._md(headers), client_timeout)
        return json.loads(response.slo_json)

    def get_profile(self, model_name="", headers=None, client_timeout=None):
        """Efficiency profiler cost table (gRPC mirror of
        ``GET /v2/profile``): per-model/per-bucket fill ratios,
        padding-waste device-seconds, compile counts, duty cycle."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.Profile,
            ops.ProfileRequest(model=model_name),
            self._md(headers), client_timeout)
        return json.loads(response.profile_json)

    def get_timeseries(self, signal="", model_name="", since_seq=None,
                       since_wall=None, until_wall=None, limit=None,
                       headers=None, client_timeout=None):
        """Flight-recorder signal ring (gRPC mirror of
        ``GET /v2/timeseries``): the 1 Hz duty-cycle / queue-depth /
        HBM sample history; ``since_seq`` is the exclusive cursor from
        the previous response's ``next_seq``; ``since_wall``/
        ``until_wall`` an epoch-seconds window (exclusive lower,
        inclusive upper)."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.Timeseries,
            ops.TimeseriesRequest(signal=signal, model=model_name,
                                  since_seq=since_seq or 0,
                                  since_wall=float(since_wall or 0.0),
                                  until_wall=float(until_wall or 0.0),
                                  limit=limit or 0),
            self._md(headers), client_timeout)
        return json.loads(response.timeseries_json)

    def get_memory(self, headers=None, client_timeout=None):
        """HBM census report (gRPC mirror of ``GET /v2/memory``)."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.MemoryCensus, ops.MemoryRequest(),
            self._md(headers), client_timeout)
        return json.loads(response.memory_json)

    def get_costs(self, model_name="", headers=None, client_timeout=None):
        """Per-tenant cost ledger (gRPC mirror of ``GET /v2/costs``):
        device/HBM/queue seconds and interference attribution per
        tenant. Tag requests with a ``tenant`` request parameter to
        attribute their spend."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.Costs,
            ops.CostsRequest(model=model_name),
            self._md(headers), client_timeout)
        return json.loads(response.costs_json)

    def get_qos_status(self, model_name="", headers=None,
                       client_timeout=None):
        """Tenant QoS status (gRPC mirror of ``GET /v2/qos``): class
        weights, quotas, governor throttle ratios, and per-model WFQ
        lane depths."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.Qos,
            ops.QosRequest(model=model_name),
            self._md(headers), client_timeout)
        return json.loads(response.qos_json)

    def get_bundles(self, bundle_id="", headers=None,
                    client_timeout=None):
        """Incident-blackbox bundles (gRPC mirror of
        ``GET /v2/debug/bundles[/{id}]``): the retained-bundle index,
        or — with ``bundle_id`` — one full bundle document."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.BlackboxBundles,
            ops.BlackboxBundlesRequest(bundle_id=bundle_id),
            self._md(headers), client_timeout)
        return json.loads(response.bundles_json)

    def capture_bundle(self, trigger="manual", incident="", note="",
                       headers=None, client_timeout=None):
        """Trigger an incident capture now (gRPC mirror of
        ``POST /v2/debug/capture``) and return the written bundle's
        meta; a non-``manual`` trigger respects the server's
        debounce/cooldown and may return ``{"deduped": true}``."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.BlackboxCapture,
            ops.BlackboxCaptureRequest(trigger=trigger or "manual",
                                       incident=incident, note=note),
            self._md(headers), client_timeout)
        return json.loads(response.bundle_json)

    # -- fleet observability (client-side federation) -------------------------
    # gRPC has no fronting router, so the multi-URL client federates the
    # per-endpoint surfaces itself with the same merge semantics the
    # router's /v2/fleet/* endpoints use (observability.fleet): the
    # aggregate never fails on a dead endpoint — its error rides inline.

    def _fleet_fan_out(self, fetch):
        results: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for url, _channel, stub in self._endpoints:
            try:
                results[url] = fetch(stub)
            except Exception as exc:  # noqa: BLE001 — inline reporting
                errors[url] = f"{type(exc).__name__}: {exc}"
        return results, errors

    def get_fleet_events(self, model_name="", severity="", category="",
                         limit=None, headers=None, client_timeout=None):
        """Every endpoint's event journal merged by wall stamp, each
        event tagged with its endpoint url; ``cursors`` carries each
        endpoint's ``next_seq`` (seq spaces are per-process)."""
        from client_tpu.observability.fleet import merge_events

        exports, errors = self._fleet_fan_out(
            lambda stub: self._events_via(
                stub, model_name, severity, category, None, limit,
                headers, client_timeout))
        return merge_events(exports, errors, limit=limit)

    def get_fleet_profile(self, headers=None, client_timeout=None):
        """Per-endpoint profiler snapshots plus fleet drift signals."""
        from client_tpu.observability.fleet import merge_profiles
        from client_tpu.protocol import ops_pb2 as ops

        profiles, errors = self._fleet_fan_out(
            lambda stub: json.loads(self._unary(
                stub.Profile, ops.ProfileRequest(model=""),
                self._md(headers), client_timeout).profile_json))
        return merge_profiles(profiles, errors)

    def get_fleet_slo(self, headers=None, client_timeout=None):
        """Per-endpoint SLO reports plus the fleet's worst fast burn."""
        from client_tpu.observability.fleet import merge_slo
        from client_tpu.protocol import ops_pb2 as ops

        exports, errors = self._fleet_fan_out(
            lambda stub: json.loads(self._unary(
                stub.SloStatus, ops.SloStatusRequest(model=""),
                self._md(headers), client_timeout).slo_json))
        return merge_slo(exports, errors)

    def get_fleet_costs(self, headers=None, client_timeout=None):
        """Per-endpoint cost-ledger snapshots plus fleet-wide per-tenant
        totals (the client-side analogue of the router's
        ``GET /v2/fleet/costs``)."""
        from client_tpu.observability.fleet import merge_costs
        from client_tpu.protocol import ops_pb2 as ops

        exports, errors = self._fleet_fan_out(
            lambda stub: json.loads(self._unary(
                stub.Costs, ops.CostsRequest(model=""),
                self._md(headers), client_timeout).costs_json))
        return merge_costs(exports, errors)

    def get_fleet_timeseries(self, signal="", model_name="", limit=None,
                             headers=None, client_timeout=None):
        """Every endpoint's flight-recorder ring merged by wall stamp,
        each sample tagged with its endpoint url; ``cursors`` carries
        each endpoint's ``next_seq`` (seq spaces are per-process)."""
        from client_tpu.observability.fleet import merge_timeseries
        from client_tpu.protocol import ops_pb2 as ops

        exports, errors = self._fleet_fan_out(
            lambda stub: json.loads(self._unary(
                stub.Timeseries,
                ops.TimeseriesRequest(signal=signal, model=model_name,
                                      since_seq=0, limit=limit or 0),
                self._md(headers), client_timeout).timeseries_json))
        return merge_timeseries(exports, errors, limit=limit)

    # -- shared memory -------------------------------------------------------

    def get_system_shared_memory_status(self, region_name="", headers=None,
                                        as_json=False, client_timeout=None):
        return self._call(
            self._client_stub.SystemSharedMemoryStatus,
            pb.SystemSharedMemoryStatusRequest(name=region_name),
            headers, as_json, client_timeout)

    def register_system_shared_memory(self, name, key, byte_size, offset=0,
                                      headers=None, client_timeout=None):
        self._call(
            self._client_stub.SystemSharedMemoryRegister,
            pb.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size),
            headers, client_timeout=client_timeout)

    def unregister_system_shared_memory(self, name="", headers=None,
                                        client_timeout=None):
        self._call(
            self._client_stub.SystemSharedMemoryUnregister,
            pb.SystemSharedMemoryUnregisterRequest(name=name), headers,
            client_timeout=client_timeout)

    def get_tpu_shared_memory_status(self, region_name="", headers=None,
                                     as_json=False, client_timeout=None):
        return self._call(
            self._client_stub.TpuSharedMemoryStatus,
            pb.TpuSharedMemoryStatusRequest(name=region_name),
            headers, as_json, client_timeout)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size, headers=None,
                                   client_timeout=None):
        """Register a TPU-HBM region by serialized buffer handle (the raw
        bytes travel in the proto, like the reference's cudaIpcMemHandle_t
        in raw_handle, grpc_client.cc:811)."""
        self._call(
            self._client_stub.TpuSharedMemoryRegister,
            pb.TpuSharedMemoryRegisterRequest(
                name=name, raw_handle=raw_handle, device_id=device_id,
                byte_size=byte_size),
            headers, client_timeout=client_timeout)

    def unregister_tpu_shared_memory(self, name="", headers=None,
                                     client_timeout=None):
        self._call(
            self._client_stub.TpuSharedMemoryUnregister,
            pb.TpuSharedMemoryUnregisterRequest(name=name), headers,
            client_timeout=client_timeout)

    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- shm slot ring (zero-copy data plane) -------------------------------

    def register_shm_ring(self, name, key, spec=None, headers=None,
                          client_timeout=None):
        """Attach a slot-ring segment (created with
        ``client_tpu.utils.shm_ring``) by POSIX shm key. A ``spec``
        (doorbell span spec without start/count) switches the ring to
        reaped mode: the engine-side reaper sweeps FILLED slots
        continuously, no doorbells needed."""
        from client_tpu.protocol import ops_pb2 as ops

        self._call(self._client_stub.RingRegister,
                   ops.RingRegisterRequest(
                       name=name, key=key,
                       spec_json=json.dumps(spec) if spec else ""),
                   headers, client_timeout=client_timeout)

    def unregister_shm_ring(self, name="", headers=None,
                            client_timeout=None):
        from client_tpu.protocol import ops_pb2 as ops

        self._call(self._client_stub.RingUnregister,
                   ops.RingUnregisterRequest(name=name), headers,
                   client_timeout=client_timeout)

    def get_shm_ring_status(self, name="", headers=None,
                            client_timeout=None):
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.RingStatus,
            ops.RingStatusRequest(name=name),
            self._md(headers), client_timeout)
        return json.loads(response.status_json)

    def ring_doorbell(self, name, spec, headers=None, client_timeout=None):
        """Submit a span of FILLED ring slots in one RPC; the span spec
        rides as JSON (same body as the HTTP doorbell) and completions
        are polled from shm."""
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.RingDoorbell,
            ops.RingDoorbellRequest(name=name,
                                    doorbell_json=json.dumps(spec)),
            self._md(headers), client_timeout)
        return json.loads(response.result_json)

    # -- staged datasets (many-producer fan-in) -----------------------------

    def register_staged_dataset(self, name, key, headers=None,
                                client_timeout=None):
        """Attach a staged-dataset segment (built with
        ``client_tpu.utils.shm_ring.staged``) by POSIX shm key."""
        from client_tpu.protocol import ops_pb2 as ops

        self._call(self._client_stub.DatasetRegister,
                   ops.DatasetRegisterRequest(name=name, key=key),
                   headers, client_timeout=client_timeout)

    def unregister_staged_dataset(self, name="", headers=None,
                                  client_timeout=None):
        from client_tpu.protocol import ops_pb2 as ops

        self._call(self._client_stub.DatasetUnregister,
                   ops.DatasetUnregisterRequest(name=name), headers,
                   client_timeout=client_timeout)

    def get_staged_dataset_status(self, name="", headers=None,
                                  client_timeout=None):
        from client_tpu.protocol import ops_pb2 as ops

        response = self._unary(
            self._client_stub.DatasetStatus,
            ops.DatasetStatusRequest(name=name),
            self._md(headers), client_timeout)
        return json.loads(response.status_json)

    # -- inference -----------------------------------------------------------

    def _make_request(self, model_name, inputs, model_version, outputs,
                      request_id, sequence_id, sequence_start, sequence_end,
                      priority, timeout, parameters):
        request = pb.ModelInferRequest(
            model_name=model_name, model_version=model_version,
            id=request_id)
        if sequence_id:
            grpc_codec.set_param(request.parameters, "sequence_id",
                                 sequence_id)
            grpc_codec.set_param(request.parameters, "sequence_start",
                                 sequence_start)
            grpc_codec.set_param(request.parameters, "sequence_end",
                                 sequence_end)
        if priority:
            grpc_codec.set_param(request.parameters, "priority", priority)
        if timeout is not None:
            grpc_codec.set_param(request.parameters, "timeout", timeout)
        for k, v in (parameters or {}).items():
            grpc_codec.set_param(request.parameters, k, v)
        for i in inputs:
            tensor, raw = i._get_tensor()
            request.inputs.append(tensor)
            if raw is not None:
                request.raw_input_contents.append(raw)
        for o in outputs or []:
            request.outputs.append(o._get_tensor())
        return request

    def infer(self, model_name, inputs, model_version="", outputs=None,
              request_id="", sequence_id=0, sequence_start=False,
              sequence_end=False, priority=0, timeout=None,
              client_timeout=None, headers=None, compression_algorithm=None,
              parameters=None):
        # Distributed tracing: propagate the caller's traceparent (parameter
        # wins, then RPC metadata), or start a new trace per request so the
        # server echoes the id and phase timings back as response
        # parameters.
        params = dict(parameters or {})
        params.setdefault("traceparent",
                          (headers or {}).get("traceparent")
                          or TraceContext.new().to_traceparent())
        request = self._make_request(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout,
            params)
        tp_parts = params["traceparent"].split("-")
        trace_id = tp_parts[1] if len(tp_parts) >= 3 else None
        t0 = time.monotonic_ns()
        response = self._unary(
            self._client_stub.ModelInfer, request, self._md(headers),
            client_timeout, trace_id=trace_id,
            compression=_compression(compression_algorithm))
        result = InferResult(response)
        self._stats.record((time.monotonic_ns() - t0) / 1e3,
                           result.server_timing(),
                           trace_id=result.trace_id() or trace_id)
        return result

    def async_infer(self, model_name, inputs, callback, model_version="",
                    outputs=None, request_id="", sequence_id=0,
                    sequence_start=False, sequence_end=False, priority=0,
                    timeout=None, client_timeout=None, headers=None,
                    compression_algorithm=None, parameters=None):
        request = self._make_request(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout,
            parameters)
        if self._retry_policy is not None or self._breaker is not None:
            # gRPC's call-future cannot replay itself, so the resilient
            # async path runs the retrying unary call on a worker thread.
            with self._async_executor_lock:
                if self._async_executor is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._async_executor = ThreadPoolExecutor(max_workers=4)
            task = self._async_executor.submit(
                self._unary, self._client_stub.ModelInfer, request,
                self._md(headers), client_timeout,
                compression=_compression(compression_algorithm))

            def _task_done(f):
                try:
                    result = InferResult(f.result())
                except InferenceServerException as exc:
                    callback(None, exc)
                    return
                except Exception as exc:  # noqa: BLE001
                    callback(None, InferenceServerException(str(exc)))
                    return
                callback(result, None)

            task.add_done_callback(_task_done)
            return CallContext(task)
        future = self._client_stub.ModelInfer.future(
            request, metadata=self._md(headers), timeout=client_timeout,
            compression=_compression(compression_algorithm))

        def _done(f):
            # only the RPC result fetch is guarded: an exception raised by
            # the user's own callback must not re-invoke it as an error
            try:
                result = InferResult(f.result())
            except grpc.RpcError as exc:
                callback(None, _grpc_error(exc))
                return
            except Exception as exc:  # noqa: BLE001
                callback(None, InferenceServerException(str(exc)))
                return
            callback(result, None)

        future.add_done_callback(_done)
        return CallContext(future)

    # -- streaming -----------------------------------------------------------

    def start_stream(self, callback, stream_timeout=None, headers=None):
        if self._stream is not None:
            raise_error("stream already started")
        if self._retry_policy is not None:
            # Streaming retries CONNECTION ESTABLISHMENT only: once a
            # stream is up, replaying in-flight stream requests would
            # reorder sequences, so mid-stream errors still surface to the
            # user callback. Each readiness probe waits up to 1s.
            def attempt(remaining_s):
                wait = 1.0 if remaining_s is None else min(1.0, remaining_s)
                try:
                    grpc.channel_ready_future(self._channel).result(
                        timeout=wait)
                except grpc.FutureTimeoutError:
                    raise ConnectionError(
                        "gRPC channel not ready (connection "
                        "establishment timed out)") from None

            run_with_resilience(
                attempt,
                policy=self._retry_policy,
                breaker=self._breaker,
                deadline_s=stream_timeout,
                host=self._breaker_host,
                on_retry=lambda n, exc, delay: self._stats.record_retry(),
                on_breaker_reject=self._stats.record_breaker_rejection)
        self._stream = _InferStream(self._client_stub, callback,
                                    stream_timeout, headers)

    def stop_stream(self, cancel_requests=False):
        if self._stream is not None:
            self._stream.close(cancel_requests)
            self._stream = None

    def async_stream_infer(self, model_name, inputs, model_version="",
                           outputs=None, request_id="", sequence_id=0,
                           sequence_start=False, sequence_end=False,
                           priority=0, timeout=None, parameters=None,
                           enable_empty_final_response=False):
        if self._stream is None:
            raise_error("stream not started (call start_stream first)")
        request = self._make_request(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout,
            parameters)
        self._stream.send(request)


def _compression(name):
    if name is None:
        return None
    if name == "gzip":
        return grpc.Compression.Gzip
    if name == "deflate":
        return grpc.Compression.Deflate
    return grpc.Compression.NoCompression
