"""Python HTTP/REST client library.

API mirrors the reference's ``tritonclient.http``
(/root/reference/src/python/library/tritonclient/http/__init__.py:131-1421):
``InferenceServerClient`` with the full control plane, ``InferInput`` /
``InferRequestedOutput`` / ``InferResult``, sync ``infer`` and pool-based
``async_infer``. Transport is stdlib ``http.client`` over a connection pool +
a thread pool (the reference uses gevent greenlets; threads are the
dependency-free equivalent and the GIL is released during socket I/O).
"""

from __future__ import annotations

import gzip
import base64
import json
import queue
import random
from client_tpu.utils import lockdep
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from http.client import BadStatusLine, HTTPConnection
from urllib.parse import quote, urlencode

import numpy as np

from client_tpu.observability.client_stats import InferStat
from client_tpu.resilience import (
    CircuitBreaker,
    CircuitBreakerOpenError,
    run_with_resilience,
)
from client_tpu.router.core import rendezvous_pick
from client_tpu.observability.tracing import (
    TraceContext,
    parse_server_timing,
)
from client_tpu.protocol import rest
from client_tpu.protocol.codec import serialize_tensor
from client_tpu.protocol.dtypes import np_to_wire_dtype, wire_to_np_dtype
from client_tpu.protocol.loadreport import LOAD_HEADER, decode_header
from client_tpu.protocol.pushback import (
    RETRY_AFTER_HEADER,
    parse_retry_after,
)
from client_tpu.utils import InferenceServerException, raise_error
from client_tpu.utils.shm_ring import RingProducer  # noqa: F401 — re-export


class InferInput:
    """An input tensor for an inference request (mirrors reference
    http/__init__.py:1540-1621 semantics incl. binary vs JSON data and shm)."""

    def __init__(self, name, shape, datatype):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype
        self._parameters = {}
        self._data = None          # JSON-inline list
        self._raw_data = None      # binary payload bytes

    def name(self):
        return self._name

    def datatype(self):
        return self._datatype

    def shape(self):
        return self._shape

    def set_shape(self, shape):
        self._shape = list(shape)
        return self

    def set_data_from_numpy(self, input_tensor, binary_data=True):
        if not isinstance(input_tensor, np.ndarray):
            raise_error("input_tensor must be a numpy array")
        dtype = np_to_wire_dtype(input_tensor.dtype)
        if self._datatype != dtype and not (
                self._datatype == "BYTES" and dtype in ("BYTES", None)):
            raise_error(
                f"got unexpected datatype {dtype} from numpy array, expected "
                f"{self._datatype}")
        valid_shape = list(input_tensor.shape) == self._shape
        if not valid_shape:
            raise_error(
                f"got unexpected numpy array shape [{list(input_tensor.shape)}]"
                f", expected [{self._shape}]")
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        if binary_data:
            self._data = None
            self._raw_data = serialize_tensor(input_tensor, self._datatype)
            self._parameters["binary_data_size"] = len(self._raw_data)
        else:
            self._raw_data = None
            self._parameters.pop("binary_data_size", None)
            if self._datatype == "BYTES":
                flat = np.ravel(input_tensor, order="C")
                self._data = [
                    x.decode("utf-8") if isinstance(x, (bytes, np.bytes_))
                    else str(x)
                    for x in flat
                ]
            else:
                self._data = np.ravel(input_tensor, order="C").tolist()
        return self

    def set_shared_memory(self, region_name, byte_size, offset=0):
        self._data = None
        self._raw_data = None
        self._parameters.pop("binary_data_size", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset:
            self._parameters["shared_memory_offset"] = offset
        return self

    def _get_tensor(self):
        entry = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            entry["parameters"] = dict(self._parameters)
        if self._data is not None:
            entry["data"] = self._data
        return entry


class InferRequestedOutput:
    """A requested output (classification count, binary flag, shm placement;
    reference http/__init__.py InferRequestedOutput)."""

    def __init__(self, name, binary_data=True, class_count=0):
        self._name = name
        self._parameters = {}
        if binary_data:
            self._parameters["binary_data"] = True
        if class_count:
            self._parameters["classification"] = class_count

    def name(self):
        return self._name

    def set_shared_memory(self, region_name, byte_size, offset=0):
        self._parameters.pop("binary_data", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset:
            self._parameters["shared_memory_offset"] = offset
        return self

    def unset_shared_memory(self):
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        return self

    def _get_tensor(self):
        entry = {"name": self._name}
        if self._parameters:
            entry["parameters"] = dict(self._parameters)
        return entry


class InferResult:
    """Parsed inference response: JSON head + binary tails mapped by offset
    (reference http/__init__.py:1768-1962)."""

    def __init__(self, response_body: bytes, header_length: int | None,
                 verbose: bool = False):
        self._head, tail = rest.split_body(response_body, header_length)
        if "error" in self._head:
            raise InferenceServerException(self._head["error"])
        self._tensors = {
            t.name: t
            for t in rest.parse_tensors(self._head.get("outputs", []), tail)
        }
        # Populated by the client transport from the response headers
        # (traceparent round-trip + Server-Timing phase breakdown).
        self._trace_id = None
        self._server_timing: dict = {}

    @classmethod
    def from_response_body(cls, response_body, verbose=False,
                           header_length=None, content_encoding=None):
        if content_encoding == "gzip":
            response_body = gzip.decompress(response_body)
        elif content_encoding == "deflate":
            response_body = zlib.decompress(response_body)
        return cls(response_body, header_length, verbose)

    def as_numpy(self, name):
        t = self._tensors.get(name)
        if t is None:
            return None
        if "shared_memory_region" in t.parameters:
            return None  # caller reads from its own region
        return t.to_numpy()

    def get_output(self, name):
        t = self._tensors.get(name)
        if t is None:
            return None
        entry = {"name": t.name, "datatype": t.datatype, "shape": t.shape}
        if t.parameters:
            entry["parameters"] = t.parameters
        if t.data is not None:
            entry["data"] = t.data
        return entry

    def get_response(self):
        return self._head

    def trace_id(self):
        """The W3C trace id this request ran under (32 hex chars), echoed
        by the server; correlate against ``GET /v2/trace/requests``."""
        return self._trace_id

    def server_timing(self):
        """Server-side phase durations in microseconds
        ({queue, compute_input, compute_infer, compute_output}), parsed
        from the Server-Timing response header; empty if absent."""
        return dict(self._server_timing)


class InferAsyncRequest:
    def __init__(self, future, verbose=False):
        self._future = future

    def get_result(self, block=True, timeout=None):
        if not block:
            if not self._future.done():
                raise InferenceServerException("result not ready")
        try:
            return self._future.result(timeout=timeout)
        except InferenceServerException:
            raise
        except Exception as exc:  # noqa: BLE001
            raise InferenceServerException(str(exc)) from exc


# Connection died before any response bytes: safe to replay regardless of
# method, since the server cannot have begun processing a request it never
# acknowledged. BadStatusLine covers http.client.RemoteDisconnected.
_STALE_SOCKET_ERRORS = (BadStatusLine, ConnectionResetError,
                        BrokenPipeError, ConnectionAbortedError)


def _parse_retry_after(resp) -> float | None:
    """Server pushback from a Retry-After header (seconds form only —
    this ecosystem's servers send fractional seconds; HTTP-date is not
    used here). None when absent or unparsable. Parsing is shared with
    the gRPC metadata path (client_tpu.protocol.pushback) so both
    transports agree on sub-second handling."""
    if resp is None:
        return None
    return parse_retry_after(resp.getheader(RETRY_AFTER_HEADER))


class _RetryableStatus(Exception):
    """Internal: a response with a retryable HTTP status (502/503, or an
    admission-shed 429 carrying Retry-After pushback), re-raised through
    the resilience loop; carries the response so retry exhaustion degrades
    to returning it (original _request contract). ``retry_after_s`` feeds
    RetryPolicy.backoff_s so the client waits exactly as long as the
    server asked."""

    def __init__(self, resp, data):
        super().__init__(f"HTTP {resp.status}")
        self.resp = resp
        self.data = data
        self.status = resp.status
        self.retry_after_s = _parse_retry_after(resp)


class _Target:
    """One server endpoint of a multi-URL client: its connection pool,
    its last piggybacked load report, and the client-local outstanding
    count. Single-URL clients never build these (zero overhead on the
    common path)."""

    def __init__(self, url, concurrency, timeout):
        if "://" in url:
            url = url.split("://", 1)[1]
        host, _, port = url.rstrip("/").partition(":")
        self.host = host
        self.port = int(port or 80)
        self.id = f"{self.host}:{self.port}"
        self.pool = _ConnectionPool(self.host, self.port, concurrency,
                                    timeout)
        self.load = None
        self.outstanding = 0
        self._lock = lockdep.Lock("httpclient.endpoint")

    def observe(self, resp) -> None:
        """Learn the endpoint's load from a response's X-Tpu-Load
        piggyback header — the zero-extra-RPC load view."""
        report = decode_header(resp.getheader(LOAD_HEADER))
        if report is not None:
            with self._lock:
                self.load = report

    def score(self) -> float:
        with self._lock:
            return self.outstanding + (self.load.score() if self.load
                                       else 0.0)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self.load is not None and self.load.draining


class _ConnectionPool:
    """LIFO keep-alive pool with symmetric accounting.

    ``live`` counts connections in existence (pooled + checked out): +1
    exactly once when a connection is constructed, -1 exactly once when it
    is destroyed (``_discard``, guarded against double-close so an errant
    double release can never drift the counter negative — the pre-PR-2
    accounting decremented on every broken release and never on pool
    drain, so the counter wandered under churn).
    """

    def __init__(self, host, port, size, timeout):
        self._host, self._port, self._timeout = host, port, timeout
        self._pool: queue.LifoQueue = queue.LifoQueue()
        self._lock = lockdep.Lock("httpclient.pool")
        self._created = 0
        self._size = size

    @property
    def live(self) -> int:
        with self._lock:
            return self._created

    def acquire(self) -> tuple[HTTPConnection, bool]:
        """Returns (conn, reused): reused connections came out of the pool
        and may be stale keep-alive sockets — the transport replays once on
        a fresh connection if one dies before response bytes arrive."""
        try:
            return self._pool.get_nowait(), True
        except queue.Empty:
            pass
        conn = HTTPConnection(self._host, self._port, timeout=self._timeout)
        # Count only after successful construction, so a failing
        # constructor cannot leak a phantom entry.
        with self._lock:
            self._created += 1
        return conn, False

    def _discard(self, conn: HTTPConnection) -> None:
        if getattr(conn, "_pool_discarded", False):
            return
        conn._pool_discarded = True
        try:
            conn.close()
        finally:
            with self._lock:
                self._created -= 1

    def release(self, conn: HTTPConnection, broken=False):
        if broken or self._pool.qsize() >= self._size:
            # enforce the pool bound: excess/broken connections are closed
            self._discard(conn)
            return
        self._pool.put(conn)

    def close(self):
        while True:
            try:
                self._discard(self._pool.get_nowait())
            except queue.Empty:
                return


class InferenceServerClient:
    """HTTP client for the v2 protocol (control plane + inference)."""

    def __init__(self, url, verbose=False, concurrency=1,
                 connection_timeout=60.0, network_timeout=60.0,
                 max_greenlets=None, ssl=False, ssl_options=None,
                 ssl_context_factory=None, insecure=False,
                 retry_policy=None, circuit_breaker=None):
        if ssl:
            raise InferenceServerException(
                "ssl is not supported by this transport yet")
        # Router-aware URL handling: a list (or comma-separated string) of
        # URLs makes the client balance across N replicas itself — P2C on
        # load score learned from X-Tpu-Load piggyback headers, per-target
        # circuit breaking, and transparent failover. A single URL (which
        # may be a standalone `client_tpu.router` frontend) keeps the
        # original single-pool transport untouched.
        urls = ([u.strip() for u in url.split(",") if u.strip()]
                if isinstance(url, str) else [str(u) for u in url])
        if not urls:
            raise InferenceServerException("no server url given")
        timeout = max(connection_timeout, network_timeout)
        self._targets = [_Target(u, concurrency, timeout) for u in urls]
        self._host = self._targets[0].host
        self._port = self._targets[0].port
        self._verbose = verbose
        self._pool = self._targets[0].pool
        self._rng = random.Random()
        self._executor = ThreadPoolExecutor(max_workers=max(concurrency, 1))
        self._stats = InferStat()
        # Opt-in resilience (client_tpu.resilience): when a RetryPolicy is
        # set, `network_timeout` becomes the end-to-end deadline budget —
        # it bounds the TOTAL wall time across all attempts and backoffs,
        # and each attempt's socket timeout shrinks to what remains.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker
        if len(self._targets) > 1 and self._breaker is None:
            # Multi-URL mode implies per-target circuit breaking: failover
            # without breaker memory would re-probe a dead replica on
            # every request.
            self._breaker = CircuitBreaker()
        self._breaker_host = f"{self._host}:{self._port}"
        self._network_timeout = network_timeout

    def get_infer_stat(self):
        """Cumulative client-side inference statistics (round-trip time
        plus the server-reported phase breakdown) — the InferStat
        equivalent of the reference client."""
        return self._stats.get()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._executor.shutdown(wait=False)
        for target in self._targets:
            target.pool.close()

    # -- low-level ----------------------------------------------------------

    def _request(self, method, path, body=None, headers=None,
                 query_params=None):
        headers = dict(headers or {})
        if query_params:
            path = path + "?" + urlencode(query_params)
        multi = len(self._targets) > 1
        send = self._request_multi if multi else self._request_once
        # Multi-target clients do per-target breaking inside the failover
        # loop; the resilience wrapper only adds value when a RetryPolicy
        # asks for cross-sweep retries.
        if self._retry_policy is None and (multi or self._breaker is None):
            return send(method, path, body, headers, None)
        # Correlate breaker transitions this request causes with its
        # distributed trace: infer() stamps a W3C traceparent header
        # (version-traceid-spanid-flags) before reaching here.
        trace_id = None
        tp = headers.get("traceparent")
        if tp:
            parts = tp.split("-")
            if len(parts) == 4:
                trace_id = parts[1]

        def attempt(remaining_s):
            resp, data = send(method, path, body, headers, remaining_s)
            retryable = (self._retry_policy is not None
                         and (resp.status
                              in self._retry_policy.retryable_statuses
                              or (resp.status in (429, 503)
                                  and _parse_retry_after(resp)
                                  is not None)))
            # A breaker-only client still needs 5xx surfaced as failures so
            # consecutive server faults trip it (4xx stays a plain return:
            # the caller's fault, not the host's). Multi-target mode
            # already recorded per-target outcomes inside the sweep.
            trips_breaker = (not multi and self._breaker is not None
                             and resp.status >= 500)
            if retryable or trips_breaker:
                # Surface retryable statuses as failures so the resilience
                # loop replays them; _RetryableStatus keeps (resp, data) so
                # exhaustion falls back to the plain return-the-response
                # contract every call site already handles.
                raise _RetryableStatus(resp, data)
            return resp, data

        try:
            return run_with_resilience(
                attempt,
                policy=self._retry_policy,
                breaker=None if multi else self._breaker,
                deadline_s=(self._network_timeout
                            if self._retry_policy is not None else None),
                host=self._breaker_host,
                on_retry=lambda n, exc, delay: self._stats.record_retry(),
                on_breaker_reject=self._stats.record_breaker_rejection,
                trace_id=trace_id)
        except _RetryableStatus as exc:
            return exc.resp, exc.data

    # -- multi-target (router-aware) transport -------------------------------

    def _order_targets(self, headers):
        """Sweep order: known-DRAINING targets last-resort only; affinity
        pin for an X-Sequence-Id header, else power-of-two-choices on load
        score; remaining targets by ascending score (failover order)."""
        pool = [t for t in self._targets if not t.draining]
        if not pool:
            pool = list(self._targets)
        if len(pool) == 1:
            return pool
        rest = sorted(pool, key=lambda t: t.score())
        seq = headers.get("X-Sequence-Id")
        if seq:
            by_id = {t.id: t for t in pool}
            primary = by_id[rendezvous_pick(sorted(by_id), seq)]
        else:
            a, b = self._rng.sample(pool, 2)
            primary = a if a.score() <= b.score() else b
        rest.remove(primary)
        return [primary] + rest

    def _request_multi(self, method, path, body, headers, remaining_s):
        """One sweep across the targets with the router's classification:
        transport failure trips that target's breaker and fails over;
        pushback (429/503 + Retry-After) is breaker-neutral-positive and
        fails over; a 5xx counts against the target and fails over. The
        sweep returns a pushback response only when EVERY reachable
        target pushed back (honest aggregation, client edition)."""
        last_exc = None
        pushback = None
        last_5xx = None
        for target in self._order_targets(headers):
            if self._breaker is not None:
                try:
                    self._breaker.check(target.id, None)
                except CircuitBreakerOpenError as exc:
                    self._stats.record_breaker_rejection()
                    last_exc = exc
                    continue
            with target._lock:
                target.outstanding += 1
            try:
                resp, data = self._request_on(target.pool, method, path,
                                              body, headers, remaining_s)
            except Exception as exc:  # noqa: BLE001 — transport failure
                if self._breaker is not None:
                    self._breaker.record_failure(target.id, None)
                last_exc = exc
                continue
            finally:
                with target._lock:
                    target.outstanding -= 1
            target.observe(resp)
            if (resp.status in (429, 503)
                    and _parse_retry_after(resp) is not None):
                # Alive and shedding — the opposite of down.
                if self._breaker is not None:
                    self._breaker.record_success(target.id, None)
                pushback = (resp, data)
                continue
            if resp.status >= 500:
                if self._breaker is not None:
                    self._breaker.record_failure(target.id, None)
                last_5xx = (resp, data)
                continue
            if self._breaker is not None:
                self._breaker.record_success(target.id, None)
            return resp, data
        if pushback is not None:
            return pushback
        if last_5xx is not None:
            return last_5xx
        raise last_exc if last_exc is not None else InferenceServerException(
            "no reachable server")

    def _request_once(self, method, path, body, headers, remaining_s):
        return self._request_on(self._pool, method, path, body, headers,
                                remaining_s)

    def _request_on(self, pool, method, path, body, headers, remaining_s):
        """One wire attempt, with the urllib3-style stale-socket replay: a
        pooled keep-alive connection that dies before ANY response bytes
        are read is discarded and the request replayed exactly once on a
        fresh connection (server-side idle timeouts routinely race the
        client's next use; independent of RetryPolicy)."""
        deadline = (time.monotonic() + remaining_s
                    if remaining_s is not None else None)
        for replay in (False, True):
            conn, reused = pool.acquire()
            if deadline is not None:
                # Per-attempt socket timeout shrinks to the remaining
                # deadline budget so one attempt cannot overrun the total.
                # Recomputed per iteration: the replay must not reuse the
                # pre-attempt budget, or it would overrun by whatever the
                # stale first attempt consumed. Floor at 1ms — settimeout(0)
                # would flip the socket into non-blocking mode.
                attempt_remaining = max(deadline - time.monotonic(), 0.001)
                conn.timeout = attempt_remaining
                if conn.sock is not None:
                    conn.sock.settimeout(attempt_remaining)
            got_response = False
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                got_response = True
                data = resp.read()
                pool.release(conn)
            except Exception as exc:
                pool.release(conn, broken=True)
                if (reused and not replay and not got_response
                        and isinstance(exc, _STALE_SOCKET_ERRORS)
                        and (deadline is None
                             or deadline - time.monotonic() > 0)):
                    self._stats.record_stale_socket_retry()
                    continue
                raise
            if self._verbose:
                print(f"{method} {path}, status {resp.status}")
            return resp, data

    def _get_json(self, path, query_params=None, headers=None):
        resp, data = self._request("GET", path, headers=headers,
                                   query_params=query_params)
        self._raise_if_error(resp, data)
        return json.loads(data) if data else {}

    def _post_json(self, path, obj=None, query_params=None, headers=None):
        body = json.dumps(obj).encode() if obj is not None else b""
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        resp, data = self._request(
            "POST", path, body=body, headers=hdrs,
            query_params=query_params)
        self._raise_if_error(resp, data)
        return json.loads(data) if data else {}

    @staticmethod
    def _raise_if_error(resp, data):
        if resp.status >= 400:
            msg = ""
            try:
                msg = json.loads(data).get("error", "")
            except Exception:  # noqa: BLE001
                msg = data.decode("utf-8", errors="replace")
            exc = InferenceServerException(msg or f"HTTP {resp.status}",
                                           status=resp.status)
            # Surface server pushback (admission sheds, drain) so callers
            # and resilience.retry_after_of can honor it.
            retry_after = _parse_retry_after(resp)
            if retry_after is not None:
                exc.retry_after_s = retry_after
            raise exc

    # -- health / metadata ---------------------------------------------------

    def is_server_live(self, headers=None, query_params=None):
        resp, _ = self._request("GET", "/v2/health/live",
                                query_params=query_params)
        return resp.status == 200

    def is_server_ready(self, headers=None, query_params=None):
        resp, _ = self._request("GET", "/v2/health/ready",
                                query_params=query_params)
        return resp.status == 200

    def is_model_ready(self, model_name, model_version="", headers=None,
                       query_params=None):
        path = f"/v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        resp, _ = self._request("GET", path + "/ready",
                                query_params=query_params)
        return resp.status == 200

    def get_server_metadata(self, headers=None, query_params=None):
        return self._get_json("/v2", query_params, headers)

    def get_model_metadata(self, model_name, model_version="", headers=None,
                           query_params=None):
        path = f"/v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return self._get_json(path, query_params, headers)

    def get_model_config(self, model_name, model_version="", headers=None,
                         query_params=None):
        path = f"/v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return self._get_json(path + "/config", query_params, headers)

    def get_model_repository_index(self, headers=None, query_params=None):
        return self._post_json("/v2/repository/index", {}, query_params, headers)

    def load_model(self, model_name, headers=None, query_params=None,
                   config=None, files=None):
        body = {}
        params = {}
        if config is not None:
            params["config"] = config
        for path, content in (files or {}).items():
            params[path] = base64.b64encode(content).decode("ascii")
        if params:
            body["parameters"] = params
        self._post_json(f"/v2/repository/models/{quote(model_name)}/load",
                        body, query_params, headers)

    def unload_model(self, model_name, headers=None, query_params=None,
                     unload_dependents=False):
        body = {}
        if unload_dependents:
            body["parameters"] = {"unload_dependents": True}
        self._post_json(f"/v2/repository/models/{quote(model_name)}/unload",
                        body, query_params, headers)

    def get_inference_statistics(self, model_name="", model_version="",
                                 headers=None, query_params=None):
        if model_name:
            path = f"/v2/models/{quote(model_name)}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "/v2/models/stats"
        return self._get_json(path, query_params, headers)

    # -- shared memory control ----------------------------------------------

    def get_system_shared_memory_status(self, region_name="", headers=None,
                                        query_params=None):
        path = "/v2/systemsharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        return self._get_json(path + "/status", query_params, headers)

    def register_system_shared_memory(self, name, key, byte_size, offset=0,
                                      headers=None, query_params=None):
        self._post_json(
            f"/v2/systemsharedmemory/region/{quote(name)}/register",
            {"key": key, "offset": offset, "byte_size": byte_size},
            query_params, headers)

    def unregister_system_shared_memory(self, name="", headers=None,
                                        query_params=None):
        path = "/v2/systemsharedmemory"
        if name:
            path += f"/region/{quote(name)}"
        self._post_json(path + "/unregister", {}, query_params, headers)

    def get_tpu_shared_memory_status(self, region_name="", headers=None,
                                     query_params=None):
        path = "/v2/tpusharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        return self._get_json(path + "/status", query_params, headers)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size, headers=None,
                                   query_params=None):
        """Register a TPU-HBM region by serialized buffer handle — the
        TPU-native replacement for register_cuda_shared_memory (reference
        cuda_shared_memory base64 handle transport). ``raw_handle`` may be
        the raw bytes from ``tpu_shared_memory.get_raw_handle`` or an
        already-base64 string."""
        if isinstance(raw_handle, (bytes, bytearray)):
            from client_tpu.protocol.codec import b64_encode_handle

            raw_handle = b64_encode_handle(bytes(raw_handle))
        self._post_json(
            f"/v2/tpusharedmemory/region/{quote(name)}/register",
            {"raw_handle": {"b64": raw_handle}, "device_id": device_id,
             "byte_size": byte_size},
            query_params, headers)

    def unregister_tpu_shared_memory(self, name="", headers=None,
                                     query_params=None):
        path = "/v2/tpusharedmemory"
        if name:
            path += f"/region/{quote(name)}"
        self._post_json(path + "/unregister", {}, query_params, headers)

    # CUDA-named aliases for drop-in compatibility with reference clients:
    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- shm slot ring (zero-copy data plane) -------------------------------

    def register_shm_ring(self, name, key, spec=None, headers=None,
                          query_params=None):
        """Attach a slot-ring segment (created with
        ``client_tpu.utils.shm_ring``) by POSIX shm key; geometry is read
        from the ring header. A ``spec`` (doorbell span spec without
        start/count) switches the ring to reaped mode: the engine-side
        reaper sweeps FILLED slots continuously, no doorbells needed."""
        body = {"key": key}
        if spec is not None:
            body["spec"] = spec
        self._post_json(f"/v2/shm/ring/{quote(name)}/register",
                        body, query_params, headers)

    def unregister_shm_ring(self, name="", headers=None, query_params=None):
        path = "/v2/shm/ring"
        if name:
            path += f"/{quote(name)}"
        self._post_json(path + "/unregister", {}, query_params, headers)

    def get_shm_ring_status(self, name="", headers=None, query_params=None):
        path = "/v2/shm/ring"
        if name:
            path += f"/{quote(name)}"
        return self._get_json(path + "/status", query_params, headers)

    def ring_doorbell(self, name, spec, headers=None, query_params=None):
        """Submit a span of FILLED slots in one round trip. ``spec`` is the
        doorbell span description (see ``RingProducer.doorbell``); returns
        ``{"admitted", "rejected", "skipped"}`` — completions are polled
        from shm, not from this response."""
        return self._post_json(f"/v2/shm/ring/{quote(name)}/doorbell",
                               spec, query_params, headers)

    # -- staged datasets (many-producer fan-in) -----------------------------

    def register_staged_dataset(self, name, key, headers=None,
                                query_params=None):
        """Attach a staged-dataset segment (built with
        ``client_tpu.utils.shm_ring.staged``) by POSIX shm key; the
        tensor manifest is read and validated from the segment header."""
        self._post_json(f"/v2/shm/dataset/{quote(name)}/register",
                        {"key": key}, query_params, headers)

    def unregister_staged_dataset(self, name="", headers=None,
                                  query_params=None):
        path = "/v2/shm/dataset"
        if name:
            path += f"/{quote(name)}"
        self._post_json(path + "/unregister", {}, query_params, headers)

    def get_staged_dataset_status(self, name="", headers=None,
                                  query_params=None):
        path = "/v2/shm/dataset"
        if name:
            path += f"/{quote(name)}"
        return self._get_json(path + "/status", query_params, headers)

    # -- trace (device profiling) --------------------------------------------

    def get_trace_settings(self, model_name="", headers=None,
                           query_params=None):
        """Server trace settings (engine-wide; ``model_name`` accepted for
        API compatibility)."""
        return self._get_json("/v2/trace/setting", query_params, headers)

    def update_trace_settings(self, model_name="", settings=None,
                              headers=None, query_params=None):
        """Update trace settings; activating (trace_level != OFF) starts a
        jax.profiler device trace into ``log_dir``."""
        return self._post_json("/v2/trace/setting", settings or {},
                               query_params, headers)

    # -- operational control plane -------------------------------------------

    def get_events(self, model_name="", severity="", category="",
                   since_seq=None, since_wall=None, until_wall=None,
                   limit=None, headers=None, query_params=None):
        """Server operational event timeline (``GET /v2/events``):
        breaker/admission/drain/model/fault/deadline transitions with
        trace correlation. ``severity`` is a minimum (e.g. ``WARNING``);
        ``since_seq`` the exclusive cursor from the previous response's
        ``next_seq``; ``since_wall``/``until_wall`` an epoch-seconds
        window (exclusive lower, inclusive upper)."""
        qp = dict(query_params or {})
        if model_name:
            qp["model"] = model_name
        if severity:
            qp["severity"] = severity
        if category:
            qp["category"] = category
        if since_seq is not None:
            qp["since"] = int(since_seq)
        if since_wall is not None:
            qp["since_wall"] = float(since_wall)
        if until_wall is not None:
            qp["until_wall"] = float(until_wall)
        if limit is not None:
            qp["limit"] = int(limit)
        return self._get_json("/v2/events", qp or None, headers)

    def get_slo_status(self, headers=None, query_params=None):
        """Per-model SLO burn-rate report (``GET /v2/slo``)."""
        return self._get_json("/v2/slo", query_params, headers)

    def get_profile(self, model_name="", headers=None, query_params=None):
        """Efficiency profiler cost table (``GET /v2/profile``): per-model
        per-bucket fill ratios, padding-waste device-seconds, compile
        counts, device duty cycle, and a suggested bucket-ladder tweak."""
        qp = dict(query_params or {})
        if model_name:
            qp["model"] = model_name
        return self._get_json("/v2/profile", qp or None, headers)

    def get_timeseries(self, signal="", model_name="", since_seq=None,
                       since_wall=None, until_wall=None, limit=None,
                       headers=None, query_params=None):
        """Flight-recorder signal ring (``GET /v2/timeseries``): ~15 min
        of 1 Hz duty-cycle / queue-depth / batch-fill / shed-rate /
        wave-p50 / HBM / SLO-burn samples. ``since_seq`` is the
        exclusive cursor from the previous response's ``next_seq``;
        ``since_wall``/``until_wall`` an epoch-seconds window
        (exclusive lower, inclusive upper)."""
        qp = dict(query_params or {})
        if signal:
            qp["signal"] = signal
        if model_name:
            qp["model"] = model_name
        if since_seq is not None:
            qp["since"] = int(since_seq)
        if since_wall is not None:
            qp["since_wall"] = float(since_wall)
        if until_wall is not None:
            qp["until_wall"] = float(until_wall)
        if limit is not None:
            qp["limit"] = int(limit)
        return self._get_json("/v2/timeseries", qp or None, headers)

    def get_memory(self, headers=None, query_params=None):
        """HBM census report (``GET /v2/memory``): live device bytes per
        ``(model, component)`` owner, plan-vs-actual drift, watermark."""
        return self._get_json("/v2/memory", query_params, headers)

    def get_costs(self, model_name="", headers=None, query_params=None):
        """Per-tenant cost ledger (``GET /v2/costs``): device-seconds,
        HBM-byte-seconds, queue-seconds, and interference attribution
        per tenant, with profiler/census reconciliation. Tag requests
        with the ``X-Tpu-Tenant`` header or a ``tenant`` request
        parameter to attribute their spend."""
        qp = dict(query_params or {})
        if model_name:
            qp["model"] = model_name
        return self._get_json("/v2/costs", qp or None, headers)

    def get_qos_status(self, model_name="", headers=None,
                       query_params=None):
        """Tenant QoS status (``GET /v2/qos``): the class table (WFQ
        weights, token-bucket quotas, governor throttle ratios,
        inflight and shed/preemption tallies) plus per-model WFQ lane
        depths. ``model_name`` narrows the lane depths to one model."""
        qp = dict(query_params or {})
        if model_name:
            qp["model"] = model_name
        return self._get_json("/v2/qos", qp or None, headers)

    def get_bundles(self, bundle_id="", headers=None, query_params=None):
        """Incident-blackbox bundles (``GET /v2/debug/bundles[/{id}]``):
        the retained-bundle index, or — with ``bundle_id`` — one full
        bundle document (render with ``tools/blackbox_report.py``)."""
        path = "/v2/debug/bundles"
        if bundle_id:
            path += f"/{bundle_id}"
        return self._get_json(path, query_params, headers)

    def capture_bundle(self, trigger="manual", incident="", note="",
                       headers=None, query_params=None):
        """Trigger an incident capture now (``POST /v2/debug/capture``)
        and return the written bundle's meta. Pass ``incident`` to
        stamp a shared incident id (fleet-coordinated captures);
        a non-``manual`` trigger name respects the server's
        debounce/cooldown and may return ``{"deduped": true}``."""
        body = {"trigger": trigger or "manual"}
        if incident:
            body["incident"] = incident
        if note:
            body["note"] = note
        return self._post_json("/v2/debug/capture", body, query_params,
                               headers)

    # -- fleet observability (router endpoints) ------------------------------

    def get_fleet_events(self, limit=None, headers=None, query_params=None):
        """Federated fleet event timeline (router ``GET
        /v2/fleet/events``): every replica's journal merged by wall
        stamp, each event tagged ``replica``, with per-replica
        ``cursors`` and inline fetch ``errors``."""
        qp = dict(query_params or {})
        if limit is not None:
            qp["limit"] = int(limit)
        return self._get_json("/v2/fleet/events", qp or None, headers)

    def get_fleet_profile(self, headers=None, query_params=None):
        """Federated profiler view (router ``GET /v2/fleet/profile``):
        per-replica snapshots plus fleet drift signals/scores."""
        return self._get_json("/v2/fleet/profile", query_params, headers)

    def get_fleet_slo(self, headers=None, query_params=None):
        """Federated SLO view (router ``GET /v2/fleet/slo``)."""
        return self._get_json("/v2/fleet/slo", query_params, headers)

    def get_fleet_costs(self, headers=None, query_params=None):
        """Federated cost-ledger view (router ``GET /v2/fleet/costs``):
        per-replica snapshots plus fleet-wide per-tenant totals."""
        return self._get_json("/v2/fleet/costs", query_params, headers)

    def get_fleet_timeseries(self, signal="", model_name="", limit=None,
                             headers=None, query_params=None):
        """Federated flight-recorder view (router ``GET
        /v2/fleet/timeseries``): every replica's signal ring merged by
        wall stamp, each sample tagged ``replica``, with per-replica
        ``cursors`` and inline fetch ``errors``."""
        qp = dict(query_params or {})
        if signal:
            qp["signal"] = signal
        if model_name:
            qp["model"] = model_name
        if limit is not None:
            qp["limit"] = int(limit)
        return self._get_json("/v2/fleet/timeseries", qp or None, headers)

    def get_fleet_metrics(self, headers=None, query_params=None):
        """Merged fleet exposition text (router ``GET
        /v2/fleet/metrics``) — counters summed, level gauges maxed."""
        resp, data = self._request("GET", "/v2/fleet/metrics",
                                   headers=headers,
                                   query_params=query_params)
        self._raise_if_error(resp, data)
        return data.decode("utf-8", "replace")

    def get_stitched_trace(self, trace_id="", headers=None,
                           query_params=None):
        """Stitched fleet Chrome trace (router ``GET
        /v2/trace/requests``): router spans + replica phase spans on
        distinct tracks; pass the ``X-Tpu-Trace-Id`` echoed on any
        routed response to narrow to one request."""
        qp = dict(query_params or {})
        if trace_id:
            qp["trace_id"] = trace_id
        return self._get_json("/v2/trace/requests", qp or None, headers)

    # -- inference -----------------------------------------------------------

    @staticmethod
    def generate_request_body(inputs, outputs=None, request_id="",
                              sequence_id=0, sequence_start=False,
                              sequence_end=False, priority=0, timeout=None,
                              parameters=None):
        """Build (body, header_length) without sending — mirrors the
        reference's static generate_request_body (http/__init__.py:1015)."""
        params = dict(parameters or {})
        if outputs is None:
            # No explicit outputs: ask the server for binary encoding of all
            # outputs (matches the reference client's default, which sets
            # binary_data_output when outputs are unspecified).
            params.setdefault("binary_data_output", True)
        if sequence_id:
            params["sequence_id"] = sequence_id
            params["sequence_start"] = sequence_start
            params["sequence_end"] = sequence_end
        if priority:
            params["priority"] = priority
        if timeout is not None:
            params["timeout"] = timeout
        tensor_entries = [(i._get_tensor(), i._raw_data) for i in inputs]
        out_entries = [o._get_tensor() for o in outputs] if outputs else None
        body, jlen = rest.build_infer_request_body(
            tensor_entries, out_entries, request_id=request_id,
            parameters=params or None)
        has_binary = any(raw is not None for _, raw in tensor_entries)
        return body, (jlen if has_binary else None)

    @staticmethod
    def parse_response_body(response_body, verbose=False, header_length=None,
                            content_encoding=None):
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding)

    def _infer_request(self, model_name, model_version, body, header_length,
                       headers, query_params, request_compression_algorithm,
                       response_compression_algorithm, timeout_ms=None,
                       sequence_id=0):
        req_headers = dict(headers or {})
        if sequence_id:
            # Affinity signal for L7 routing (this client's own multi-URL
            # sweep and the standalone router both rendezvous-hash on it)
            # — a header, so no intermediary ever parses the body.
            req_headers.setdefault("X-Sequence-Id", str(sequence_id))
        if timeout_ms is not None:
            # End-to-end deadline propagation: the server's scheduler and
            # model skip this request once the budget lapses (504 instead
            # of wasted device time).
            req_headers["timeout-ms"] = f"{float(timeout_ms):g}"
        if header_length is not None:
            req_headers[rest.HEADER_INFERENCE_CONTENT_LENGTH] = str(header_length)
        if request_compression_algorithm == "gzip":
            body = gzip.compress(body)
            req_headers["Content-Encoding"] = "gzip"
        elif request_compression_algorithm == "deflate":
            body = zlib.compress(body)
            req_headers["Content-Encoding"] = "deflate"
        if response_compression_algorithm in ("gzip", "deflate"):
            req_headers["Accept-Encoding"] = response_compression_algorithm
        # Distributed tracing: propagate the caller's traceparent, or start
        # a new trace per request so every inference is correlatable with
        # the server's span timeline.
        req_headers.setdefault("traceparent",
                               TraceContext.new().to_traceparent())

        path = f"/v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        path += "/infer"
        t0 = time.monotonic_ns()
        resp, data = self._request("POST", path, body=body,
                                   headers=req_headers,
                                   query_params=query_params)
        round_trip_us = (time.monotonic_ns() - t0) / 1e3
        encoding = resp.getheader("Content-Encoding")
        if encoding == "gzip":
            data = gzip.decompress(data)
        elif encoding == "deflate":
            data = zlib.decompress(data)
        self._raise_if_error(resp, data)
        hdr = resp.getheader(rest.HEADER_INFERENCE_CONTENT_LENGTH)
        result = InferResult(data, int(hdr) if hdr is not None else None,
                             self._verbose)
        tp = resp.getheader("traceparent") or ""
        if tp.count("-") >= 2:
            result._trace_id = tp.split("-")[1]
        result._server_timing = parse_server_timing(
            resp.getheader("Server-Timing"))
        self._stats.record(round_trip_us, result._server_timing,
                           trace_id=result._trace_id)
        return result

    def infer(self, model_name, inputs, model_version="", outputs=None,
              request_id="", sequence_id=0, sequence_start=False,
              sequence_end=False, priority=0, timeout=None, headers=None,
              query_params=None, request_compression_algorithm=None,
              response_compression_algorithm=None, parameters=None,
              timeout_ms=None):
        body, header_length = self.generate_request_body(
            inputs, outputs, request_id, sequence_id, sequence_start,
            sequence_end, priority, timeout, parameters)
        return self._infer_request(
            model_name, model_version, body, header_length, headers,
            query_params, request_compression_algorithm,
            response_compression_algorithm, timeout_ms=timeout_ms,
            sequence_id=sequence_id)

    def async_infer(self, model_name, inputs, model_version="", outputs=None,
                    request_id="", sequence_id=0, sequence_start=False,
                    sequence_end=False, priority=0, timeout=None,
                    headers=None, query_params=None,
                    request_compression_algorithm=None,
                    response_compression_algorithm=None, parameters=None,
                    timeout_ms=None):
        body, header_length = self.generate_request_body(
            inputs, outputs, request_id, sequence_id, sequence_start,
            sequence_end, priority, timeout, parameters)
        future = self._executor.submit(
            self._infer_request, model_name, model_version, body,
            header_length, headers, query_params,
            request_compression_algorithm, response_compression_algorithm,
            timeout_ms, sequence_id)
        return InferAsyncRequest(future, self._verbose)
