package tpu.client;

/**
 * v2 wire datatypes with element byte sizes (reference DataType POJO,
 * /root/reference/src/java .../DataType.java; dtype table mirrors
 * client_tpu/protocol/dtypes.py).
 */
public enum DataType {
    BOOL(1), UINT8(1), UINT16(2), UINT32(4), UINT64(8),
    INT8(1), INT16(2), INT32(4), INT64(8),
    FP16(2), BF16(2), FP32(4), FP64(8),
    BYTES(0);

    private final int byteSize;

    DataType(int byteSize) {
        this.byteSize = byteSize;
    }

    /** Element size in bytes; 0 for BYTES (variable length). */
    public int byteSize() {
        return byteSize;
    }

    public static DataType fromWire(String name) {
        return DataType.valueOf(name);
    }
}
