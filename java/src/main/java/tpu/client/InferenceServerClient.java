package tpu.client;

import java.io.ByteArrayOutputStream;
import java.net.URI;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.charset.StandardCharsets;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import java.util.concurrent.CompletableFuture;
import java.util.concurrent.ExecutorService;
import java.util.concurrent.Executors;

import tpu.client.endpoint.AbstractEndpoint;
import tpu.client.endpoint.FixedEndpoint;

/**
 * HTTP/REST client for the v2 inference protocol (reference
 * InferenceServerClient.java:72+ on Apache HttpAsyncClient; this one rides
 * the JDK's HttpClient). Sync + async infer with the binary tensor
 * extension (JSON head + concatenated binary tails framed by
 * Inference-Header-Content-Length), plus the control plane: health,
 * metadata, config, repository index/load/unload, statistics, and
 * system/TPU shared-memory registration.
 */
public class InferenceServerClient implements AutoCloseable {

    private final AbstractEndpoint endpoint;
    private final HttpConfig config;
    private final HttpClient http;
    private final ExecutorService asyncPool;

    public InferenceServerClient(String url) {
        this(new FixedEndpoint(url), new HttpConfig());
    }

    public InferenceServerClient(String url, HttpConfig config) {
        this(new FixedEndpoint(url), config);
    }

    public InferenceServerClient(AbstractEndpoint endpoint,
                                 HttpConfig config) {
        this.endpoint = endpoint;
        this.config = config;
        this.http = HttpClient.newBuilder()
                .connectTimeout(config.getConnectTimeout())
                .build();
        this.asyncPool =
                Executors.newFixedThreadPool(config.getMaxAsyncRequests());
    }

    @Override
    public void close() {
        asyncPool.shutdown();
    }

    // ------------------------------------------------------ health ----------

    public boolean isServerLive() throws InferenceException {
        return get("/v2/health/live").statusCode() == 200;
    }

    public boolean isServerReady() throws InferenceException {
        return get("/v2/health/ready").statusCode() == 200;
    }

    public boolean isModelReady(String modelName) throws InferenceException {
        return get("/v2/models/" + modelName + "/ready").statusCode() == 200;
    }

    // ---------------------------------------------------- metadata ----------

    public Map<String, Object> getServerMetadata() throws InferenceException {
        return Json.parseObject(bodyOf(checked(get("/v2"))));
    }

    public Map<String, Object> getModelMetadata(String modelName)
            throws InferenceException {
        return Json.parseObject(
                bodyOf(checked(get("/v2/models/" + modelName))));
    }

    public Map<String, Object> getModelConfig(String modelName)
            throws InferenceException {
        return Json.parseObject(
                bodyOf(checked(get("/v2/models/" + modelName + "/config"))));
    }

    public Object getModelRepositoryIndex() throws InferenceException {
        return Json.parse(
                bodyOf(checked(post("/v2/repository/index", "{}"))));
    }

    public void loadModel(String modelName) throws InferenceException {
        checked(post("/v2/repository/models/" + modelName + "/load", "{}"));
    }

    public void unloadModel(String modelName) throws InferenceException {
        checked(post("/v2/repository/models/" + modelName + "/unload", "{}"));
    }

    public Map<String, Object> getInferenceStatistics(String modelName)
            throws InferenceException {
        return Json.parseObject(
                bodyOf(checked(get("/v2/models/" + modelName + "/stats"))));
    }

    // ----------------------------------------------- shared memory ----------

    public void registerSystemSharedMemory(String name, String key,
                                           long byteSize, long offset)
            throws InferenceException {
        Map<String, Object> body = new LinkedHashMap<>();
        body.put("key", key);
        body.put("offset", offset);
        body.put("byte_size", byteSize);
        checked(post("/v2/systemsharedmemory/region/" + name + "/register",
                Json.write(body)));
    }

    public void unregisterSystemSharedMemory(String name)
            throws InferenceException {
        checked(post("/v2/systemsharedmemory/region/" + name + "/unregister",
                "{}"));
    }

    public void registerTpuSharedMemory(String name, String rawHandleB64,
                                        long deviceId, long byteSize)
            throws InferenceException {
        Map<String, Object> body = new LinkedHashMap<>();
        body.put("raw_handle", Map.of("b64", rawHandleB64));
        body.put("device_id", deviceId);
        body.put("byte_size", byteSize);
        checked(post("/v2/tpusharedmemory/region/" + name + "/register",
                Json.write(body)));
    }

    public void unregisterTpuSharedMemory(String name)
            throws InferenceException {
        checked(post("/v2/tpusharedmemory/region/" + name + "/unregister",
                "{}"));
    }

    // -------------------------------------------------------- infer ---------

    public InferResult infer(String modelName, List<InferInput> inputs,
                             List<InferRequestedOutput> outputs)
            throws InferenceException {
        return infer(modelName, inputs, outputs, null);
    }

    public InferResult infer(String modelName, List<InferInput> inputs,
                             List<InferRequestedOutput> outputs,
                             String requestId) throws InferenceException {
        // Head serialized ONCE; its byte length frames the binary tails.
        byte[] head = requestHead(inputs, outputs, requestId)
                .getBytes(StandardCharsets.UTF_8);
        byte[] body = buildRequestBody(head, inputs);
        int headLen = head.length;
        HttpRequest request = HttpRequest.newBuilder()
                .uri(URI.create(endpoint.next() + "/v2/models/" + modelName
                        + "/infer"))
                .timeout(config.getRequestTimeout())
                .header("Content-Type", "application/octet-stream")
                .header("Inference-Header-Content-Length",
                        String.valueOf(headLen))
                .POST(HttpRequest.BodyPublishers.ofByteArray(body))
                .build();
        HttpResponse<byte[]> response;
        try {
            response = http.send(request,
                    HttpResponse.BodyHandlers.ofByteArray());
        } catch (Exception e) {
            throw new InferenceException("infer request failed", e);
        }
        return parseInferResponse(response);
    }

    /** Callback-style async infer on the client's thread pool. */
    public CompletableFuture<InferResult> asyncInfer(
            String modelName, List<InferInput> inputs,
            List<InferRequestedOutput> outputs) {
        CompletableFuture<InferResult> future = new CompletableFuture<>();
        asyncPool.submit(() -> {
            try {
                future.complete(infer(modelName, inputs, outputs));
            } catch (Throwable t) {
                future.completeExceptionally(t);
            }
        });
        return future;
    }

    // ----------------------------------------------------- plumbing ---------

    private String requestHead(List<InferInput> inputs,
                               List<InferRequestedOutput> outputs,
                               String requestId) {
        Map<String, Object> head = new LinkedHashMap<>();
        if (requestId != null) {
            head.put("id", requestId);
        }
        java.util.List<Object> ins = new java.util.ArrayList<>();
        for (InferInput input : inputs) {
            ins.add(input.toJson());
        }
        head.put("inputs", ins);
        if (outputs != null && !outputs.isEmpty()) {
            java.util.List<Object> outs = new java.util.ArrayList<>();
            for (InferRequestedOutput output : outputs) {
                outs.add(output.toJson());
            }
            head.put("outputs", outs);
        }
        return Json.write(head);
    }

    private byte[] buildRequestBody(byte[] head, List<InferInput> inputs) {
        ByteArrayOutputStream out = new ByteArrayOutputStream();
        out.writeBytes(head);
        for (InferInput input : inputs) {
            if (!input.isSharedMemory()) {
                out.writeBytes(input.getData());
            }
        }
        return out.toByteArray();
    }

    private InferResult parseInferResponse(HttpResponse<byte[]> response)
            throws InferenceException {
        byte[] body = response.body();
        if (response.statusCode() >= 400) {
            throw new InferenceException(
                    new String(body, StandardCharsets.UTF_8),
                    response.statusCode());
        }
        int headerLength;
        try {
            headerLength = response.headers()
                    .firstValue("Inference-Header-Content-Length")
                    .map(Integer::parseInt).orElse(0);
        } catch (NumberFormatException e) {
            throw new InferenceException(
                    "bad Inference-Header-Content-Length header", e);
        }
        return new InferResult(body, headerLength);
    }

    private HttpResponse<byte[]> get(String path) throws InferenceException {
        try {
            HttpRequest request = HttpRequest.newBuilder()
                    .uri(URI.create(endpoint.next() + path))
                    .timeout(config.getRequestTimeout())
                    .GET().build();
            return http.send(request, HttpResponse.BodyHandlers.ofByteArray());
        } catch (Exception e) {
            throw new InferenceException("GET " + path + " failed", e);
        }
    }

    private HttpResponse<byte[]> post(String path, String body)
            throws InferenceException {
        try {
            HttpRequest request = HttpRequest.newBuilder()
                    .uri(URI.create(endpoint.next() + path))
                    .timeout(config.getRequestTimeout())
                    .header("Content-Type", "application/json")
                    .POST(HttpRequest.BodyPublishers.ofString(body))
                    .build();
            return http.send(request, HttpResponse.BodyHandlers.ofByteArray());
        } catch (Exception e) {
            throw new InferenceException("POST " + path + " failed", e);
        }
    }

    private HttpResponse<byte[]> checked(HttpResponse<byte[]> response)
            throws InferenceException {
        if (response.statusCode() >= 400) {
            throw new InferenceException(
                    new String(response.body(), StandardCharsets.UTF_8),
                    response.statusCode());
        }
        return response;
    }

    private static String bodyOf(HttpResponse<byte[]> response) {
        return new String(response.body(), StandardCharsets.UTF_8);
    }
}
