package tpu.client;

import java.util.LinkedHashMap;
import java.util.Map;

/**
 * Input tensor: shape/dtype metadata plus binary payload (reference
 * InferInput.java:335 with BinaryProtocol LE encoders). Data always rides
 * the binary extension (JSON head + binary tail).
 */
public class InferInput {
    private final String name;
    private final long[] shape;
    private final DataType datatype;
    private byte[] data;
    private String shmRegion;
    private long shmByteSize;
    private long shmOffset;

    public InferInput(String name, long[] shape, DataType datatype) {
        this.name = name;
        this.shape = shape;
        this.datatype = datatype;
    }

    public String getName() {
        return name;
    }

    public DataType getDatatype() {
        return datatype;
    }

    public long[] getShape() {
        return shape;
    }

    public void setData(int[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    public void setData(long[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    public void setData(float[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    public void setData(double[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    public void setData(boolean[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    /** BYTES tensors: 4-byte-LE length-prefixed elements. */
    public void setData(String[] values) {
        this.data = BinaryProtocol.toBytes(values);
    }

    /** Raw little-endian bytes, caller-encoded. */
    public void setRawData(byte[] raw) {
        this.data = raw;
    }

    public void setSharedMemory(String regionName, long byteSize,
                                long offset) {
        this.shmRegion = regionName;
        this.shmByteSize = byteSize;
        this.shmOffset = offset;
        this.data = null;
    }

    public byte[] getData() {
        return data;
    }

    public boolean isSharedMemory() {
        return shmRegion != null;
    }

    /** JSON head entry for this input. */
    Map<String, Object> toJson() {
        Map<String, Object> out = new LinkedHashMap<>();
        out.put("name", name);
        out.put("shape", shape);
        out.put("datatype", datatype.name());
        Map<String, Object> params = new LinkedHashMap<>();
        if (shmRegion != null) {
            params.put("shared_memory_region", shmRegion);
            params.put("shared_memory_byte_size", shmByteSize);
            if (shmOffset != 0) {
                params.put("shared_memory_offset", shmOffset);
            }
        } else {
            if (data == null) {
                throw new IllegalStateException("input '" + name
                        + "' has no data: call setData() or "
                        + "setSharedMemory() before infer()");
            }
            params.put("binary_data_size", (long) data.length);
        }
        out.put("parameters", params);
        return out;
    }
}
