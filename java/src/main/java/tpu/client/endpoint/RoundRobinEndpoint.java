package tpu.client.endpoint;

import java.util.ArrayList;
import java.util.List;
import java.util.concurrent.atomic.AtomicInteger;

/** Rotates across a fixed replica list, one URL per request. */
public class RoundRobinEndpoint extends AbstractEndpoint {
    private final List<String> urls = new ArrayList<>();
    private final AtomicInteger index = new AtomicInteger();

    public RoundRobinEndpoint(List<String> urls) {
        for (String u : urls) {
            this.urls.add(u.contains("://") ? u : "http://" + u);
        }
        if (this.urls.isEmpty()) {
            throw new IllegalArgumentException("no endpoints");
        }
    }

    @Override
    public String next() {
        int i = Math.floorMod(index.getAndIncrement(), urls.size());
        return urls.get(i);
    }
}
