package tpu.client.endpoint;

/** Single fixed base URL. */
public class FixedEndpoint extends AbstractEndpoint {
    private final String url;

    public FixedEndpoint(String url) {
        // tolerate bare host:port
        this.url = url.contains("://") ? url : "http://" + url;
    }

    @Override
    public String next() {
        return url;
    }
}
