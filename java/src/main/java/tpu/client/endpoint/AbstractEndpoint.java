package tpu.client.endpoint;

/**
 * Pluggable URL provider (reference endpoint/ layer, SURVEY.md §2.5):
 * each request asks for the next base URL, enabling client-side rotation
 * over replicas.
 */
public abstract class AbstractEndpoint {
    /** Returns the base URL (e.g. "http://host:8000") for the next call. */
    public abstract String next();
}
