package tpu.client;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * Minimal JSON parser/writer sized for the v2 protocol (objects, arrays,
 * strings, numbers, booleans, null). Replaces the external JSON library the
 * reference depends on so this client builds with nothing but a JDK.
 *
 * Parsed values map to: Map&lt;String,Object&gt;, List&lt;Object&gt;,
 * String, Long, Double, Boolean, null.
 */
public final class Json {

    private final String text;
    private int pos;

    private Json(String text) {
        this.text = text;
    }

    public static Object parse(String text) throws InferenceException {
        Json p = new Json(text);
        p.skipWhitespace();
        Object value = p.parseValue();
        p.skipWhitespace();
        if (p.pos != text.length()) {
            throw new InferenceException("trailing JSON content at " + p.pos);
        }
        return value;
    }

    @SuppressWarnings("unchecked")
    public static Map<String, Object> parseObject(String text)
            throws InferenceException {
        Object value = parse(text);
        if (!(value instanceof Map)) {
            throw new InferenceException("expected JSON object");
        }
        return (Map<String, Object>) value;
    }

    // ---------------------------------------------------------- parsing ----

    private Object parseValue() throws InferenceException {
        if (pos >= text.length()) {
            throw new InferenceException("unexpected end of JSON");
        }
        char c = text.charAt(pos);
        switch (c) {
            case '{':
                return parseObjectValue();
            case '[':
                return parseArray();
            case '"':
                return parseString();
            case 't':
                expect("true");
                return Boolean.TRUE;
            case 'f':
                expect("false");
                return Boolean.FALSE;
            case 'n':
                expect("null");
                return null;
            default:
                return parseNumber();
        }
    }

    private Map<String, Object> parseObjectValue() throws InferenceException {
        Map<String, Object> out = new LinkedHashMap<>();
        pos++; // '{'
        skipWhitespace();
        if (peek() == '}') {
            pos++;
            return out;
        }
        while (true) {
            skipWhitespace();
            String key = parseString();
            skipWhitespace();
            if (peek() != ':') {
                throw new InferenceException("expected ':' at " + pos);
            }
            pos++;
            skipWhitespace();
            out.put(key, parseValue());
            skipWhitespace();
            char c = peek();
            if (c == ',') {
                pos++;
            } else if (c == '}') {
                pos++;
                return out;
            } else {
                throw new InferenceException("expected ',' or '}' at " + pos);
            }
        }
    }

    private List<Object> parseArray() throws InferenceException {
        List<Object> out = new ArrayList<>();
        pos++; // '['
        skipWhitespace();
        if (peek() == ']') {
            pos++;
            return out;
        }
        while (true) {
            skipWhitespace();
            out.add(parseValue());
            skipWhitespace();
            char c = peek();
            if (c == ',') {
                pos++;
            } else if (c == ']') {
                pos++;
                return out;
            } else {
                throw new InferenceException("expected ',' or ']' at " + pos);
            }
        }
    }

    private String parseString() throws InferenceException {
        if (peek() != '"') {
            throw new InferenceException("expected string at " + pos);
        }
        pos++;
        StringBuilder sb = new StringBuilder();
        while (true) {
            if (pos >= text.length()) {
                throw new InferenceException("unterminated string");
            }
            char c = text.charAt(pos++);
            if (c == '"') {
                return sb.toString();
            }
            if (c != '\\') {
                sb.append(c);
                continue;
            }
            if (pos >= text.length()) {
                throw new InferenceException("unterminated escape");
            }
            char esc = text.charAt(pos++);
            switch (esc) {
                case '"': sb.append('"'); break;
                case '\\': sb.append('\\'); break;
                case '/': sb.append('/'); break;
                case 'b': sb.append('\b'); break;
                case 'f': sb.append('\f'); break;
                case 'n': sb.append('\n'); break;
                case 'r': sb.append('\r'); break;
                case 't': sb.append('\t'); break;
                case 'u':
                    if (pos + 4 > text.length()) {
                        throw new InferenceException(
                                "truncated \\u escape");
                    }
                    try {
                        sb.append((char) Integer.parseInt(
                                text.substring(pos, pos + 4), 16));
                    } catch (NumberFormatException e) {
                        throw new InferenceException(
                                "bad \\u escape at " + pos);
                    }
                    pos += 4;
                    break;
                default:
                    throw new InferenceException("bad escape \\" + esc);
            }
        }
    }

    private Object parseNumber() throws InferenceException {
        int start = pos;
        boolean isDouble = false;
        while (pos < text.length()) {
            char c = text.charAt(pos);
            if (c == '-' || c == '+' || (c >= '0' && c <= '9')) {
                pos++;
            } else if (c == '.' || c == 'e' || c == 'E') {
                isDouble = true;
                pos++;
            } else {
                break;
            }
        }
        String token = text.substring(start, pos);
        try {
            return isDouble ? (Object) Double.parseDouble(token)
                            : (Object) Long.parseLong(token);
        } catch (NumberFormatException e) {
            throw new InferenceException("bad number '" + token + "'");
        }
    }

    private char peek() throws InferenceException {
        if (pos >= text.length()) {
            throw new InferenceException("unexpected end of JSON");
        }
        return text.charAt(pos);
    }

    private void expect(String literal) throws InferenceException {
        if (!text.startsWith(literal, pos)) {
            throw new InferenceException("bad literal at " + pos);
        }
        pos += literal.length();
    }

    private void skipWhitespace() {
        while (pos < text.length()
                && Character.isWhitespace(text.charAt(pos))) {
            pos++;
        }
    }

    // ---------------------------------------------------------- writing ----

    public static void write(Object value, StringBuilder sb) {
        if (value == null) {
            sb.append("null");
        } else if (value instanceof String) {
            writeString((String) value, sb);
        } else if (value instanceof Map) {
            sb.append('{');
            boolean first = true;
            for (Map.Entry<?, ?> e : ((Map<?, ?>) value).entrySet()) {
                if (!first) {
                    sb.append(',');
                }
                first = false;
                writeString(String.valueOf(e.getKey()), sb);
                sb.append(':');
                write(e.getValue(), sb);
            }
            sb.append('}');
        } else if (value instanceof Iterable) {
            sb.append('[');
            boolean first = true;
            for (Object item : (Iterable<?>) value) {
                if (!first) {
                    sb.append(',');
                }
                first = false;
                write(item, sb);
            }
            sb.append(']');
        } else if (value instanceof long[]) {
            sb.append('[');
            long[] arr = (long[]) value;
            for (int i = 0; i < arr.length; i++) {
                if (i > 0) {
                    sb.append(',');
                }
                sb.append(arr[i]);
            }
            sb.append(']');
        } else {
            sb.append(value); // Number / Boolean
        }
    }

    public static String write(Object value) {
        StringBuilder sb = new StringBuilder();
        write(value, sb);
        return sb.toString();
    }

    private static void writeString(String s, StringBuilder sb) {
        sb.append('"');
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            switch (c) {
                case '"': sb.append("\\\""); break;
                case '\\': sb.append("\\\\"); break;
                case '\n': sb.append("\\n"); break;
                case '\r': sb.append("\\r"); break;
                case '\t': sb.append("\\t"); break;
                default:
                    if (c < 0x20) {
                        sb.append(String.format("\\u%04x", (int) c));
                    } else {
                        sb.append(c);
                    }
            }
        }
        sb.append('"');
    }
}
