package tpu.client;

/**
 * Tensor metadata POJO (reference IOTensor, SURVEY.md §2.5): name, wire
 * datatype, shape.
 */
public class IOTensor {
    private final String name;
    private final String datatype;
    private final long[] shape;

    public IOTensor(String name, String datatype, long[] shape) {
        this.name = name;
        this.datatype = datatype;
        this.shape = shape;
    }

    public String getName() {
        return name;
    }

    public String getDatatype() {
        return datatype;
    }

    public long[] getShape() {
        return shape;
    }

    public DataType dataType() {
        return DataType.fromWire(datatype);
    }

    public long elementCount() {
        long n = 1;
        for (long d : shape) {
            n *= d;
        }
        return n;
    }
}
