package tpu.client;

/** Client error carrying the HTTP status when one is available. */
public class InferenceException extends Exception {
    private final int status;

    public InferenceException(String message) {
        this(message, 0);
    }

    public InferenceException(String message, int status) {
        super(message);
        this.status = status;
    }

    public InferenceException(String message, Throwable cause) {
        super(message, cause);
        this.status = 0;
    }

    /** HTTP status code, or 0 when the failure was not an HTTP error. */
    public int getStatus() {
        return status;
    }
}
