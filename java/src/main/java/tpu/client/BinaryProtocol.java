package tpu.client;

import java.io.ByteArrayOutputStream;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.List;

/**
 * Little-endian tensor (de)serialization, including the BYTES codec
 * (4-byte LE length prefix per element). Counterpart of the reference's
 * BinaryProtocol.java:52-104 encoders and Util.intToBytes; wire-identical
 * to client_tpu/protocol/codec.py.
 */
public final class BinaryProtocol {

    private BinaryProtocol() {
    }

    public static byte[] toBytes(int[] values) {
        ByteBuffer buf = ByteBuffer.allocate(values.length * 4)
                .order(ByteOrder.LITTLE_ENDIAN);
        for (int v : values) {
            buf.putInt(v);
        }
        return buf.array();
    }

    public static byte[] toBytes(long[] values) {
        ByteBuffer buf = ByteBuffer.allocate(values.length * 8)
                .order(ByteOrder.LITTLE_ENDIAN);
        for (long v : values) {
            buf.putLong(v);
        }
        return buf.array();
    }

    public static byte[] toBytes(float[] values) {
        ByteBuffer buf = ByteBuffer.allocate(values.length * 4)
                .order(ByteOrder.LITTLE_ENDIAN);
        for (float v : values) {
            buf.putFloat(v);
        }
        return buf.array();
    }

    public static byte[] toBytes(double[] values) {
        ByteBuffer buf = ByteBuffer.allocate(values.length * 8)
                .order(ByteOrder.LITTLE_ENDIAN);
        for (double v : values) {
            buf.putDouble(v);
        }
        return buf.array();
    }

    public static byte[] toBytes(boolean[] values) {
        byte[] out = new byte[values.length];
        for (int i = 0; i < values.length; i++) {
            out[i] = (byte) (values[i] ? 1 : 0);
        }
        return out;
    }

    /** BYTES tensor: each element is 4-byte LE length + UTF-8 payload. */
    public static byte[] toBytes(String[] values) {
        ByteArrayOutputStream out = new ByteArrayOutputStream();
        for (String s : values) {
            byte[] payload = s.getBytes(StandardCharsets.UTF_8);
            ByteBuffer len = ByteBuffer.allocate(4)
                    .order(ByteOrder.LITTLE_ENDIAN).putInt(payload.length);
            out.writeBytes(len.array());
            out.writeBytes(payload);
        }
        return out.toByteArray();
    }

    public static int[] toIntArray(byte[] data) {
        ByteBuffer buf = ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
        int[] out = new int[data.length / 4];
        for (int i = 0; i < out.length; i++) {
            out[i] = buf.getInt();
        }
        return out;
    }

    public static long[] toLongArray(byte[] data) {
        ByteBuffer buf = ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
        long[] out = new long[data.length / 8];
        for (int i = 0; i < out.length; i++) {
            out[i] = buf.getLong();
        }
        return out;
    }

    public static float[] toFloatArray(byte[] data) {
        ByteBuffer buf = ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
        float[] out = new float[data.length / 4];
        for (int i = 0; i < out.length; i++) {
            out[i] = buf.getFloat();
        }
        return out;
    }

    public static double[] toDoubleArray(byte[] data) {
        ByteBuffer buf = ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
        double[] out = new double[data.length / 8];
        for (int i = 0; i < out.length; i++) {
            out[i] = buf.getDouble();
        }
        return out;
    }

    /** Decodes a BYTES tensor payload into its string elements. */
    public static String[] toStringArray(byte[] data)
            throws InferenceException {
        ByteBuffer buf = ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
        List<String> out = new ArrayList<>();
        while (buf.remaining() >= 4) {
            int len = buf.getInt();
            if (len < 0 || len > buf.remaining()) {
                throw new InferenceException(
                        "malformed BYTES tensor: element length " + len);
            }
            byte[] payload = new byte[len];
            buf.get(payload);
            out.add(new String(payload, StandardCharsets.UTF_8));
        }
        if (buf.remaining() != 0) {
            throw new InferenceException(
                    "malformed BYTES tensor: trailing bytes");
        }
        return out.toArray(new String[0]);
    }
}
