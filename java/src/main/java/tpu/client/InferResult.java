package tpu.client;

import java.nio.charset.StandardCharsets;
import java.util.Arrays;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * Inference response: JSON head sized by Inference-Header-Content-Length,
 * followed by concatenated binary output tails addressed in declaration
 * order (reference InferResult.java:293 split-body parsing; wire contract
 * identical to http_client.cc:752-835).
 */
public class InferResult {

    private final Map<String, Object> head;
    private final Map<String, IOTensor> tensors = new LinkedHashMap<>();
    private final Map<String, byte[]> binary = new LinkedHashMap<>();
    private final Map<String, List<Object>> jsonData = new LinkedHashMap<>();

    @SuppressWarnings("unchecked")
    public InferResult(byte[] body, int headerLength)
            throws InferenceException {
        int headLen = headerLength > 0 ? headerLength : body.length;
        String headText =
                new String(body, 0, headLen, StandardCharsets.UTF_8);
        head = Json.parseObject(headText);

        int offset = headLen;
        Object outputs = head.get("outputs");
        if (!(outputs instanceof List)) {
            return;
        }
        for (Object entry : (List<Object>) outputs) {
            Map<String, Object> out = (Map<String, Object>) entry;
            String name = (String) out.get("name");
            String datatype = (String) out.get("datatype");
            List<Object> shapeList = (List<Object>) out.get("shape");
            long[] shape = new long[shapeList.size()];
            for (int i = 0; i < shape.length; i++) {
                shape[i] = ((Number) shapeList.get(i)).longValue();
            }
            tensors.put(name, new IOTensor(name, datatype, shape));

            Map<String, Object> params =
                    (Map<String, Object>) out.get("parameters");
            Long binSize = null;
            if (params != null && params.get("binary_data_size") != null) {
                binSize = ((Number) params.get("binary_data_size"))
                        .longValue();
            }
            if (binSize != null) {
                if (offset + binSize > body.length) {
                    throw new InferenceException(
                            "binary tail overruns body for '" + name + "'");
                }
                binary.put(name, Arrays.copyOfRange(
                        body, offset, offset + binSize.intValue()));
                offset += binSize.intValue();
            } else if (out.get("data") instanceof List) {
                jsonData.put(name, (List<Object>) out.get("data"));
            }
        }
    }

    public String getModelName() {
        return (String) head.get("model_name");
    }

    public String getId() {
        return (String) head.get("id");
    }

    public IOTensor getOutput(String name) {
        return tensors.get(name);
    }

    /** Raw little-endian bytes of a binary output (null if JSON/shm). */
    public byte[] getRawOutput(String name) {
        return binary.get(name);
    }

    public int[] getOutputAsInt(String name) throws InferenceException {
        byte[] raw = binary.get(name);
        if (raw != null) {
            return BinaryProtocol.toIntArray(raw);
        }
        List<Object> data = jsonDataFor(name);
        int[] out = new int[data.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = ((Number) data.get(i)).intValue();
        }
        return out;
    }

    public long[] getOutputAsLong(String name) throws InferenceException {
        byte[] raw = binary.get(name);
        if (raw != null) {
            return BinaryProtocol.toLongArray(raw);
        }
        List<Object> data = jsonDataFor(name);
        long[] out = new long[data.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = ((Number) data.get(i)).longValue();
        }
        return out;
    }

    public float[] getOutputAsFloat(String name) throws InferenceException {
        byte[] raw = binary.get(name);
        if (raw != null) {
            return BinaryProtocol.toFloatArray(raw);
        }
        List<Object> data = jsonDataFor(name);
        float[] out = new float[data.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = ((Number) data.get(i)).floatValue();
        }
        return out;
    }

    public double[] getOutputAsDouble(String name) throws InferenceException {
        byte[] raw = binary.get(name);
        if (raw != null) {
            return BinaryProtocol.toDoubleArray(raw);
        }
        List<Object> data = jsonDataFor(name);
        double[] out = new double[data.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = ((Number) data.get(i)).doubleValue();
        }
        return out;
    }

    public String[] getOutputAsString(String name) throws InferenceException {
        byte[] raw = binary.get(name);
        if (raw != null) {
            return BinaryProtocol.toStringArray(raw);
        }
        List<Object> data = jsonDataFor(name);
        String[] out = new String[data.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = String.valueOf(data.get(i));
        }
        return out;
    }

    private List<Object> jsonDataFor(String name) throws InferenceException {
        List<Object> data = jsonData.get(name);
        if (data == null) {
            throw new InferenceException("output '" + name
                    + "' has no inline data (shared memory?)");
        }
        return data;
    }
}
