package tpu.client.examples;

import java.util.List;

import tpu.client.DataType;
import tpu.client.InferInput;
import tpu.client.InferRequestedOutput;
import tpu.client.InferenceServerClient;

/**
 * Heap-stability loop (reference MemoryGrowthTest.java): many inferences
 * while sampling used heap; fails when growth exceeds the bound after
 * steady state.
 */
public final class MemoryGrowthTest {

    private MemoryGrowthTest() {
    }

    private static long usedHeap() {
        Runtime rt = Runtime.getRuntime();
        return rt.totalMemory() - rt.freeMemory();
    }

    public static void main(String[] args) throws Exception {
        String url = args.length > 0 ? args[0] : "http://localhost:8000";
        int iterations = args.length > 1 ? Integer.parseInt(args[1]) : 1000;
        long maxGrowthBytes = 64L * 1024 * 1024;

        try (InferenceServerClient client = new InferenceServerClient(url)) {
            int[] a = new int[16];
            int[] b = new int[16];
            for (int i = 0; i < 16; i++) {
                a[i] = i;
                b[i] = 1;
            }
            InferInput input0 = new InferInput("INPUT0", new long[]{1, 16},
                    DataType.INT32);
            InferInput input1 = new InferInput("INPUT1", new long[]{1, 16},
                    DataType.INT32);
            input0.setData(a);
            input1.setData(b);
            List<InferInput> inputs = List.of(input0, input1);
            List<InferRequestedOutput> outputs =
                    List.of(new InferRequestedOutput("OUTPUT0"));

            for (int i = 0; i < 100; i++) {
                client.infer("simple", inputs, outputs);
            }
            System.gc();
            long base = usedHeap();
            for (int i = 0; i < iterations; i++) {
                client.infer("simple", inputs, outputs);
                if (i % 200 == 0) {
                    System.out.printf("iter %d: heap %d MB%n", i,
                            usedHeap() >> 20);
                }
            }
            System.gc();
            long growth = usedHeap() - base;
            System.out.printf("Heap growth over %d inferences: %d MB%n",
                    iterations, growth >> 20);
            if (growth > maxGrowthBytes) {
                System.err.println("FAIL: heap growth exceeds bound");
                System.exit(1);
            }
            System.out.println("PASS: MemoryGrowthTest");
        }
    }
}
