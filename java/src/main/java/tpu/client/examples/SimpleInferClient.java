package tpu.client.examples;

import java.util.List;

import tpu.client.InferInput;
import tpu.client.InferRequestedOutput;
import tpu.client.InferResult;
import tpu.client.InferenceServerClient;
import tpu.client.DataType;

/**
 * Value-asserting add/sub conformance client (reference
 * SimpleInferClient.java, SURVEY.md §2.5): INT32[1,16] through `simple`,
 * OUTPUT0=a+b and OUTPUT1=a-b checked elementwise.
 */
public final class SimpleInferClient {

    private SimpleInferClient() {
    }

    public static void main(String[] args) throws Exception {
        String url = args.length > 0 ? args[0] : "http://localhost:8000";
        try (InferenceServerClient client = new InferenceServerClient(url)) {
            if (!client.isServerLive()) {
                throw new IllegalStateException("server not live");
            }

            int[] a = new int[16];
            int[] b = new int[16];
            for (int i = 0; i < 16; i++) {
                a[i] = i;
                b[i] = 1;
            }
            InferInput input0 =
                    new InferInput("INPUT0", new long[]{1, 16},
                            DataType.INT32);
            InferInput input1 =
                    new InferInput("INPUT1", new long[]{1, 16},
                            DataType.INT32);
            input0.setData(a);
            input1.setData(b);

            InferResult result = client.infer("simple",
                    List.of(input0, input1),
                    List.of(new InferRequestedOutput("OUTPUT0"),
                            new InferRequestedOutput("OUTPUT1")),
                    "1");

            int[] sum = result.getOutputAsInt("OUTPUT0");
            int[] diff = result.getOutputAsInt("OUTPUT1");
            for (int i = 0; i < 16; i++) {
                if (sum[i] != a[i] + b[i] || diff[i] != a[i] - b[i]) {
                    System.err.println("mismatch at " + i + ": " + sum[i]
                            + " / " + diff[i]);
                    System.exit(1);
                }
            }
            System.out.println("PASS: SimpleInferClient");
        }
    }
}
