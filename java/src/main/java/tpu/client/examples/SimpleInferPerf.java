package tpu.client.examples;

import java.util.ArrayList;
import java.util.Collections;
import java.util.List;
import java.util.concurrent.CompletableFuture;

import tpu.client.DataType;
import tpu.client.InferInput;
import tpu.client.InferRequestedOutput;
import tpu.client.InferResult;
import tpu.client.InferenceServerClient;

/**
 * Latency/throughput micro-benchmark (reference SimpleInferPerf.java):
 * fixed request count with bounded async concurrency; prints throughput
 * and latency percentiles.
 */
public final class SimpleInferPerf {

    private SimpleInferPerf() {
    }

    public static void main(String[] args) throws Exception {
        String url = args.length > 0 ? args[0] : "http://localhost:8000";
        int requests = args.length > 1 ? Integer.parseInt(args[1]) : 200;
        int concurrency = args.length > 2 ? Integer.parseInt(args[2]) : 4;

        try (InferenceServerClient client = new InferenceServerClient(url)) {
            int[] a = new int[16];
            int[] b = new int[16];
            for (int i = 0; i < 16; i++) {
                a[i] = i;
                b[i] = 2;
            }
            InferInput input0 = new InferInput("INPUT0", new long[]{1, 16},
                    DataType.INT32);
            InferInput input1 = new InferInput("INPUT1", new long[]{1, 16},
                    DataType.INT32);
            input0.setData(a);
            input1.setData(b);
            List<InferInput> inputs = List.of(input0, input1);
            List<InferRequestedOutput> outputs =
                    List.of(new InferRequestedOutput("OUTPUT0"));

            // warmup
            for (int i = 0; i < 10; i++) {
                client.infer("simple", inputs, outputs);
            }

            // Latencies come back as dependent futures joined explicitly —
            // collecting them in callbacks would race the final sort.
            List<CompletableFuture<Long>> latencyFutures = new ArrayList<>();
            long start = System.nanoTime();
            List<CompletableFuture<Long>> inflight = new ArrayList<>();
            for (int i = 0; i < requests; i++) {
                long t0 = System.nanoTime();
                CompletableFuture<Long> lat =
                        client.asyncInfer("simple", inputs, outputs)
                                .thenApply(r ->
                                        (System.nanoTime() - t0) / 1000);
                latencyFutures.add(lat);
                inflight.add(lat);
                if (inflight.size() >= concurrency) {
                    CompletableFuture.anyOf(
                            inflight.toArray(new CompletableFuture[0])).join();
                    inflight.removeIf(CompletableFuture::isDone);
                }
            }
            List<Long> sorted = new ArrayList<>();
            for (CompletableFuture<Long> lat : latencyFutures) {
                sorted.add(lat.join());
            }
            double seconds = (System.nanoTime() - start) / 1e9;
            Collections.sort(sorted);
            System.out.printf("Requests: %d, concurrency %d%n", requests,
                    concurrency);
            System.out.printf("Throughput: %.1f infer/sec%n",
                    requests / seconds);
            System.out.printf("Latency p50/p90/p99: %d / %d / %d usec%n",
                    sorted.get(sorted.size() / 2),
                    sorted.get(sorted.size() * 9 / 10),
                    sorted.get(Math.max(0, sorted.size() * 99 / 100 - 1)));
        }
    }
}
