package tpu.client;

import java.time.Duration;

/**
 * Client transport knobs (reference HttpConfig,
 * InferenceServerClient.java:76-167: ioThreads/timeouts/keepalive). The
 * JDK HttpClient manages its own IO threads and keep-alive pool, so the
 * surviving knobs are the timeouts and async concurrency.
 */
public class HttpConfig {
    private Duration connectTimeout = Duration.ofSeconds(10);
    private Duration requestTimeout = Duration.ofSeconds(120);
    private int maxAsyncRequests = 8;

    public Duration getConnectTimeout() {
        return connectTimeout;
    }

    public HttpConfig setConnectTimeout(Duration timeout) {
        this.connectTimeout = timeout;
        return this;
    }

    public Duration getRequestTimeout() {
        return requestTimeout;
    }

    public HttpConfig setRequestTimeout(Duration timeout) {
        this.requestTimeout = timeout;
        return this;
    }

    public int getMaxAsyncRequests() {
        return maxAsyncRequests;
    }

    public HttpConfig setMaxAsyncRequests(int n) {
        this.maxAsyncRequests = n;
        return this;
    }
}
