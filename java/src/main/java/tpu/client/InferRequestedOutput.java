package tpu.client;

import java.util.LinkedHashMap;
import java.util.Map;

/**
 * Requested output: binary placement by default, optional classification
 * extension or shared-memory placement (reference InferRequestedOutput
 * semantics, common.h:359-431 wire shape).
 */
public class InferRequestedOutput {
    private final String name;
    private final boolean binaryData;
    private final int classCount;
    private String shmRegion;
    private long shmByteSize;
    private long shmOffset;

    public InferRequestedOutput(String name) {
        this(name, true, 0);
    }

    public InferRequestedOutput(String name, boolean binaryData,
                                int classCount) {
        this.name = name;
        this.binaryData = binaryData;
        this.classCount = classCount;
    }

    public String getName() {
        return name;
    }

    public void setSharedMemory(String regionName, long byteSize,
                                long offset) {
        this.shmRegion = regionName;
        this.shmByteSize = byteSize;
        this.shmOffset = offset;
    }

    Map<String, Object> toJson() {
        Map<String, Object> out = new LinkedHashMap<>();
        out.put("name", name);
        Map<String, Object> params = new LinkedHashMap<>();
        if (shmRegion != null) {
            params.put("shared_memory_region", shmRegion);
            params.put("shared_memory_byte_size", shmByteSize);
            if (shmOffset != 0) {
                params.put("shared_memory_offset", shmOffset);
            }
        } else {
            if (binaryData) {
                params.put("binary_data", Boolean.TRUE);
            }
            if (classCount > 0) {
                params.put("classification", (long) classCount);
            }
        }
        if (!params.isEmpty()) {
            out.put("parameters", params);
        }
        return out;
    }
}
