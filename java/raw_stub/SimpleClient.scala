// Raw generated-stub client for the v2 gRPC inference service, in Scala.
//
// Counterpart of the reference's SimpleClient.scala
// (/root/reference/src/grpc_generated/java/.../SimpleClient.scala:292):
// the same protoc/grpc-java generated classes the Java client uses (Scala
// interoperates directly), manual little-endian INT32 framing, add/sub
// value assertions against the `simple` model.
//
// Toolchain caveat: no JDK/scalac in this build image; structure-checked in
// CI (tests/test_langs.py), builds with sbt/scalac where available.

package tpu.rawstub

import com.google.protobuf.ByteString

import inference.GRPCInferenceServiceGrpc
import inference.GrpcService.{ModelInferRequest, ModelInferResponse}

import io.grpc.ManagedChannelBuilder

import java.nio.{ByteBuffer, ByteOrder}

object SimpleClient {

  def main(args: Array[String]): Unit = {
    val host = if (args.length > 0) args(0) else "localhost"
    val port = if (args.length > 1) args(1).toInt else 8001

    val channel =
      ManagedChannelBuilder.forAddress(host, port).usePlaintext().build()
    val stub = GRPCInferenceServiceGrpc.newBlockingStub(channel)

    val input0 = Array.tabulate(16)(i => i)
    val input1 = Array.fill(16)(1)

    val in0 = ModelInferRequest.InferInputTensor
      .newBuilder()
      .setName("INPUT0")
      .setDatatype("INT32")
      .addShape(1)
      .addShape(16)
    val in1 = ModelInferRequest.InferInputTensor
      .newBuilder()
      .setName("INPUT1")
      .setDatatype("INT32")
      .addShape(1)
      .addShape(16)

    val request = ModelInferRequest
      .newBuilder()
      .setModelName("simple")
      .setId("scala-raw-stub")
      .addInputs(in0)
      .addInputs(in1)
      .addRawInputContents(toLittleEndian(input0))
      .addRawInputContents(toLittleEndian(input1))
      .addOutputs(
        ModelInferRequest.InferRequestedOutputTensor
          .newBuilder()
          .setName("OUTPUT0"))
      .addOutputs(
        ModelInferRequest.InferRequestedOutputTensor
          .newBuilder()
          .setName("OUTPUT1"))
      .build()

    val response: ModelInferResponse = stub.modelInfer(request)

    val output0 = fromLittleEndian(response.getRawOutputContents(0))
    val output1 = fromLittleEndian(response.getRawOutputContents(1))
    for (i <- 0 until 16) {
      require(
        output0(i) == input0(i) + input1(i),
        s"sum mismatch at $i: ${output0(i)}")
      require(
        output1(i) == input0(i) - input1(i),
        s"diff mismatch at $i: ${output1(i)}")
      println(
        s"${input0(i)} + ${input1(i)} = ${output0(i)} ; " +
          s"${input0(i)} - ${input1(i)} = ${output1(i)}")
    }
    println("PASS: scala raw stub")
    channel.shutdownNow()
  }

  def toLittleEndian(values: Array[Int]): ByteString = {
    val buf =
      ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN)
    values.foreach(buf.putInt)
    buf.flip()
    ByteString.copyFrom(buf)
  }

  def fromLittleEndian(data: ByteString): Array[Int] = {
    val buf = data.asReadOnlyByteBuffer().order(ByteOrder.LITTLE_ENDIAN)
    Array.fill(buf.remaining() / 4)(buf.getInt)
  }
}
