// Raw generated-stub client for the v2 gRPC inference service.
//
// Counterpart of the reference's SimpleJavaClient
// (/root/reference/src/grpc_generated/java/.../SimpleJavaClient.java:160):
// no client library — just the protoc/grpc-java generated classes (see
// gen_java_stubs.sh), manual little-endian (de)serialization of INT32
// tensors through raw_input_contents, and an element-wise add/sub check
// against the `simple` model.
//
// Toolchain caveat: this build image carries no JDK or grpc-java plugin;
// the source is structure-checked in CI (tests/test_langs.py) and compiles
// with `mvn package` wherever a JDK 11+ toolchain exists.

package tpu.rawstub;

import com.google.protobuf.ByteString;

import inference.GRPCInferenceServiceGrpc;
import inference.GrpcService.InferTensorContents;
import inference.GrpcService.ModelInferRequest;
import inference.GrpcService.ModelInferResponse;

import io.grpc.ManagedChannel;
import io.grpc.ManagedChannelBuilder;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public class SimpleJavaClient {

  public static void main(String[] args) {
    String host = args.length > 0 ? args[0] : "localhost";
    int port = args.length > 1 ? Integer.parseInt(args[1]) : 8001;

    ManagedChannel channel = ManagedChannelBuilder
        .forAddress(host, port).usePlaintext().build();
    GRPCInferenceServiceGrpc.GRPCInferenceServiceBlockingStub stub =
        GRPCInferenceServiceGrpc.newBlockingStub(channel);

    int[] input0 = new int[16];
    int[] input1 = new int[16];
    for (int i = 0; i < 16; i++) {
      input0[i] = i;
      input1[i] = 1;
    }

    ModelInferRequest.InferInputTensor.Builder in0 =
        ModelInferRequest.InferInputTensor.newBuilder()
            .setName("INPUT0").setDatatype("INT32")
            .addShape(1).addShape(16);
    ModelInferRequest.InferInputTensor.Builder in1 =
        ModelInferRequest.InferInputTensor.newBuilder()
            .setName("INPUT1").setDatatype("INT32")
            .addShape(1).addShape(16);

    ModelInferRequest request = ModelInferRequest.newBuilder()
        .setModelName("simple")
        .setId("java-raw-stub")
        .addInputs(in0).addInputs(in1)
        .addRawInputContents(toLittleEndian(input0))
        .addRawInputContents(toLittleEndian(input1))
        .addOutputs(ModelInferRequest.InferRequestedOutputTensor
            .newBuilder().setName("OUTPUT0"))
        .addOutputs(ModelInferRequest.InferRequestedOutputTensor
            .newBuilder().setName("OUTPUT1"))
        .build();

    ModelInferResponse response = stub.modelInfer(request);

    int[] output0 = fromLittleEndian(response.getRawOutputContents(0));
    int[] output1 = fromLittleEndian(response.getRawOutputContents(1));
    for (int i = 0; i < 16; i++) {
      if (output0[i] != input0[i] + input1[i]
          || output1[i] != input0[i] - input1[i]) {
        System.err.println("error: mismatch at " + i);
        System.exit(1);
      }
      System.out.println(input0[i] + " + " + input1[i] + " = " + output0[i]
          + " ; " + input0[i] + " - " + input1[i] + " = " + output1[i]);
    }
    System.out.println("PASS: java raw stub");
    channel.shutdownNow();
  }

  // v2 raw tensor framing is packed little-endian bytes.
  static ByteString toLittleEndian(int[] values) {
    ByteBuffer buf = ByteBuffer.allocate(values.length * 4)
        .order(ByteOrder.LITTLE_ENDIAN);
    for (int v : values) {
      buf.putInt(v);
    }
    buf.flip();
    return ByteString.copyFrom(buf);
  }

  static int[] fromLittleEndian(ByteString data) {
    ByteBuffer buf = data.asReadOnlyByteBuffer()
        .order(ByteOrder.LITTLE_ENDIAN);
    int[] out = new int[buf.remaining() / 4];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getInt();
    }
    return out;
  }
}
