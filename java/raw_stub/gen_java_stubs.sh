#!/bin/bash
# Generates Java gRPC stubs for the v2 inference service from the proto
# shared with the Python/C++/Go stacks (reference: the grpc_generated/java
# library pom protoc-compiles protos dropped into library/src/main/proto,
# /root/reference/src/grpc_generated/java/README.md:149).
#
# Requires protoc with the protoc-gen-grpc-java plugin (not in this build
# image — see README.md for the toolchain caveat).
set -euo pipefail
cd "$(dirname "$0")"
mkdir -p inference
protoc \
  -I ../../client_tpu/protocol/protos \
  --java_out=inference \
  --grpc-java_out=inference \
  grpc_service.proto
echo "stubs written to java/raw_stub/inference/"
