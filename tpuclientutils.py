"""Deprecated alias for :mod:`client_tpu.utils`.

Compat-shim pattern of the reference's tritonclientutils module.
"""

import warnings

from client_tpu.utils import *  # noqa: F401,F403

warnings.warn(
    "tpuclientutils is deprecated; import client_tpu.utils instead",
    DeprecationWarning, stacklevel=2)
