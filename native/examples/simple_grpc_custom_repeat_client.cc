// Decoupled-model conformance client: one request to `simple_repeat`
// produces N ordered responses on the bidi stream.
//
// Reference counterpart: simple_grpc_custom_repeat_client
// (/root/reference/src/c++/examples/, the custom repeat/decoupled model
// flow): a repeat model with a decoupled transaction policy answers a single
// request with one response per input element, then an empty final-flagged
// response. Exit 0 only if all N values arrive in order.
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <iostream>
#include <mutex>
#include <vector>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int repeat = 4;
  int opt;
  while ((opt = getopt(argc, argv, "u:n:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'n') repeat = atoi(optarg);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  if (!tc::InferenceServerGrpcClient::Create(&client, url).IsOk()) return 1;

  std::mutex mtx;
  std::condition_variable cv;
  std::vector<int32_t> got;
  bool done = false, stream_error = false;

  tc::Error err = client->StartStream([&](tc::InferResult* result) {
    std::unique_ptr<tc::InferResult> owner(result);
    std::lock_guard<std::mutex> lk(mtx);
    if (!result->RequestStatus().IsOk()) {
      std::cerr << "stream response error: " << result->RequestStatus()
                << std::endl;
      stream_error = true;
    } else {
      const uint8_t* buf;
      size_t sz;
      if (result->RawData("OUT", &buf, &sz).IsOk() && sz == sizeof(int32_t)) {
        got.push_back(*reinterpret_cast<const int32_t*>(buf));
      } else {
        // Empty response: the decoupled stream's final-flag terminator.
        done = true;
      }
    }
    cv.notify_all();
  });
  if (!err.IsOk()) {
    std::cerr << "StartStream failed: " << err << std::endl;
    return 1;
  }

  std::vector<int32_t> values(repeat);
  for (int i = 0; i < repeat; ++i) values[i] = i * 11;

  tc::InferInput* input;
  tc::InferInput::Create(&input, "IN", {repeat}, "INT32");
  std::unique_ptr<tc::InferInput> owner_in(input);
  input->AppendRaw(reinterpret_cast<uint8_t*>(values.data()),
                   values.size() * sizeof(int32_t));

  tc::InferOptions options("simple_repeat");
  options.request_id = "r1";
  tc::Error serr = client->AsyncStreamInfer(options, {input});
  if (!serr.IsOk()) {
    std::cerr << "AsyncStreamInfer failed: " << serr << std::endl;
    return 1;
  }

  {
    std::unique_lock<std::mutex> lk(mtx);
    if (!cv.wait_for(lk, std::chrono::seconds(60), [&] {
          return stream_error ||
                 (got.size() >= size_t(repeat) && done);
        })) {
      std::cerr << "error: timed out (" << got.size() << "/" << repeat
                << " responses, final=" << done << ")" << std::endl;
      return 1;
    }
    if (stream_error) return 1;
    if (got.size() != size_t(repeat)) {
      std::cerr << "error: " << got.size() << " responses, expected "
                << repeat << std::endl;
      return 1;
    }
    for (int i = 0; i < repeat; ++i) {
      if (got[i] != values[i]) {
        std::cerr << "error: response " << i << " = " << got[i]
                  << ", expected " << values[i] << std::endl;
        return 1;
      }
    }
  }
  client->StopStream();

  std::cout << "PASS : decoupled repeat (" << repeat
            << " responses from one request)" << std::endl;
  return 0;
}
