// gRPC keepalive conformance client.
//
// Counterpart of the reference's simple_grpc_keepalive_client
// (/root/reference/src/c++/examples/simple_grpc_keepalive_client.cc):
// creates a channel with aggressive KeepAliveOptions, idles across several
// ping periods, then infers — proving the transport-level PING/ack cycle
// keeps the connection healthy instead of letting it rot. Exit 0 only if
// the post-idle inference round-trips with correct values.
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <iostream>
#include <thread>
#include <vector>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                            \
  do {                                                                 \
    tc::Error err__ = (X);                                             \
    if (!err__.IsOk()) {                                               \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl;   \
      exit(1);                                                         \
    }                                                                  \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  bool verbose = false;
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'u':
        url = optarg;
        break;
      case 'v':
        verbose = true;
        break;
      default:
        std::cerr << "usage: " << argv[0] << " [-v] [-u host:port]"
                  << std::endl;
        return 2;
    }
  }

  // Reference values: keepalive_time 1s, timeout 1s, ping when idle
  // (permit_without_calls), unlimited data-less pings.
  tc::KeepAliveOptions keepalive;
  keepalive.keepalive_time_ms = 1000;
  keepalive.keepalive_timeout_ms = 1000;
  keepalive.keepalive_permit_without_calls = true;
  keepalive.http2_max_pings_without_data = 0;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  // Dedicated (uncached) channel so this client's keepalive cadence can't
  // leak into other tests' shared channel.
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(
                  &client, url, verbose, /*use_cached_channel=*/false,
                  /*use_ssl=*/false, tc::SslOptions(), keepalive),
              "unable to create keepalive client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live check");
  if (!live) {
    std::cerr << "error: server not live" << std::endl;
    return 1;
  }

  // Idle across ~3 ping periods: with keepalive_time_ms=1000 the transport
  // must exchange PINGs during this window or fail the connection.
  std::this_thread::sleep_for(std::chrono::milliseconds(3200));

  std::vector<int32_t> in0(16), in1(16);
  for (int i = 0; i < 16; ++i) {
    in0[i] = i;
    in1[i] = 2;
  }
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
              "create INPUT0");
  FAIL_IF_ERR(tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
              "create INPUT1");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  FAIL_IF_ERR(input0->AppendRaw(reinterpret_cast<uint8_t*>(in0.data()),
                                in0.size() * sizeof(int32_t)),
              "INPUT0 data");
  FAIL_IF_ERR(input1->AppendRaw(reinterpret_cast<uint8_t*>(in1.data()),
                                in1.size() * sizeof(int32_t)),
              "INPUT1 data");

  tc::InferOptions options("simple");
  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, {input0, input1}),
              "infer after idle");
  std::unique_ptr<tc::InferResult> owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  const uint8_t* buf;
  size_t n;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &n), "OUTPUT0 data");
  const int32_t* vals = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (vals[i] != in0[i] + in1[i]) {
      std::cerr << "error: OUTPUT0[" << i << "] = " << vals[i] << ", expected "
                << in0[i] + in1[i] << std::endl;
      return 1;
    }
  }
  std::cout << "PASS : keepalive" << std::endl;
  return 0;
}
