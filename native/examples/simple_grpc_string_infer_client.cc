// BYTES (string tensor) conformance client over gRPC.
//
// Reference counterpart: simple_grpc_string_infer_client.cc (§2.7) — sends
// decimal strings through the 4-byte-LE-length-prefixed BYTES codec to the
// `simple_string` model and validates the summed/subtracted string results.
#include <unistd.h>

#include <iostream>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  if (!tc::InferenceServerGrpcClient::Create(&client, url).IsOk()) return 1;

  std::vector<std::string> in0, in1;
  for (int i = 0; i < 16; ++i) {
    in0.push_back(std::to_string(i));
    in1.push_back(std::to_string(1));
  }

  tc::InferInput *input0, *input1;
  tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "BYTES");
  tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "BYTES");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  input0->AppendFromString(in0);
  input1->AppendFromString(in1);

  tc::InferOptions options("simple_string");
  tc::InferResult* result;
  tc::Error err = client->Infer(&result, options, {input0, input1});
  if (!err.IsOk()) {
    std::cerr << "infer failed: " << err << std::endl;
    return 1;
  }
  std::unique_ptr<tc::InferResult> owner(result);
  if (!result->RequestStatus().IsOk()) {
    std::cerr << "request failed: " << result->RequestStatus() << std::endl;
    return 1;
  }

  for (const auto& check :
       {std::make_pair(std::string("OUTPUT0"), +1),
        std::make_pair(std::string("OUTPUT1"), -1)}) {
    std::vector<std::string> values;
    if (!result->StringData(check.first, &values).IsOk() ||
        values.size() != 16) {
      std::cerr << "bad " << check.first << std::endl;
      return 1;
    }
    for (int i = 0; i < 16; ++i) {
      int expect = i + check.second * 1;
      if (atoi(values[i].c_str()) != expect) {
        std::cerr << "error: " << check.first << "[" << i
                  << "] = " << values[i] << ", expected " << expect
                  << std::endl;
        return 1;
      }
    }
  }
  std::cout << "PASS : simple_grpc_string_infer_client" << std::endl;
  return 0;
}
