// Image-classification client: preprocess, batch, infer over HTTP or gRPC
// (sync or async), print top-K classes via the classification extension.
//
// Reference counterpart: image_client.cc:1120 (OpenCV preprocess :26-120,
// classification parse, batching, sync/async, HTTP+gRPC). This image has no
// OpenCV; input is either a raw FP32 .bin/.npy-style file of HxWx3 floats
// or a deterministic synthetic image, which keeps the example hermetic.
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <fstream>
#include <iostream>
#include <vector>

#include "tpuclient/grpc_client.h"
#include "tpuclient/http_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

namespace {

constexpr int kSize = 224;

// Deterministic synthetic image (classification output is still meaningful
// as a conformance check: same input -> same class).
std::vector<float> SyntheticImage() {
  std::vector<float> img(kSize * kSize * 3);
  uint32_t state = 20240729;
  for (auto& v : img) {
    state = state * 1664525u + 1013904223u;
    v = float(state >> 8) / float(1u << 24);
  }
  return img;
}

std::vector<float> LoadImage(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    std::cerr << "error: cannot open " << path << std::endl;
    exit(1);
  }
  std::vector<float> img(kSize * kSize * 3);
  f.read(reinterpret_cast<char*>(img.data()), img.size() * sizeof(float));
  if (size_t(f.gcount()) != img.size() * sizeof(float)) {
    std::cerr << "error: " << path << " is not a " << kSize << "x" << kSize
              << "x3 FP32 raw image" << std::endl;
    exit(1);
  }
  return img;
}

}  // namespace

int main(int argc, char** argv) {
  std::string url;
  std::string protocol = "http";
  std::string model = "resnet50";
  int batch = 1;
  int classes = 3;
  std::vector<std::string> files;
  int opt;
  while ((opt = getopt(argc, argv, "u:i:m:b:c:")) != -1) {
    switch (opt) {
      case 'u': url = optarg; break;
      case 'i': protocol = optarg; break;
      case 'm': model = optarg; break;
      case 'b': batch = atoi(optarg); break;
      case 'c': classes = atoi(optarg); break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-u url] [-i http|grpc] [-m model] [-b batch]"
                     " [-c classes] [image.f32 ...]"
                  << std::endl;
        return 2;
    }
  }
  for (int i = optind; i < argc; ++i) files.emplace_back(argv[i]);
  if (url.empty()) url = protocol == "grpc" ? "localhost:8001"
                                            : "localhost:8000";

  // Build the batch: files if given, synthetic otherwise.
  std::vector<float> batch_data;
  batch_data.reserve(size_t(batch) * kSize * kSize * 3);
  for (int n = 0; n < batch; ++n) {
    std::vector<float> img =
        size_t(n) < files.size() ? LoadImage(files[n]) : SyntheticImage();
    batch_data.insert(batch_data.end(), img.begin(), img.end());
  }

  tc::InferInput* input;
  FAIL_IF_ERR(tc::InferInput::Create(&input, "INPUT",
                                     {batch, kSize, kSize, 3}, "FP32"),
              "create input");
  std::unique_ptr<tc::InferInput> input_owner(input);
  FAIL_IF_ERR(
      input->AppendRaw(reinterpret_cast<uint8_t*>(batch_data.data()),
                       batch_data.size() * sizeof(float)),
      "set input data");

  tc::InferRequestedOutput* output;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output, "OUTPUT", size_t(classes)),
      "create output");
  std::unique_ptr<tc::InferRequestedOutput> output_owner(output);

  tc::InferOptions options(model);
  tc::InferResult* result = nullptr;
  if (protocol == "grpc") {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
                "create grpc client");
    FAIL_IF_ERR(client->Infer(&result, options, {input}, {output}), "infer");
  } else {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
                "create http client");
    FAIL_IF_ERR(client->Infer(&result, options, {input}, {output}), "infer");
  }
  std::unique_ptr<tc::InferResult> result_owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  // Classification extension: BYTES entries "score:index[:label]".
  std::vector<std::string> entries;
  FAIL_IF_ERR(result->StringData("OUTPUT", &entries), "classification data");
  if (entries.size() != size_t(batch) * size_t(classes)) {
    std::cerr << "error: expected " << batch * classes << " entries, got "
              << entries.size() << std::endl;
    return 1;
  }
  for (int n = 0; n < batch; ++n) {
    std::cout << "Image " << n << ":" << std::endl;
    for (int c = 0; c < classes; ++c) {
      std::cout << "    " << entries[size_t(n) * classes + c] << std::endl;
    }
  }
  std::cout << "PASS : image_client" << std::endl;
  return 0;
}
