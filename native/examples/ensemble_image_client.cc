// Ensemble pipeline conformance client: raw HxWx3 bytes through the
// image_preprocess → resnet50 ensemble in one request.
//
// Reference counterpart: ensemble_image_client.cc
// (/root/reference/src/c++/examples/ensemble_image_client.cc:365) — there,
// OpenCV-decoded images into the preprocess+inception ensemble; here a
// deterministic synthetic image (no OpenCV in the dependency-free tree), the
// same single-request many-model flow, asserting a full finite logits
// vector comes back.
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <iostream>
#include <vector>

#include "tpuclient/http_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
              "create client");

  // Deterministic synthetic 480x640 RGB image.
  constexpr int kH = 480, kW = 640;
  std::vector<uint8_t> image(size_t(kH) * kW * 3);
  uint32_t state = 11;
  for (auto& px : image) {
    state = state * 1664525u + 1013904223u;  // LCG
    px = uint8_t(state >> 24);
  }

  tc::InferInput* raw;
  FAIL_IF_ERR(tc::InferInput::Create(&raw, "RAW_IMAGE", {1, kH, kW, 3},
                                     "UINT8"),
              "create RAW_IMAGE");
  std::unique_ptr<tc::InferInput> owner_in(raw);
  FAIL_IF_ERR(raw->AppendRaw(image.data(), image.size()), "RAW_IMAGE data");

  tc::InferOptions options("ensemble_image");
  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, {raw}), "ensemble infer");
  std::unique_ptr<tc::InferResult> owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  std::vector<int64_t> shape;
  std::string datatype;
  FAIL_IF_ERR(result->Shape("CLASS_LOGITS", &shape), "logits shape");
  FAIL_IF_ERR(result->Datatype("CLASS_LOGITS", &datatype), "logits dtype");
  if (shape != std::vector<int64_t>({1, 1000}) || datatype != "FP32") {
    std::cerr << "error: unexpected CLASS_LOGITS shape/dtype" << std::endl;
    return 1;
  }
  const uint8_t* buf;
  size_t byte_size;
  FAIL_IF_ERR(result->RawData("CLASS_LOGITS", &buf, &byte_size),
              "logits data");
  if (byte_size != 1000 * sizeof(float)) {
    std::cerr << "error: unexpected logits byte size " << byte_size
              << std::endl;
    return 1;
  }
  const float* logits = reinterpret_cast<const float*>(buf);
  int best = 0;
  for (int i = 0; i < 1000; ++i) {
    if (!std::isfinite(logits[i])) {
      std::cerr << "error: non-finite logit at " << i << std::endl;
      return 1;
    }
    if (logits[i] > logits[best]) best = i;
  }
  std::cout << "top class: " << best << std::endl;
  std::cout << "PASS : ensemble image" << std::endl;
  return 0;
}
