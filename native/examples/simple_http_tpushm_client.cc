// TPU shared-memory data-plane conformance client over HTTP — the REST
// flavor of the north-star zero-copy path.
//
// Reference counterpart: simple_http_cudashm_client.cc
// (/root/reference/src/c++/examples/): there, cudaMalloc →
// cudaIpcGetMemHandle → base64 handle → RegisterCudaSharedMemory → infer →
// cudaMemcpy back. Here the handle is the framework's opaque TPU region
// descriptor (host-staged flavor), base64-encoded by the client for REST
// transport exactly as the reference encodes cudaIpcMemHandle_t. Tensor
// bytes never ride the HTTP request/response.
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <iostream>

#include "tpuclient/http_client.h"
#include "tpuclient/shm_utils.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

// Opaque TPU region handle: the host-staged JSON descriptor the server's
// tpu_shared_memory registry understands (client_tpu/engine/shm.py
// register_handle's host_staged schema).
static std::string MakeTpuHandle(const std::string& key, size_t byte_size) {
  return std::string("{\"kind\": \"host_staged\", \"key\": \"") + key +
         "\", \"byte_size\": " + std::to_string(byte_size) + "}";
}

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
              "create client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const char* input_key = "/simple_http_tpushm_input";
  const char* output_key = "/simple_http_tpushm_output";

  client->UnregisterTpuSharedMemory();  // fresh slate, ignore errors
  tc::UnlinkSharedMemoryRegion(input_key);
  tc::UnlinkSharedMemoryRegion(output_key);

  int input_fd, output_fd;
  void *input_addr, *output_addr;
  FAIL_IF_ERR(tc::CreateSharedMemoryRegion(input_key, 2 * kTensorBytes,
                                           &input_fd),
              "create input region");
  FAIL_IF_ERR(tc::MapSharedMemory(input_fd, 0, 2 * kTensorBytes, &input_addr),
              "map input region");
  FAIL_IF_ERR(tc::CreateSharedMemoryRegion(output_key, 2 * kTensorBytes,
                                           &output_fd),
              "create output region");
  FAIL_IF_ERR(tc::MapSharedMemory(output_fd, 0, 2 * kTensorBytes,
                                  &output_addr),
              "map output region");

  int32_t* input0_stage = reinterpret_cast<int32_t*>(input_addr);
  int32_t* input1_stage = input0_stage + 16;
  for (int i = 0; i < 16; ++i) {
    input0_stage[i] = i;
    input1_stage[i] = 7;
  }

  FAIL_IF_ERR(client->RegisterTpuSharedMemory(
                  "tpu_input_data", MakeTpuHandle(input_key, 2 * kTensorBytes),
                  2 * kTensorBytes, /*device_id=*/0),
              "register input region");
  FAIL_IF_ERR(
      client->RegisterTpuSharedMemory(
          "tpu_output_data", MakeTpuHandle(output_key, 2 * kTensorBytes),
          2 * kTensorBytes, /*device_id=*/0),
      "register output region");

  tc::JsonPtr status;
  FAIL_IF_ERR(client->TpuSharedMemoryStatus(&status), "tpushm status");

  tc::InferInput *input0, *input1;
  tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  FAIL_IF_ERR(input0->SetSharedMemory("tpu_input_data", kTensorBytes, 0),
              "INPUT0 shm");
  FAIL_IF_ERR(input1->SetSharedMemory("tpu_input_data", kTensorBytes,
                                      kTensorBytes),
              "INPUT1 shm");

  tc::InferRequestedOutput *output0, *output1;
  tc::InferRequestedOutput::Create(&output0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&output1, "OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> o0(output0), o1(output1);
  FAIL_IF_ERR(output0->SetSharedMemory("tpu_output_data", kTensorBytes, 0),
              "OUTPUT0 shm");
  FAIL_IF_ERR(output1->SetSharedMemory("tpu_output_data", kTensorBytes,
                                       kTensorBytes),
              "OUTPUT1 shm");

  tc::InferOptions options("simple");
  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, {input0, input1},
                            {output0, output1}),
              "infer");
  std::unique_ptr<tc::InferResult> owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  const int32_t* out0 = reinterpret_cast<const int32_t*>(output_addr);
  const int32_t* out1 = out0 + 16;
  for (int i = 0; i < 16; ++i) {
    if (out0[i] != input0_stage[i] + input1_stage[i] ||
        out1[i] != input0_stage[i] - input1_stage[i]) {
      std::cerr << "error: tpushm output mismatch at " << i << ": "
                << out0[i] << "/" << out1[i] << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnregisterTpuSharedMemory("tpu_input_data"),
              "unregister input");
  FAIL_IF_ERR(client->UnregisterTpuSharedMemory("tpu_output_data"),
              "unregister output");
  tc::UnmapSharedMemory(input_addr, 2 * kTensorBytes);
  tc::UnmapSharedMemory(output_addr, 2 * kTensorBytes);
  tc::CloseSharedMemory(input_fd);
  tc::CloseSharedMemory(output_fd);
  tc::UnlinkSharedMemoryRegion(input_key);
  tc::UnlinkSharedMemoryRegion(output_key);

  std::cout << "PASS : simple_http_tpushm_client" << std::endl;
  return 0;
}
