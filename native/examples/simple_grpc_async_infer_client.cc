// Async gRPC conformance client: N concurrent AsyncInfer calls, callback
// completion, value assertions on every response.
//
// Reference counterpart: simple_grpc_async_infer_client.cc (§2.7) — the
// async path exercises the completion-dispatch worker the way the
// reference's CompletionQueue drain loop is exercised
// (/root/reference/src/c++/library/grpc_client.cc:1225-1268).
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <iostream>
#include <mutex>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int requests = 8;
  int opt;
  while ((opt = getopt(argc, argv, "u:n:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'n') requests = atoi(optarg);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
              "create client");

  std::vector<int32_t> input0_data(16);
  std::vector<int32_t> input1_data(16);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 2;
  }

  tc::InferInput *input0, *input1;
  FAIL_IF_ERR(tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
              "create INPUT0");
  FAIL_IF_ERR(tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
              "create INPUT1");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(input0_data.data()),
                    16 * sizeof(int32_t));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(input1_data.data()),
                    16 * sizeof(int32_t));

  std::mutex mtx;
  std::condition_variable cv;
  int done = 0, failed = 0;

  tc::InferOptions options("simple");
  for (int r = 0; r < requests; ++r) {
    options.request_id = std::to_string(r);
    FAIL_IF_ERR(
        client->AsyncInfer(
            [&](tc::InferResult* result) {
              std::unique_ptr<tc::InferResult> owner(result);
              bool ok = result->RequestStatus().IsOk();
              if (ok) {
                const uint8_t* buf;
                size_t n;
                ok = result->RawData("OUTPUT0", &buf, &n).IsOk() &&
                     n == 16 * sizeof(int32_t);
                if (ok) {
                  const int32_t* vals =
                      reinterpret_cast<const int32_t*>(buf);
                  for (int i = 0; i < 16 && ok; ++i) {
                    ok = vals[i] == input0_data[i] + input1_data[i];
                  }
                }
              } else {
                std::cerr << "async infer failed: "
                          << result->RequestStatus() << std::endl;
              }
              std::lock_guard<std::mutex> lk(mtx);
              ++done;
              if (!ok) ++failed;
              cv.notify_all();
            },
            options, {input0, input1}),
        "submit async infer");
  }

  std::unique_lock<std::mutex> lk(mtx);
  if (!cv.wait_for(lk, std::chrono::seconds(60),
                   [&] { return done == requests; })) {
    std::cerr << "error: timed out waiting for async completions (" << done
              << "/" << requests << ")" << std::endl;
    return 1;
  }
  if (failed > 0) {
    std::cerr << "error: " << failed << " async requests failed validation"
              << std::endl;
    return 1;
  }
  std::cout << "PASS : simple_grpc_async_infer_client" << std::endl;
  return 0;
}
