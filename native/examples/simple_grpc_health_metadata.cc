// Control-plane conformance client over gRPC: liveness, readiness, server
// and model metadata, model config, repository index, statistics.
//
// Reference counterpart: simple_grpc_health_metadata.py / the control-plane
// surface of grpc_client.h:125-312 (§2.7). Asserts protobuf-typed responses,
// exercising the zero-parse path the JSON/HTTP client can't.
#include <unistd.h>

#include <iostream>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
              "create client");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "ServerLive");
  FAIL_IF_ERR(client->IsServerReady(&ready), "ServerReady");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "ModelReady");
  if (!live || !ready || !model_ready) {
    std::cerr << "error: live/ready flags false" << std::endl;
    return 1;
  }

  inference::ServerMetadataResponse server_meta;
  FAIL_IF_ERR(client->ServerMetadata(&server_meta), "ServerMetadata");
  if (server_meta.name().empty() || server_meta.version().empty()) {
    std::cerr << "error: empty server metadata" << std::endl;
    return 1;
  }

  inference::ModelMetadataResponse model_meta;
  FAIL_IF_ERR(client->ModelMetadata(&model_meta, "simple"), "ModelMetadata");
  if (model_meta.name() != "simple" || model_meta.inputs_size() != 2 ||
      model_meta.outputs_size() != 2) {
    std::cerr << "error: unexpected model metadata: "
              << model_meta.ShortDebugString() << std::endl;
    return 1;
  }
  for (const auto& io : model_meta.inputs()) {
    if (io.datatype() != "INT32") {
      std::cerr << "error: unexpected input dtype " << io.datatype()
                << std::endl;
      return 1;
    }
  }

  inference::ModelConfigResponse model_config;
  FAIL_IF_ERR(client->ModelConfig(&model_config, "simple"), "ModelConfig");
  if (model_config.config().name() != "simple") {
    std::cerr << "error: unexpected model config" << std::endl;
    return 1;
  }

  inference::RepositoryIndexResponse index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "RepositoryIndex");
  bool found = false;
  for (const auto& m : index.models()) found |= m.name() == "simple";
  if (!found) {
    std::cerr << "error: 'simple' missing from repository index" << std::endl;
    return 1;
  }

  inference::ModelStatisticsResponse stats;
  FAIL_IF_ERR(client->ModelInferenceStatistics(&stats, "simple"),
              "ModelStatistics");
  if (stats.model_stats_size() < 1) {
    std::cerr << "error: empty model statistics" << std::endl;
    return 1;
  }

  std::cout << "PASS : simple_grpc_health_metadata" << std::endl;
  return 0;
}
