// Explicit model control over gRPC: unload/load with readiness transitions
// and repository index checks.
//
// Reference counterpart: simple_grpc_model_control example (§2.7
// load/unload pairs; control plane surface grpc_client.h:195-213).
#include <unistd.h>

#include <iostream>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  std::string model = "simple";
  int opt;
  while ((opt = getopt(argc, argv, "u:m:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'm') model = optarg;
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
              "create client");

  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "initial ready");
  if (!ready) {
    FAIL_IF_ERR(client->LoadModel(model), "initial load");
  }

  FAIL_IF_ERR(client->UnloadModel(model), "unload");
  ready = true;
  // Unloaded models report not-ready (the call may also error; both accept).
  if (client->IsModelReady(&ready, model).IsOk() && ready) {
    std::cerr << "error: model still ready after unload" << std::endl;
    return 1;
  }
  inference::RepositoryIndexResponse index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  for (const auto& m : index.models()) {
    if (m.name() == model && m.state() == "READY") {
      std::cerr << "error: index still READY after unload" << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(client->LoadModel(model), "reload");
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "ready after load");
  if (!ready) {
    std::cerr << "error: model not ready after load" << std::endl;
    return 1;
  }

  std::cout << "PASS : simple_grpc_model_control" << std::endl;
  return 0;
}
