// Token-streaming generation client (native): drives the `tiny_gpt`
// generative model over the bidi gRPC stream, printing tokens as they
// arrive and asserting stream-protocol invariants (ordered INDEX values,
// final-flag termination, exact token count).
//
// No reference counterpart — the reference's only decoupled example is the
// repeat demo (simple_grpc_custom_repeat.cc). Server-side, every decode
// step is shared across all live streams (continuous batching over a
// KV-cache arena); this client shows the wire protocol is the ordinary
// decoupled one, reachable from the dependency-free native transport.
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <iostream>
#include <mutex>
#include <vector>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int max_tokens = 8;
  int opt;
  while ((opt = getopt(argc, argv, "u:n:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'n') max_tokens = atoi(optarg);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  if (!tc::InferenceServerGrpcClient::Create(&client, url).IsOk()) return 1;

  std::mutex mtx;
  std::condition_variable cv;
  std::vector<int32_t> tokens;
  bool done = false, stream_error = false;

  tc::Error err = client->StartStream([&](tc::InferResult* result) {
    std::unique_ptr<tc::InferResult> owner(result);
    std::lock_guard<std::mutex> lk(mtx);
    if (!result->RequestStatus().IsOk()) {
      std::cerr << "stream response error: " << result->RequestStatus()
                << std::endl;
      stream_error = true;
    } else {
      const uint8_t* tok_buf;
      size_t tok_sz;
      if (result->RawData("TOKEN", &tok_buf, &tok_sz).IsOk() &&
          tok_sz == sizeof(int32_t)) {
        const uint8_t* idx_buf;
        size_t idx_sz;
        uint32_t idx = 0;
        if (result->RawData("INDEX", &idx_buf, &idx_sz).IsOk() &&
            idx_sz == sizeof(uint32_t)) {
          idx = *reinterpret_cast<const uint32_t*>(idx_buf);
        }
        if (idx != tokens.size()) {
          std::cerr << "out-of-order token index " << idx << std::endl;
          stream_error = true;
        }
        int32_t tok = *reinterpret_cast<const int32_t*>(tok_buf);
        tokens.push_back(tok);
        std::cout << "token[" << idx << "] = " << tok << std::endl;
      } else {
        // Empty response: the decoupled stream's final-flag terminator.
        done = true;
      }
    }
    cv.notify_all();
  });
  if (!err.IsOk()) {
    std::cerr << "StartStream failed: " << err << std::endl;
    return 1;
  }

  std::vector<int32_t> prompt = {7, 8, 9};
  tc::InferInput* input;
  tc::InferInput::Create(&input, "INPUT_IDS",
                         {static_cast<int64_t>(prompt.size())}, "INT32");
  std::unique_ptr<tc::InferInput> owner_in(input);
  input->AppendRaw(reinterpret_cast<uint8_t*>(prompt.data()),
                   prompt.size() * sizeof(int32_t));

  tc::InferOptions options("tiny_gpt");
  options.request_id = "gen-0";
  options.int_parameters["max_tokens"] = max_tokens;
  err = client->AsyncStreamInfer(options, {input});
  if (!err.IsOk()) {
    std::cerr << "AsyncStreamInfer failed: " << err << std::endl;
    return 1;
  }

  {
    std::unique_lock<std::mutex> lk(mtx);
    cv.wait_for(lk, std::chrono::seconds(300),
                [&] { return done || stream_error; });
    if (stream_error || !done) {
      std::cerr << "stream did not finish cleanly" << std::endl;
      return 1;
    }
    if (static_cast<int>(tokens.size()) != max_tokens) {
      std::cerr << "expected " << max_tokens << " tokens, got "
                << tokens.size() << std::endl;
      return 1;
    }
  }
  client->StopStream();
  std::cout << "PASS : grpc_generate_client (" << tokens.size()
            << " streamed tokens)" << std::endl;
  return 0;
}
