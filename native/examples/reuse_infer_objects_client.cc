// Object-lifecycle conformance client: the same InferInput /
// InferRequestedOutput / options objects reused across many requests and
// across BOTH protocol clients, with value assertions each iteration.
//
// Reference counterpart: reuse_infer_objects_client.cc:482 (object
// lifecycle across protocols).
#include <unistd.h>

#include <cstdint>
#include <iostream>
#include <vector>

#include "tpuclient/grpc_client.h"
#include "tpuclient/http_client.h"

namespace tc = tpuclient;

namespace {

template <typename Client>
int Run(Client* client, const char* label, int iterations) {
  tc::InferInput *input0, *input1;
  tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  tc::InferRequestedOutput *o0, *o1;
  tc::InferRequestedOutput::Create(&o0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&o1, "OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> oo0(o0), oo1(o1);
  tc::InferOptions options("simple");

  std::vector<int32_t> a(16), b(16);
  for (int iter = 0; iter < iterations; ++iter) {
    // Fresh data through the SAME objects: Reset + AppendRaw each round.
    for (int i = 0; i < 16; ++i) {
      a[i] = iter + i;
      b[i] = 2 * iter + 1;
    }
    input0->Reset();
    input1->Reset();
    input0->SetShape({1, 16});
    input1->SetShape({1, 16});
    input0->AppendRaw(reinterpret_cast<uint8_t*>(a.data()), 64);
    input1->AppendRaw(reinterpret_cast<uint8_t*>(b.data()), 64);
    options.request_id = std::to_string(iter);

    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, {input0, input1},
                                  {o0, o1});
    if (!err.IsOk()) {
      std::cerr << label << " iter " << iter << ": " << err << std::endl;
      return 1;
    }
    std::unique_ptr<tc::InferResult> owner(result);
    const uint8_t* buf;
    size_t n;
    if (!result->RawData("OUTPUT0", &buf, &n).IsOk() || n != 64) {
      std::cerr << label << " iter " << iter << ": bad OUTPUT0" << std::endl;
      return 1;
    }
    const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
    for (int i = 0; i < 16; ++i) {
      if (sum[i] != a[i] + b[i]) {
        std::cerr << label << " iter " << iter << ": mismatch at " << i
                  << std::endl;
        return 1;
      }
    }
  }
  std::cout << label << ": " << iterations << " iterations OK" << std::endl;
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string http_url = "localhost:8000";
  std::string grpc_url = "localhost:8001";
  int iterations = 10;
  int opt;
  while ((opt = getopt(argc, argv, "u:g:n:")) != -1) {
    if (opt == 'u') http_url = optarg;
    if (opt == 'g') grpc_url = optarg;
    if (opt == 'n') iterations = atoi(optarg);
  }

  int rc = 0;
  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    if (!tc::InferenceServerHttpClient::Create(&client, http_url).IsOk()) {
      std::cerr << "http create failed" << std::endl;
      return 1;
    }
    rc |= Run(client.get(), "http", iterations);
  }
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    if (!tc::InferenceServerGrpcClient::Create(&client, grpc_url).IsOk()) {
      std::cerr << "grpc create failed" << std::endl;
      return 1;
    }
    rc |= Run(client.get(), "grpc", iterations);
  }
  if (rc == 0) std::cout << "PASS : reuse_infer_objects_client" << std::endl;
  return rc;
}
