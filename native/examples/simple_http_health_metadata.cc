// Control-plane conformance client: health, metadata, config, statistics,
// repository index + load/unload.
//
// Reference counterpart: simple_http_health_metadata.py and
// simple_http_model_control (§2.7) folded into one binary.
#include <unistd.h>

#include <iostream>

#include "tpuclient/http_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                        \
  do {                                                             \
    tc::Error err__ = (X);                                         \
    if (!err__.IsOk()) {                                           \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                     \
    }                                                              \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
              "create client");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "live");
  FAIL_IF_ERR(client->IsServerReady(&ready), "ready");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "model ready");
  if (!live || !ready || !model_ready) {
    std::cerr << "health checks failed: live=" << live << " ready=" << ready
              << " model_ready=" << model_ready << std::endl;
    return 1;
  }

  tc::JsonPtr metadata;
  FAIL_IF_ERR(client->ServerMetadata(&metadata), "server metadata");
  if (!metadata->Has("name") || !metadata->Has("version")) {
    std::cerr << "server metadata missing fields" << std::endl;
    return 1;
  }

  tc::JsonPtr model_md;
  FAIL_IF_ERR(client->ModelMetadata(&model_md, "simple"), "model metadata");
  if (model_md->Get("name")->AsString() != "simple") {
    std::cerr << "model metadata name mismatch" << std::endl;
    return 1;
  }

  tc::JsonPtr config;
  FAIL_IF_ERR(client->ModelConfig(&config, "simple"), "model config");
  if (config->Get("max_batch_size")->AsInt() <= 0) {
    std::cerr << "model config missing max_batch_size" << std::endl;
    return 1;
  }

  tc::JsonPtr index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");

  // unload → not ready → load → ready
  FAIL_IF_ERR(client->UnloadModel("simple"), "unload");
  client->IsModelReady(&model_ready, "simple");
  if (model_ready) {
    std::cerr << "model still ready after unload" << std::endl;
    return 1;
  }
  FAIL_IF_ERR(client->LoadModel("simple"), "load");
  client->IsModelReady(&model_ready, "simple");
  if (!model_ready) {
    std::cerr << "model not ready after load" << std::endl;
    return 1;
  }

  tc::JsonPtr stats;
  FAIL_IF_ERR(client->ModelInferenceStatistics(&stats, "simple"), "stats");
  if (!stats->Has("model_stats")) {
    std::cerr << "stats missing model_stats" << std::endl;
    return 1;
  }

  std::cout << "PASS : simple_http_health_metadata" << std::endl;
  return 0;
}
