// Value-asserting add/sub conformance client over gRPC.
//
// Reference counterpart: simple_grpc_infer_client.cc
// (/root/reference/src/c++/examples/simple_grpc_infer_client.cc:337 asserts
// OUTPUT0=a+b, OUTPUT1=a-b on INT32[16]). Exercises the in-tree HTTP/2
// transport end-to-end against the framework's grpcio-based server.
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <iostream>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  bool verbose = false;
  std::string ca_file;  // -C: CA bundle; implies TLS (as does grpcs://)
  std::string compress;  // -z gzip|deflate: per-call message compression
  int opt;
  while ((opt = getopt(argc, argv, "vu:C:z:")) != -1) {
    switch (opt) {
      case 'u':
        url = optarg;
        break;
      case 'v':
        verbose = true;
        break;
      case 'C':
        ca_file = optarg;
        break;
      case 'z':
        compress = optarg;
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-v] [-u host:port] [-C ca.pem] [-z gzip|deflate]"
                  << std::endl;
        return 2;
    }
  }

  tc::SslOptions ssl;
  ssl.root_certificates = ca_file;
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose,
                                            /*use_cached_channel=*/true,
                                            /*use_ssl=*/!ca_file.empty(), ssl),
      "unable to create client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live check");
  if (!live) {
    std::cerr << "error: server not live" << std::endl;
    return 1;
  }

  std::vector<int32_t> input0_data(16);
  std::vector<int32_t> input1_data(16);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 1;
  }

  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
              "create INPUT0");
  FAIL_IF_ERR(tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
              "create INPUT1");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  FAIL_IF_ERR(
      input0->AppendRaw(reinterpret_cast<uint8_t*>(input0_data.data()),
                        input0_data.size() * sizeof(int32_t)),
      "set INPUT0 data");
  FAIL_IF_ERR(
      input1->AppendRaw(reinterpret_cast<uint8_t*>(input1_data.data()),
                        input1_data.size() * sizeof(int32_t)),
      "set INPUT1 data");

  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  FAIL_IF_ERR(tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
              "create OUTPUT0");
  FAIL_IF_ERR(tc::InferRequestedOutput::Create(&output1, "OUTPUT1"),
              "create OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> o0(output0), o1(output1);

  tc::InferOptions options("simple");
  options.request_id = "1";
  if (compress == "gzip") {
    options.compression_algorithm = tc::GrpcCompression::GZIP;
  } else if (compress == "deflate") {
    options.compression_algorithm = tc::GrpcCompression::DEFLATE;
  } else if (!compress.empty()) {
    std::cerr << "error: unknown compression '" << compress << "'"
              << std::endl;
    return 2;
  }

  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, {input0, input1},
                            {output0, output1}),
              "infer");
  std::unique_ptr<tc::InferResult> result_owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  for (const auto& check :
       {std::make_pair(std::string("OUTPUT0"), +1),
        std::make_pair(std::string("OUTPUT1"), -1)}) {
    std::vector<int64_t> shape;
    std::string datatype;
    FAIL_IF_ERR(result->Shape(check.first, &shape), "output shape");
    FAIL_IF_ERR(result->Datatype(check.first, &datatype), "output dtype");
    if (shape != std::vector<int64_t>({1, 16}) || datatype != "INT32") {
      std::cerr << "error: unexpected shape/datatype for " << check.first
                << std::endl;
      return 1;
    }
    const uint8_t* buf;
    size_t byte_size;
    FAIL_IF_ERR(result->RawData(check.first, &buf, &byte_size), "raw data");
    if (byte_size != 16 * sizeof(int32_t)) {
      std::cerr << "error: unexpected byte size " << byte_size << std::endl;
      return 1;
    }
    const int32_t* vals = reinterpret_cast<const int32_t*>(buf);
    for (int i = 0; i < 16; ++i) {
      int32_t expect = input0_data[i] + check.second * input1_data[i];
      if (vals[i] != expect) {
        std::cerr << "error: " << check.first << "[" << i << "] = " << vals[i]
                  << ", expected " << expect << std::endl;
        return 1;
      }
    }
  }

  tc::InferStat stat;
  client->ClientInferStat(&stat);
  if (verbose) {
    std::cout << "completed " << stat.completed_request_count << " requests"
              << std::endl;
  }
  std::cout << "PASS : simple_grpc_infer_client" << std::endl;
  return 0;
}
