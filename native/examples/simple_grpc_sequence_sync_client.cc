// Synchronous stateful sequence-batching conformance client over gRPC.
//
// Reference counterpart: simple_grpc_sequence_sync_infer_client.cc
// (/root/reference/src/c++/examples/): two interleaved sequences driven
// with correlation ids and sequence_start/sequence_end flags, asserting
// server-held per-sequence state is isolated and ordered.
#include <unistd.h>

#include <cstdint>
#include <iostream>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

static int32_t Step(tc::InferenceServerGrpcClient* client, uint64_t seq_id,
                    bool start, bool end, int32_t value) {
  tc::InferInput* input;
  tc::InferInput::Create(&input, "INPUT", {1}, "INT32");
  std::unique_ptr<tc::InferInput> owner(input);
  input->AppendRaw(reinterpret_cast<uint8_t*>(&value), sizeof(value));

  tc::InferOptions options("simple_sequence");
  options.sequence_id = seq_id;
  options.sequence_start = start;
  options.sequence_end = end;

  tc::InferResult* result;
  tc::Error err = client->Infer(&result, options, {input});
  if (!err.IsOk()) {
    std::cerr << "infer failed: " << err << std::endl;
    exit(1);
  }
  std::unique_ptr<tc::InferResult> rowner(result);
  if (!result->RequestStatus().IsOk()) {
    std::cerr << "request failed: " << result->RequestStatus() << std::endl;
    exit(1);
  }
  const uint8_t* buf;
  size_t sz;
  if (!result->RawData("OUTPUT", &buf, &sz).IsOk() ||
      sz != sizeof(int32_t)) {
    std::cerr << "bad OUTPUT" << std::endl;
    exit(1);
  }
  return *reinterpret_cast<const int32_t*>(buf);
}

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  if (!tc::InferenceServerGrpcClient::Create(&client, url).IsOk()) return 1;

  const uint64_t kSeqA = 2001, kSeqB = 2002;
  int32_t a_total = 0, b_total = 0;
  int32_t a_vals[] = {2, 4, 6};
  int32_t b_vals[] = {100, 200, 300};
  for (int i = 0; i < 3; ++i) {
    a_total += a_vals[i];
    b_total += b_vals[i];
    int32_t a = Step(client.get(), kSeqA, i == 0, i == 2, a_vals[i]);
    int32_t b = Step(client.get(), kSeqB, i == 0, i == 2, b_vals[i]);
    if (a != a_total || b != b_total) {
      std::cerr << "state mismatch at step " << i << ": " << a << "/"
                << a_total << ", " << b << "/" << b_total << std::endl;
      return 1;
    }
  }
  std::cout << "PASS : simple_grpc_sequence_sync_client" << std::endl;
  return 0;
}
