// Bidirectional-streaming sequence conformance client.
//
// Reference counterpart: simple_grpc_sequence_stream_infer_client.cc (§2.7):
// drives two interleaved stateful sequences over ONE ModelStreamInfer bidi
// stream (StartStream + AsyncStreamInfer + ordered callbacks), asserting the
// server-held accumulator state per sequence — the decoupled/streaming hot
// path of the reference (grpc_client.cc:986-1080).
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <iostream>
#include <mutex>

#include "tpuclient/grpc_client.h"

namespace tc = tpuclient;

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  if (!tc::InferenceServerGrpcClient::Create(&client, url).IsOk()) return 1;

  std::mutex mtx;
  std::condition_variable cv;
  // Responses complete in engine order, not send order, across different
  // sequences — match them back by request id (per-sequence order is still
  // guaranteed by the sequence scheduler, which the totals assert below).
  std::map<std::string, int32_t> results;
  bool stream_error = false;

  tc::Error err = client->StartStream([&](tc::InferResult* result) {
    std::unique_ptr<tc::InferResult> owner(result);
    std::lock_guard<std::mutex> lk(mtx);
    std::string id;
    if (!result->RequestStatus().IsOk() || !result->Id(&id).IsOk()) {
      std::cerr << "stream response error: " << result->RequestStatus()
                << std::endl;
      stream_error = true;
    } else {
      const uint8_t* buf;
      size_t sz;
      if (result->RawData("OUTPUT", &buf, &sz).IsOk() &&
          sz == sizeof(int32_t)) {
        results[id] = *reinterpret_cast<const int32_t*>(buf);
      } else {
        stream_error = true;
      }
    }
    cv.notify_all();
  });
  if (!err.IsOk()) {
    std::cerr << "StartStream failed: " << err << std::endl;
    return 1;
  }

  // Two interleaved sequences on one stream, accumulator oracle per step.
  const uint64_t kSeqA = 2001, kSeqB = 2002;
  int32_t a_vals[] = {1, 2, 3};
  int32_t b_vals[] = {10, 20, 30};
  std::map<std::string, int32_t> expected;
  int32_t a_total = 0, b_total = 0;
  // Keep inputs alive until all responses arrive (no-copy AppendRaw).
  std::deque<int32_t> values;
  std::vector<std::unique_ptr<tc::InferInput>> inputs_alive;
  for (int i = 0; i < 3; ++i) {
    for (auto seq : {kSeqA, kSeqB}) {
      int32_t value = seq == kSeqA ? a_vals[i] : b_vals[i];
      (seq == kSeqA ? a_total : b_total) += value;
      std::string id =
          (seq == kSeqA ? "A" : "B") + std::to_string(i);
      expected[id] = seq == kSeqA ? a_total : b_total;

      values.push_back(value);
      tc::InferInput* input;
      tc::InferInput::Create(&input, "INPUT", {1}, "INT32");
      inputs_alive.emplace_back(input);
      input->AppendRaw(reinterpret_cast<uint8_t*>(&values.back()),
                       sizeof(int32_t));

      tc::InferOptions options("simple_sequence");
      options.request_id = id;
      options.sequence_id = seq;
      options.sequence_start = i == 0;
      options.sequence_end = i == 2;
      tc::Error serr = client->AsyncStreamInfer(options, {input});
      if (!serr.IsOk()) {
        std::cerr << "AsyncStreamInfer failed: " << serr << std::endl;
        return 1;
      }
    }
  }

  {
    std::unique_lock<std::mutex> lk(mtx);
    if (!cv.wait_for(lk, std::chrono::seconds(60), [&] {
          return stream_error || results.size() >= expected.size();
        })) {
      std::cerr << "error: timed out (" << results.size() << "/"
                << expected.size() << " responses)" << std::endl;
      return 1;
    }
    if (stream_error) return 1;
    for (const auto& kv : expected) {
      auto it = results.find(kv.first);
      if (it == results.end() || it->second != kv.second) {
        std::cerr << "error: response " << kv.first << " = "
                  << (it == results.end() ? -999999 : it->second)
                  << ", expected " << kv.second << std::endl;
        return 1;
      }
    }
  }

  client->StopStream();
  std::cout << "PASS : simple_grpc_sequence_stream_client" << std::endl;
  return 0;
}
