// Async + concurrent inference conformance client.
//
// Reference counterpart: simple_http_async_infer_client.cc / the async
// paths of /root/reference/src/c++/examples (§2.7) — issues N AsyncInfer
// requests, waits on a counter, validates every result's values.
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <iostream>
#include <mutex>

#include "tpuclient/http_client.h"

namespace tc = tpuclient;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int n_requests = 20;
  int opt;
  while ((opt = getopt(argc, argv, "u:n:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'n') n_requests = atoi(optarg);
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    std::cerr << "client create failed: " << err << std::endl;
    return 1;
  }

  std::vector<int32_t> input0_data(16), input1_data(16);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 2 * i;
  }

  std::mutex mu;
  std::condition_variable cv;
  int done = 0, failed = 0;

  for (int r = 0; r < n_requests; ++r) {
    tc::InferInput *input0, *input1;
    tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32");
    tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32");
    std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
    input0->AppendRaw(reinterpret_cast<uint8_t*>(input0_data.data()), 64);
    input1->AppendRaw(reinterpret_cast<uint8_t*>(input1_data.data()), 64);

    tc::InferOptions options("simple");
    options.request_id = std::to_string(r);
    // AsyncInfer copies input buffers at enqueue, so the inputs may go out
    // of scope right after this call returns.
    err = client->AsyncInfer(
        [&](tc::InferResult* result) {
          std::unique_ptr<tc::InferResult> owner(result);
          bool ok = result->RequestStatus().IsOk();
          if (ok) {
            const uint8_t* buf;
            size_t sz;
            ok = result->RawData("OUTPUT0", &buf, &sz).IsOk() && sz == 64;
            if (ok) {
              const int32_t* vals = reinterpret_cast<const int32_t*>(buf);
              for (int i = 0; i < 16 && ok; ++i)
                ok = (vals[i] == input0_data[i] + input1_data[i]);
            }
          }
          std::lock_guard<std::mutex> lk(mu);
          ++done;
          if (!ok) ++failed;
          cv.notify_one();
        },
        options, {input0, input1});
    if (!err.IsOk()) {
      std::cerr << "AsyncInfer failed: " << err << std::endl;
      return 1;
    }
  }

  std::unique_lock<std::mutex> lk(mu);
  if (!cv.wait_for(lk, std::chrono::seconds(120),
                   [&]() { return done == n_requests; })) {
    std::cerr << "timeout: " << done << "/" << n_requests << std::endl;
    return 1;
  }
  if (failed) {
    std::cerr << failed << " requests returned wrong values" << std::endl;
    return 1;
  }
  std::cout << "PASS : simple_http_async_infer_client (" << n_requests
            << " concurrent)" << std::endl;
  return 0;
}
