// System shared-memory data-plane conformance client over gRPC.
//
// Reference counterpart: simple_grpc_shm_client.cc
// (/root/reference/src/c++/examples/simple_grpc_shm_client.cc:299): POSIX
// shm regions for inputs and outputs, registered via the gRPC control plane;
// tensor bytes move through /dev/shm, not the wire.
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <iostream>

#include "tpuclient/grpc_client.h"
#include "tpuclient/shm_utils.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  int opt;
  while ((opt = getopt(argc, argv, "u:")) != -1)
    if (opt == 'u') url = optarg;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
              "create client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const char* input_key = "/simple_grpc_shm_input";
  const char* output_key = "/simple_grpc_shm_output";

  client->UnregisterSystemSharedMemory("grpc_input_data");
  client->UnregisterSystemSharedMemory("grpc_output_data");
  tc::UnlinkSharedMemoryRegion(input_key);
  tc::UnlinkSharedMemoryRegion(output_key);

  int input_fd, output_fd;
  void *input_addr, *output_addr;
  FAIL_IF_ERR(tc::CreateSharedMemoryRegion(input_key, 2 * kTensorBytes,
                                           &input_fd),
              "create input region");
  FAIL_IF_ERR(tc::MapSharedMemory(input_fd, 0, 2 * kTensorBytes, &input_addr),
              "map input region");
  FAIL_IF_ERR(tc::CreateSharedMemoryRegion(output_key, 2 * kTensorBytes,
                                           &output_fd),
              "create output region");
  FAIL_IF_ERR(tc::MapSharedMemory(output_fd, 0, 2 * kTensorBytes,
                                  &output_addr),
              "map output region");

  int32_t* input0_shm = reinterpret_cast<int32_t*>(input_addr);
  int32_t* input1_shm = input0_shm + 16;
  for (int i = 0; i < 16; ++i) {
    input0_shm[i] = i;
    input1_shm[i] = 1;
  }

  FAIL_IF_ERR(client->RegisterSystemSharedMemory("grpc_input_data", input_key,
                                                 2 * kTensorBytes),
              "register input region");
  FAIL_IF_ERR(client->RegisterSystemSharedMemory("grpc_output_data",
                                                 output_key,
                                                 2 * kTensorBytes),
              "register output region");

  inference::SystemSharedMemoryStatusResponse status;
  FAIL_IF_ERR(client->SystemSharedMemoryStatus(&status), "shm status");
  if (status.regions().count("grpc_input_data") == 0 ||
      status.regions().count("grpc_output_data") == 0) {
    std::cerr << "error: regions missing from status" << std::endl;
    return 1;
  }

  tc::InferInput *input0, *input1;
  tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32");
  std::unique_ptr<tc::InferInput> i0(input0), i1(input1);
  FAIL_IF_ERR(input0->SetSharedMemory("grpc_input_data", kTensorBytes, 0),
              "INPUT0 shm");
  FAIL_IF_ERR(input1->SetSharedMemory("grpc_input_data", kTensorBytes,
                                      kTensorBytes),
              "INPUT1 shm");

  // Mixed placement: OUTPUT0 lands in shared memory, OUTPUT1 comes back
  // inline — the response's raw contents then hold only OUTPUT1, which must
  // not be misattributed to OUTPUT0 (shm outputs have no raw wire entry).
  tc::InferRequestedOutput *output0, *output1;
  tc::InferRequestedOutput::Create(&output0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&output1, "OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> o0(output0), o1(output1);
  FAIL_IF_ERR(output0->SetSharedMemory("grpc_output_data", kTensorBytes, 0),
              "OUTPUT0 shm");

  tc::InferOptions options("simple");
  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, {input0, input1},
                            {output0, output1}),
              "infer");
  std::unique_ptr<tc::InferResult> owner(result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  const int32_t* out0 = reinterpret_cast<const int32_t*>(output_addr);
  for (int i = 0; i < 16; ++i) {
    if (out0[i] != input0_shm[i] + input1_shm[i]) {
      std::cerr << "error: shm OUTPUT0 mismatch at " << i << ": " << out0[i]
                << std::endl;
      return 1;
    }
  }
  const uint8_t* shm_view = nullptr;
  size_t shm_view_size = 1;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &shm_view, &shm_view_size),
              "OUTPUT0 raw");
  if (shm_view != nullptr || shm_view_size != 0) {
    std::cerr << "error: shm OUTPUT0 unexpectedly has inline bytes"
              << std::endl;
    return 1;
  }
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT1", &buf, &byte_size), "OUTPUT1 raw");
  if (byte_size != kTensorBytes) {
    std::cerr << "error: OUTPUT1 byte size " << byte_size << std::endl;
    return 1;
  }
  const int32_t* out1 = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (out1[i] != input0_shm[i] - input1_shm[i]) {
      std::cerr << "error: inline OUTPUT1 mismatch at " << i << ": "
                << out1[i] << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnregisterSystemSharedMemory("grpc_input_data"),
              "unregister input");
  FAIL_IF_ERR(client->UnregisterSystemSharedMemory("grpc_output_data"),
              "unregister output");
  tc::UnmapSharedMemory(input_addr, 2 * kTensorBytes);
  tc::UnmapSharedMemory(output_addr, 2 * kTensorBytes);
  tc::UnlinkSharedMemoryRegion(input_key);
  tc::UnlinkSharedMemoryRegion(output_key);

  std::cout << "PASS : simple_grpc_shm_client" << std::endl;
  return 0;
}
