// Explicit model control over HTTP: unload then load a model, checking
// readiness transitions and the repository index.
//
// Reference counterpart: simple_http_model_control.cc
// (/root/reference/src/c++/examples/): LoadModel/UnloadModel/IsModelReady
// against the `simple` model.
#include <unistd.h>

#include <iostream>
#include <string>

#include "tpuclient/http_client.h"

namespace tc = tpuclient;

#define FAIL_IF_ERR(X, MSG)                                          \
  do {                                                               \
    tc::Error err__ = (X);                                           \
    if (!err__.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err__ << std::endl; \
      exit(1);                                                       \
    }                                                                \
  } while (false)

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  std::string model = "simple";
  int opt;
  while ((opt = getopt(argc, argv, "u:m:")) != -1) {
    if (opt == 'u') url = optarg;
    if (opt == 'm') model = optarg;
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
              "create client");

  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "initial ready check");
  if (!ready) FAIL_IF_ERR(client->LoadModel(model), "initial load");

  FAIL_IF_ERR(client->UnloadModel(model), "unload");
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "ready after unload");
  if (ready) {
    std::cerr << "error: model still ready after unload" << std::endl;
    return 1;
  }

  // The unloaded model must still appear in the repository index.
  tc::JsonPtr index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");

  FAIL_IF_ERR(client->LoadModel(model), "load");
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "ready after load");
  if (!ready) {
    std::cerr << "error: model not ready after load" << std::endl;
    return 1;
  }

  std::cout << "PASS : simple_http_model_control" << std::endl;
  return 0;
}
