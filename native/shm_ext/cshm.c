/* C extension backing client_tpu.utils.shared_memory (ctypes-loaded).
 *
 * Same API shape as the reference's libcshm
 * (/root/reference/src/python/library/tritonclient/utils/shared_memory/
 * shared_memory.cc, shared_memory_handle.h:44): an opaque handle wrapping
 * {shm key, fd, mmap base, size, offset}, created/written/read/destroyed
 * from Python via ctypes. Kept in C so region setup costs no Python-level
 * copies and the handle can be passed between processes by key.
 */
#include <errno.h>
#include <fcntl.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

typedef struct {
  char* shm_key;
  int shm_fd;
  void* base_addr;
  uint64_t byte_size;
  uint64_t offset;
} SharedMemoryHandle;

/* Error codes mirror the reference's convention: 0 success, negative errno-
 * style failures. */
#define SHM_ERR_CREATE -2
#define SHM_ERR_MAP -3
#define SHM_ERR_RANGE -4
#define SHM_ERR_UNLINK -5

int SharedMemoryRegionCreate(const char* shm_key, uint64_t byte_size,
                             void** handle_out) {
  int fd = shm_open(shm_key, O_RDWR | O_CREAT, S_IRUSR | S_IWUSR);
  if (fd < 0) return SHM_ERR_CREATE;
  if (ftruncate(fd, (off_t)byte_size) != 0) {
    close(fd);
    return SHM_ERR_CREATE;
  }
  void* base = mmap(NULL, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return SHM_ERR_MAP;
  }
  SharedMemoryHandle* h = (SharedMemoryHandle*)malloc(sizeof(*h));
  h->shm_key = strdup(shm_key);
  h->shm_fd = fd;
  h->base_addr = base;
  h->byte_size = byte_size;
  h->offset = 0;
  *handle_out = h;
  return 0;
}

/* Overflow-safe bounds check: offset+byte_size may wrap in uint64. */
static int in_range(const SharedMemoryHandle* h, uint64_t offset,
                    uint64_t byte_size) {
  return offset <= h->byte_size && byte_size <= h->byte_size - offset;
}

int SharedMemoryRegionSet(void* handle, uint64_t offset, uint64_t byte_size,
                          const void* data) {
  SharedMemoryHandle* h = (SharedMemoryHandle*)handle;
  if (!in_range(h, offset, byte_size)) return SHM_ERR_RANGE;
  memcpy((char*)h->base_addr + offset, data, byte_size);
  return 0;
}

int SharedMemoryRegionRead(void* handle, uint64_t offset, uint64_t byte_size,
                           void* out) {
  SharedMemoryHandle* h = (SharedMemoryHandle*)handle;
  if (!in_range(h, offset, byte_size)) return SHM_ERR_RANGE;
  memcpy(out, (char*)h->base_addr + offset, byte_size);
  return 0;
}

int GetSharedMemoryHandleInfo(void* handle, char** shm_key, int* shm_fd,
                              uint64_t* offset, uint64_t* byte_size,
                              void** base_addr) {
  SharedMemoryHandle* h = (SharedMemoryHandle*)handle;
  if (shm_key) *shm_key = h->shm_key;
  if (shm_fd) *shm_fd = h->shm_fd;
  if (offset) *offset = h->offset;
  if (byte_size) *byte_size = h->byte_size;
  if (base_addr) *base_addr = h->base_addr;
  return 0;
}

int SharedMemoryRegionDestroy(void* handle) {
  SharedMemoryHandle* h = (SharedMemoryHandle*)handle;
  int rc = 0;
  munmap(h->base_addr, h->byte_size);
  close(h->shm_fd);
  if (shm_unlink(h->shm_key) != 0) rc = SHM_ERR_UNLINK;
  free(h->shm_key);
  free(h);
  return rc;
}

/* Release the local mapping without unlinking the segment (for handles that
 * merely attach to a region owned elsewhere). */
int SharedMemoryRegionRelease(void* handle) {
  SharedMemoryHandle* h = (SharedMemoryHandle*)handle;
  munmap(h->base_addr, h->byte_size);
  close(h->shm_fd);
  free(h->shm_key);
  free(h);
  return 0;
}
