#include "tpuclient/base64.h"

namespace tpuclient {

static const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string Base64Encode(const uint8_t* data, size_t len) {
  std::string out;
  out.reserve(((len + 2) / 3) * 4);
  size_t i = 0;
  for (; i + 3 <= len; i += 3) {
    uint32_t v = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2];
    out.push_back(kB64[(v >> 18) & 63]);
    out.push_back(kB64[(v >> 12) & 63]);
    out.push_back(kB64[(v >> 6) & 63]);
    out.push_back(kB64[v & 63]);
  }
  if (i + 1 == len) {
    uint32_t v = data[i] << 16;
    out.push_back(kB64[(v >> 18) & 63]);
    out.push_back(kB64[(v >> 12) & 63]);
    out.append("==");
  } else if (i + 2 == len) {
    uint32_t v = (data[i] << 16) | (data[i + 1] << 8);
    out.push_back(kB64[(v >> 18) & 63]);
    out.push_back(kB64[(v >> 12) & 63]);
    out.push_back(kB64[(v >> 6) & 63]);
    out.push_back('=');
  }
  return out;
}

static inline int B64Val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

bool Base64Decode(const std::string& text, std::vector<uint8_t>* out) {
  out->clear();
  out->reserve((text.size() / 4) * 3);
  uint32_t acc = 0;
  int bits = 0;
  for (char c : text) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int v = B64Val(c);
    if (v < 0) return false;
    acc = (acc << 6) | static_cast<uint32_t>(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back(static_cast<uint8_t>((acc >> bits) & 0xFF));
    }
  }
  return true;
}

}  // namespace tpuclient
