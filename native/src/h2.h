// Minimal dependency-free HTTP/2 (h2c, RFC 7540) + HPACK (RFC 7541) client
// transport, sized for gRPC: cleartext prior-knowledge connections, client-
// initiated streams only (no server push), full flow control, HPACK with
// dynamic table + Huffman decoding (table generated and verified against
// libnghttp2 — see hpack_huffman.inc / tools/gen_hpack_table.py).
//
// This is the piece the reference gets from linking grpc++
// (/root/reference/src/c++/library/grpc_client.cc); this image has no grpc++
// or nghttp2 headers, and the native tree is dependency-free by design, so
// the transport is implemented here and the gRPC semantics (message framing,
// trailers, status) live in grpc_client.cc on top of it.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "tpuclient/error.h"
#include "tpuclient/tls.h"

namespace tpuclient {
namespace h2 {

using HeaderList = std::vector<std::pair<std::string, std::string>>;

// ---------------------------------------------------------------- HPACK ----

// Encoder: emits every field as "literal without indexing — new name"
// (RFC 7541 §6.2.2, no Huffman). Always legal, stateless, and keeps the
// encoder trivially correct; the decoder side is where full HPACK lives.
void HpackEncode(const HeaderList& headers, std::string* out);

// Decoder: full HPACK — static + dynamic tables, all field representations,
// Huffman-coded strings, dynamic table size updates.
class HpackDecoder {
 public:
  explicit HpackDecoder(size_t max_dynamic_size = 4096)
      : max_dynamic_size_(max_dynamic_size),
        configured_max_(max_dynamic_size) {}

  // Decodes one complete header block (HEADERS + CONTINUATIONs payload).
  Error Decode(const uint8_t* data, size_t len, HeaderList* out);

 private:
  Error ReadInt(const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
                uint64_t* value);
  Error ReadString(const uint8_t* data, size_t len, size_t* pos,
                   std::string* out);
  Error LookupIndex(uint64_t index, std::string* name, std::string* value);
  void DynamicInsert(const std::string& name, const std::string& value);
  void EvictToFit();

  std::deque<std::pair<std::string, std::string>> dynamic_;  // newest front
  size_t dynamic_size_ = 0;
  size_t max_dynamic_size_;
  // Ceiling for Dynamic Table Size Updates (RFC 7541 §6.3): since we never
  // advertise SETTINGS_HEADER_TABLE_SIZE, a peer may not raise the table
  // beyond the configured default — otherwise it could grow client memory
  // without bound via incremental-indexing literals.
  size_t configured_max_;
};

// Huffman primitives exposed for unit tests.
Error HuffmanDecode(const uint8_t* data, size_t len, std::string* out);
void HuffmanEncode(const std::string& in, std::string* out);

// ----------------------------------------------------------- connection ----

// One h2c connection: socket, reader thread, stream registry, flow control.
// Thread-safe: any thread may open streams / send data; the reader thread
// dispatches frames into per-stream state and wakes waiters.
class Connection {
 public:
  struct Stream {
    int32_t id = 0;
    HeaderList headers;         // initial response HEADERS block
    HeaderList trailers;        // trailing HEADERS block
    bool headers_done = false;
    std::string data;           // received DATA bytes, appended in order
    size_t consumed = 0;        // bytes the application has taken from data
    bool end_stream = false;    // peer half-closed (trailers or END_STREAM)
    bool reset = false;         // RST_STREAM received
    uint32_t reset_code = 0;
    int64_t send_window = 0;
    // Called (with the connection stream lock held) whenever state advances;
    // used by gRPC streaming to pump messages without a poll loop.
    std::function<void()> on_event;
  };

  Connection() = default;
  ~Connection();
  Connection(const Connection&) = delete;
  Connection& operator=(const Connection&) = delete;

  // TCP connect + preface + SETTINGS exchange kickoff (does not wait for the
  // server SETTINGS ack). host may be an IPv4 literal or DNS name.
  // tls != nullptr: TLS handshake (ALPN per tls->alpn) before the preface.
  Error Connect(const std::string& host, int port,
                const TlsOptions* tls = nullptr);

  // gRPC-core-style transport keepalive (reference KeepAliveOptions,
  // grpc_client.h:61-81): a PING every time_ms; the connection fails if no
  // ack arrives within timeout_ms. permit_without_calls allows pings with
  // no open streams; max_pings_without_data caps consecutive pings sent
  // with no intervening DATA/HEADERS (0 = unlimited). time_ms <= 0 or
  // INT_MAX disables. Call once, after Connect.
  void StartKeepalive(int time_ms, int timeout_ms, bool permit_without_calls,
                      int max_pings_without_data);

  // Opens a stream with the given request headers. end_stream=true for
  // requests with no body. Returns the stream id.
  Error StartStream(const HeaderList& headers, bool end_stream, int32_t* sid);

  // Sends body bytes on a stream, splitting to MAX_FRAME_SIZE and blocking
  // on connection/stream flow-control windows. deadline_ns: steady-clock
  // deadline (0 = none) applied to window waits.
  Error SendData(int32_t sid, const uint8_t* data, size_t len,
                 bool end_stream, uint64_t deadline_ns = 0);

  // Blocks until the stream has ≥ min_bytes unconsumed data, is half-closed
  // by the peer, reset, or the deadline passes. Returns false on deadline.
  bool WaitStream(int32_t sid, size_t min_bytes, uint64_t deadline_ns);

  // Access stream state under the registry lock via callback (the pointer is
  // only valid inside the callback).
  bool WithStream(int32_t sid, const std::function<void(Stream&)>& fn);

  // Drops the stream from the registry (sends RST_STREAM if still open).
  void CloseStream(int32_t sid);

  bool Alive();
  // Whether this connection is TLS (stable after Connect returns).
  bool Tls() const { return tls_ != nullptr; }
  const std::string& ConnectionError();  // non-empty once dead

 private:
  Error SendFrame(uint8_t type, uint8_t flags, int32_t sid,
                  const uint8_t* payload, size_t len);
  Error SendRaw(const uint8_t* data, size_t len);
  void ReaderLoop();
  void HandleFrame(uint8_t type, uint8_t flags, int32_t sid,
                   const uint8_t* payload, size_t len);
  void FailConnection(const std::string& reason);
  bool ReadN(uint8_t* buf, size_t n);

  int fd_ = -1;
  std::unique_ptr<TlsSession> tls_;  // non-null once a TLS handshake is done
  // OpenSSL SSL objects are not thread-safe even for concurrent read+write;
  // with TLS active the fd is non-blocking and every SSL call runs under
  // this mutex (reader polls outside it, so writers never starve).
  std::mutex tls_mutex_;
  std::thread reader_;
  // Keepalive state (all under state_mutex_ unless noted).
  std::thread ka_thread_;
  bool ka_started_ = false;
  bool ka_stop_ = false;
  bool ka_ack_pending_ = false;
  int ka_pings_without_data_ = 0;
  bool ka_data_since_ping_ = false;
  std::mutex write_mutex_;   // serializes socket writes
  std::mutex state_mutex_;   // streams_, windows, settings, error
  std::condition_variable state_cv_;
  std::map<int32_t, std::shared_ptr<Stream>> streams_;
  int32_t next_stream_id_ = 1;
  std::string error_;
  bool dead_ = false;
  // First server SETTINGS seen: data senders briefly wait for it so the
  // body is chunked under the server's real limits, not the defaults.
  bool peer_settings_received_ = false;

  // Flow control / peer settings.
  int64_t conn_send_window_ = 65535;
  int64_t peer_initial_window_ = 65535;
  size_t peer_max_frame_ = 16384;

  HpackDecoder hpack_;
  // HEADERS accumulation across CONTINUATION frames.
  int32_t continuation_sid_ = 0;
  std::string continuation_buf_;
  bool continuation_end_stream_ = false;
};

}  // namespace h2
}  // namespace tpuclient
