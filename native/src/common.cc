#include "tpuclient/common.h"

#include <zlib.h>

#include <algorithm>
#include <cstdlib>
#include <cstring>

namespace tpuclient {

namespace zutil {

Error Deflate(const std::string& in, bool gzip, std::string* out) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED,
                   gzip ? 15 | 16 : 15, 8, Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("failed to initialize compression", 400);
  }
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = static_cast<uInt>(out->size());
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) {
    return Error("compression failed (zlib rc " + std::to_string(rc) + ")",
                 400);
  }
  out->resize(zs.total_out);
  return Error::Success();
}

Error Inflate(const std::string& in, std::string* out) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  // 15 | 32: auto-detect zlib vs gzip framing.
  if (inflateInit2(&zs, 15 | 32) != Z_OK) {
    return Error("failed to initialize decompression", 400);
  }
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  std::string buf(std::max<size_t>(in.size() * 4, 16384), '\0');
  int rc = Z_OK;
  while (rc == Z_OK) {
    zs.next_out = reinterpret_cast<Bytef*>(&buf[0]);
    zs.avail_out = static_cast<uInt>(buf.size());
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc == Z_OK || rc == Z_STREAM_END) {
      out->append(buf.data(), buf.size() - zs.avail_out);
    }
    if (rc == Z_OK && zs.avail_in == 0 && zs.avail_out != 0) break;
  }
  inflateEnd(&zs);
  if (rc != Z_STREAM_END) {
    return Error("decompression failed (zlib rc " + std::to_string(rc) + ")",
                 400);
  }
  return Error::Success();
}

}  // namespace zutil

size_t DtypeByteSize(const std::string& datatype) {
  if (datatype == "BOOL" || datatype == "INT8" || datatype == "UINT8")
    return 1;
  if (datatype == "INT16" || datatype == "UINT16" || datatype == "FP16" ||
      datatype == "BF16")
    return 2;
  if (datatype == "INT32" || datatype == "UINT32" || datatype == "FP32")
    return 4;
  if (datatype == "INT64" || datatype == "UINT64" || datatype == "FP64")
    return 8;
  return 0;  // BYTES / unknown: variable
}

// ---------------------------------------------------------------------------
// InferInput
// ---------------------------------------------------------------------------

std::string SplitUrl(const std::string& url, int default_port,
                     std::string* host, int* port) {
  std::string scheme;
  std::string hostport = url;
  auto sep = hostport.find("://");
  if (sep != std::string::npos) {
    scheme = hostport.substr(0, sep);
    hostport = hostport.substr(sep + 3);
  }
  *host = hostport;
  *port = default_port;
  if (!hostport.empty() && hostport[0] == '[') {
    // Bracketed IPv6 literal — strip brackets for getaddrinfo/TLS checks.
    auto rb = hostport.find(']');
    if (rb != std::string::npos) {
      *host = hostport.substr(1, rb - 1);
      if (rb + 1 < hostport.size() && hostport[rb + 1] == ':') {
        *port = atoi(hostport.c_str() + rb + 2);
      }
    }
  } else if (std::count(hostport.begin(), hostport.end(), ':') > 1) {
    *host = hostport;  // bare IPv6 literal, no port suffix
  } else {
    auto colon = hostport.rfind(':');
    if (colon != std::string::npos) {
      *host = hostport.substr(0, colon);
      *port = atoi(hostport.c_str() + colon + 1);
    }
  }
  return scheme;
}

Error InferInput::Create(InferInput** input, const std::string& name,
                         const std::vector<int64_t>& dims,
                         const std::string& datatype) {
  *input = new InferInput(name, dims, datatype);
  return Error::Success();
}

Error InferInput::SetShape(const std::vector<int64_t>& dims) {
  shape_ = dims;
  return Error::Success();
}

Error InferInput::AppendRaw(const uint8_t* data, size_t byte_size) {
  if (IsSharedMemory()) {
    return Error("can not append raw data to a shared-memory input '" + name_ +
                     "'",
                 400);
  }
  bufs_.emplace_back(data, byte_size);
  total_byte_size_ += byte_size;
  return Error::Success();
}

Error InferInput::AppendFromString(const std::vector<std::string>& strings) {
  std::string serialized;
  SerializeStringTensor(strings, &serialized);
  owned_.push_back(std::move(serialized));
  const std::string& s = owned_.back();
  return AppendRaw(reinterpret_cast<const uint8_t*>(s.data()), s.size());
}

Error InferInput::SetSharedMemory(const std::string& region_name,
                                  size_t byte_size, size_t offset) {
  if (!bufs_.empty()) {
    return Error("can not set shared memory on input '" + name_ +
                     "' with raw data appended",
                 400);
  }
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success();
}

Error InferInput::Reset() {
  bufs_.clear();
  owned_.clear();
  total_byte_size_ = 0;
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success();
}

void InferInput::CopyTo(std::string* out) const {
  out->reserve(out->size() + total_byte_size_);
  for (const auto& buf : bufs_) {
    out->append(reinterpret_cast<const char*>(buf.first), buf.second);
  }
}

// ---------------------------------------------------------------------------
// InferRequestedOutput
// ---------------------------------------------------------------------------

Error InferRequestedOutput::Create(InferRequestedOutput** output,
                                   const std::string& name,
                                   size_t class_count) {
  *output = new InferRequestedOutput(name, class_count);
  return Error::Success();
}

Error InferRequestedOutput::SetSharedMemory(const std::string& region_name,
                                            size_t byte_size, size_t offset) {
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success();
}

Error InferRequestedOutput::UnsetSharedMemory() {
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success();
}

// ---------------------------------------------------------------------------
// BYTES codec
// ---------------------------------------------------------------------------

void SerializeStringTensor(const std::vector<std::string>& strings,
                           std::string* out) {
  size_t total = 0;
  for (const auto& s : strings) total += 4 + s.size();
  out->reserve(out->size() + total);
  for (const auto& s : strings) {
    uint32_t len = static_cast<uint32_t>(s.size());
    char lenbuf[4];
    memcpy(lenbuf, &len, 4);  // little-endian on all supported targets
    out->append(lenbuf, 4);
    out->append(s);
  }
}

Error DeserializeStringTensor(const uint8_t* buf, size_t byte_size,
                              std::vector<std::string>* out) {
  size_t pos = 0;
  while (pos + 4 <= byte_size) {
    uint32_t len;
    memcpy(&len, buf + pos, 4);
    pos += 4;
    if (pos + len > byte_size) {
      return Error("malformed BYTES tensor: element length " +
                       std::to_string(len) + " exceeds buffer",
                   400);
    }
    out->emplace_back(reinterpret_cast<const char*>(buf + pos), len);
    pos += len;
  }
  if (pos != byte_size) {
    return Error("malformed BYTES tensor: trailing bytes", 400);
  }
  return Error::Success();
}

Error InferResult::StringData(const std::string& output_name,
                              std::vector<std::string>* string_result) const {
  const uint8_t* buf;
  size_t byte_size;
  Error err = RawData(output_name, &buf, &byte_size);
  if (!err.IsOk()) return err;
  return DeserializeStringTensor(buf, byte_size, string_result);
}

std::string SanitizeForLog(const std::string& s, size_t cap) {
  std::string out;
  out.reserve(s.size() < cap ? s.size() : cap);
  for (size_t i = 0; i < s.size() && i < cap; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    out.push_back((c >= 0x20 && c < 0x7f) ? static_cast<char>(c) : '.');
  }
  if (s.size() > cap) out += "...";
  return out;
}

}  // namespace tpuclient
