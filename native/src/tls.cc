// Runtime-bound OpenSSL 3 client shim — see tls.h for why dlopen.
//
// Only stable, ABI-frozen entry points are used (the same set every
// libssl-linked program of the last decade calls); prototypes are declared
// here by hand because the image has no openssl headers.

#include "tpuclient/tls.h"

#include <arpa/inet.h>
#include <dlfcn.h>

#include <cstdio>
#include <mutex>

namespace tpuclient {

namespace {

// ---- minimal OpenSSL ABI surface (opaque pointers throughout) -------------
struct OpenSsl {
  // libssl
  const void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  int (*SSL_CTX_set_alpn_protos)(void*, const unsigned char*, unsigned);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_set1_host)(void*, const char*);
  void* (*SSL_get0_param)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);  // NOLINT(runtime/int)
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_shutdown)(void*);
  int (*SSL_get_error)(const void*, int);
  // libcrypto
  unsigned long (*ERR_get_error)();  // NOLINT(runtime/int)
  void (*ERR_error_string_n)(unsigned long, char*, size_t);  // NOLINT
  int (*X509_VERIFY_PARAM_set1_ip_asc)(void*, const char*);

  bool ok = false;
};

constexpr int kSslVerifyNone = 0;
constexpr int kSslVerifyPeer = 1;
constexpr int kSslFiletypePem = 1;
constexpr int kSslCtrlSetTlsextHostname = 55;  // SSL_CTRL_SET_TLSEXT_HOSTNAME
constexpr long kTlsextNametypeHostName = 0;    // NOLINT(runtime/int)
constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;
constexpr int kSslErrorZeroReturn = 6;

const OpenSsl& Lib() {
  static OpenSsl lib;
  static std::once_flag once;
  std::call_once(once, [] {
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) ssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (crypto == nullptr) crypto = dlopen("libcrypto.so", RTLD_NOW);
    if (ssl == nullptr || crypto == nullptr) return;
    bool all = true;
    auto bind = [&all](void* lib_handle, const char* name) -> void* {
      void* sym = dlsym(lib_handle, name);
      if (sym == nullptr) all = false;
      return sym;
    };
#define TPU_BIND(handle, field) \
  lib.field = reinterpret_cast<decltype(lib.field)>(bind(handle, #field))
    TPU_BIND(ssl, TLS_client_method);
    TPU_BIND(ssl, SSL_CTX_new);
    TPU_BIND(ssl, SSL_CTX_free);
    TPU_BIND(ssl, SSL_CTX_set_verify);
    TPU_BIND(ssl, SSL_CTX_set_default_verify_paths);
    TPU_BIND(ssl, SSL_CTX_load_verify_locations);
    TPU_BIND(ssl, SSL_CTX_use_certificate_chain_file);
    TPU_BIND(ssl, SSL_CTX_use_PrivateKey_file);
    TPU_BIND(ssl, SSL_CTX_set_alpn_protos);
    TPU_BIND(ssl, SSL_new);
    TPU_BIND(ssl, SSL_free);
    TPU_BIND(ssl, SSL_set_fd);
    TPU_BIND(ssl, SSL_set1_host);
    TPU_BIND(ssl, SSL_get0_param);
    TPU_BIND(ssl, SSL_ctrl);
    TPU_BIND(ssl, SSL_connect);
    TPU_BIND(ssl, SSL_read);
    TPU_BIND(ssl, SSL_write);
    TPU_BIND(ssl, SSL_shutdown);
    TPU_BIND(ssl, SSL_get_error);
    TPU_BIND(crypto, ERR_get_error);
    TPU_BIND(crypto, X509_VERIFY_PARAM_set1_ip_asc);
    TPU_BIND(crypto, ERR_error_string_n);
#undef TPU_BIND
    lib.ok = all;
  });
  return lib;
}

std::string LastSslError(const OpenSsl& ssl, const char* fallback) {
  unsigned long code = ssl.ERR_get_error ? ssl.ERR_get_error() : 0;
  if (code == 0) return fallback;
  char buf[256];
  ssl.ERR_error_string_n(code, buf, sizeof(buf));
  return std::string(buf);
}

}  // namespace

bool TlsSession::Available() { return Lib().ok; }

TlsSession::~TlsSession() { Close(); }

void TlsSession::Close() {
  const OpenSsl& lib = Lib();
  if (ssl_ != nullptr && lib.ok) {
    lib.SSL_shutdown(ssl_);  // best-effort close_notify, ignore result
    lib.SSL_free(ssl_);
  }
  ssl_ = nullptr;
  if (ctx_ != nullptr && lib.ok) lib.SSL_CTX_free(ctx_);
  ctx_ = nullptr;
}

Error TlsSession::Handshake(int fd, const std::string& host,
                            const TlsOptions& opts) {
  const OpenSsl& lib = Lib();
  if (!lib.ok) {
    return Error(
        "TLS requested but libssl.so.3 could not be loaded on this machine",
        400);
  }
  ctx_ = lib.SSL_CTX_new(lib.TLS_client_method());
  if (ctx_ == nullptr) {
    return Error("SSL_CTX_new failed: " + LastSslError(lib, "unknown"), 400);
  }
  if (!opts.root_certificates.empty()) {
    if (lib.SSL_CTX_load_verify_locations(
            ctx_, opts.root_certificates.c_str(), nullptr) != 1) {
      Error err("failed to load root certificates '" +
                    opts.root_certificates +
                    "': " + LastSslError(lib, "unknown"),
                400);
      Close();
      return err;
    }
  } else {
    lib.SSL_CTX_set_default_verify_paths(ctx_);
  }
  if (!opts.certificate_chain.empty() &&
      lib.SSL_CTX_use_certificate_chain_file(
          ctx_, opts.certificate_chain.c_str()) != 1) {
    Error err("failed to load certificate chain '" + opts.certificate_chain +
                  "': " + LastSslError(lib, "unknown"),
              400);
    Close();
    return err;
  }
  if (!opts.private_key.empty() &&
      lib.SSL_CTX_use_PrivateKey_file(ctx_, opts.private_key.c_str(),
                                      kSslFiletypePem) != 1) {
    Error err("failed to load private key '" + opts.private_key +
                  "': " + LastSslError(lib, "unknown"),
              400);
    Close();
    return err;
  }
  lib.SSL_CTX_set_verify(
      ctx_, opts.verify_peer ? kSslVerifyPeer : kSslVerifyNone, nullptr);
  if (!opts.alpn.empty()) {
    // Wire format: length-prefixed protocol list.
    std::string wire;
    wire.push_back(static_cast<char>(opts.alpn.size()));
    wire += opts.alpn;
    lib.SSL_CTX_set_alpn_protos(
        ctx_, reinterpret_cast<const unsigned char*>(wire.data()),
        static_cast<unsigned>(wire.size()));
  }

  ssl_ = lib.SSL_new(ctx_);
  if (ssl_ == nullptr) {
    Error err("SSL_new failed: " + LastSslError(lib, "unknown"), 400);
    Close();
    return err;
  }
  lib.SSL_set_fd(ssl_, fd);
  const std::string& name =
      opts.server_name.empty() ? host : opts.server_name;
  // SNI (harmless for IP literals — servers ignore unknown names).
  lib.SSL_ctrl(ssl_, kSslCtrlSetTlsextHostname, kTlsextNametypeHostName,
               const_cast<char*>(name.c_str()));
  if (opts.verify_peer && opts.verify_host) {
    // IP literals match against IP SANs (X509_VERIFY_PARAM_set1_ip_asc);
    // SSL_set1_host only checks DNS names.
    unsigned char ipbuf[16];
    bool is_ip = inet_pton(AF_INET, name.c_str(), ipbuf) == 1 ||
                 inet_pton(AF_INET6, name.c_str(), ipbuf) == 1;
    if (is_ip) {
      lib.X509_VERIFY_PARAM_set1_ip_asc(lib.SSL_get0_param(ssl_),
                                        name.c_str());
    } else {
      lib.SSL_set1_host(ssl_, name.c_str());
    }
  }
  if (lib.SSL_connect(ssl_) != 1) {
    Error err("TLS handshake with " + host +
                  " failed: " + LastSslError(lib, "handshake error"),
              400);
    Close();
    return err;
  }
  return Error::Success();
}

ssize_t TlsSession::Read(void* buf, size_t n, Error* err) {
  const OpenSsl& lib = Lib();
  int rc = lib.SSL_read(ssl_, buf,
                        static_cast<int>(n > 1 << 30 ? 1 << 30 : n));
  if (rc > 0) return rc;
  int code = lib.SSL_get_error(ssl_, rc);
  if (code == kSslErrorZeroReturn) return 0;  // clean TLS close
  if (code == kSslErrorWantRead) return kWantRead;
  if (code == kSslErrorWantWrite) return kWantWrite;
  if (err != nullptr) {
    *err = Error("TLS read failed: " + LastSslError(lib, "connection error"),
                 400);
  }
  return -1;
}

ssize_t TlsSession::Write(const void* buf, size_t n, Error* err) {
  const OpenSsl& lib = Lib();
  int rc = lib.SSL_write(ssl_, buf,
                         static_cast<int>(n > 1 << 30 ? 1 << 30 : n));
  if (rc > 0) return rc;
  int code = lib.SSL_get_error(ssl_, rc);
  if (code == kSslErrorWantRead) return kWantRead;
  if (code == kSslErrorWantWrite) return kWantWrite;
  if (err != nullptr) {
    *err = Error("TLS write failed: " + LastSslError(lib, "connection error"),
                 400);
  }
  return -1;
}


}  // namespace tpuclient
