// gRPC client implementation over the in-tree HTTP/2 transport. See
// grpc_client.h for the role map onto the reference grpc_client.cc.

#include "tpuclient/grpc_client.h"

#include <algorithm>
#include <cctype>
#include <cstdlib>
#include <cstring>

#include "h2.h"

namespace tpuclient {

namespace {

constexpr const char* kServicePrefix = "/inference.GRPCInferenceService/";

// Process-global channel cache keyed by "host:port" (reference
// grpc_client.cc:48-123). Dead connections are replaced on next Create.
std::mutex& CacheMutex() {
  static std::mutex m;
  return m;
}
std::map<std::string, std::shared_ptr<h2::Connection>>& ChannelCache() {
  static auto* cache = new std::map<std::string,
                                    std::shared_ptr<h2::Connection>>();
  return *cache;
}

// gRPC message framing: 1-byte compressed flag + 4-byte BE length.
void FrameMessage(const std::string& payload, std::string* out,
                  bool compressed = false) {
  out->reserve(5 + payload.size());
  out->push_back(compressed ? 1 : 0);
  uint32_t n = uint32_t(payload.size());
  out->push_back(char(n >> 24));
  out->push_back(char(n >> 16));
  out->push_back(char(n >> 8));
  out->push_back(char(n));
  out->append(payload);
}

// Frames `payload`, compressing per `algo` (reference passes
// grpc_compression_algorithm per call, grpc_client.h:323-382; here the
// algorithm rides InferOptions). Sets *encoding to the grpc-encoding
// header value, or nullptr when sending identity.
Error BuildInferBody(const std::string& payload, GrpcCompression algo,
                     std::string* body, const char** encoding) {
  *encoding = nullptr;
  if (algo == GrpcCompression::NONE) {
    FrameMessage(payload, body);
    return Error::Success();
  }
  std::string z;
  Error err = zutil::Deflate(payload, algo == GrpcCompression::GZIP, &z);
  if (!err.IsOk()) return err;
  FrameMessage(z, body, true);
  *encoding = algo == GrpcCompression::GZIP ? "gzip" : "deflate";
  return Error::Success();
}

// Pops one complete framed message out of buf[*pos..]; false if incomplete.
// Messages with the compressed flag set are inflated (the client always
// advertises `grpc-accept-encoding: identity, deflate, gzip`; both wire
// formats are auto-detected by the inflater).
bool PopMessage(const std::string& buf, size_t* pos, std::string* msg,
                Error* err) {
  if (buf.size() - *pos < 5) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf.data()) + *pos;
  uint32_t len = (uint32_t(p[1]) << 24) | (uint32_t(p[2]) << 16) |
                 (uint32_t(p[3]) << 8) | uint32_t(p[4]);
  if (buf.size() - *pos - 5 < len) return false;
  if (p[0] != 0) {
    std::string z;
    z.assign(buf, *pos + 5, len);
    msg->clear();
    Error ierr = zutil::Inflate(z, msg);
    if (!ierr.IsOk()) {
      *err = Error("gRPC: failed to decompress message: " + ierr.Message());
      return false;
    }
  } else {
    msg->assign(buf, *pos + 5, len);
  }
  *pos += 5 + len;
  return true;
}

std::string PercentDecode(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size() && isxdigit(in[i + 1]) &&
        isxdigit(in[i + 2])) {
      out.push_back(char(std::stoi(in.substr(i + 1, 2), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(in[i]);
    }
  }
  return out;
}

const std::string* FindHeader(const h2::HeaderList& headers,
                              const std::string& name) {
  for (const auto& h : headers) {
    if (h.first == name) return &h.second;
  }
  return nullptr;
}

// Extracts the gRPC status from a finished stream's header/trailer blocks.
// found=false when neither block carries grpc-status (stream died early).
Error GrpcStatusFromStream(const h2::Connection::Stream& s, bool* found) {
  *found = false;
  const std::string* status = FindHeader(s.trailers, "grpc-status");
  const std::string* message = FindHeader(s.trailers, "grpc-message");
  if (status == nullptr) {
    status = FindHeader(s.headers, "grpc-status");
    message = FindHeader(s.headers, "grpc-message");
  }
  if (status == nullptr) return Error("gRPC: no status in response");
  *found = true;
  int code = atoi(status->c_str());
  if (code == 0) return Error::Success();
  std::string msg = message != nullptr ? PercentDecode(*message)
                                       : "(no message)";
  // DEADLINE_EXCEEDED(4) maps onto the library's timeout status 499 the way
  // the HTTP client maps curl timeouts (reference http_client.cc:1278-1279).
  return Error("gRPC error " + std::to_string(code) + ": " + msg,
               code == 4 ? 499 : code);
}

// Extracts status + the single framed message from a finished unary stream
// (shared by Rpc, Infer, and the async completion worker). conn_error
// carries the connection-level failure reason (GOAWAY text, socket error)
// so reset diagnostics keep their root cause.
Error ExtractUnaryResult(const h2::Connection::Stream& s,
                         const std::string& conn_error, std::string* msg) {
  if (s.reset && !s.end_stream) {
    return Error("gRPC: stream reset (code " + std::to_string(s.reset_code) +
                 ")" + (conn_error.empty() ? "" : ": " + conn_error));
  }
  bool have = false;
  Error status = GrpcStatusFromStream(s, &have);
  if (!status.IsOk()) return status;
  size_t pos = 0;
  Error perr = Error::Success();
  if (!PopMessage(s.data, &pos, msg, &perr)) {
    return perr.IsOk() ? Error("gRPC: empty unary response") : perr;
  }
  return Error::Success();
}

h2::HeaderList CallHeaders(const std::string& authority,
                           const std::string& method_path,
                           uint64_t timeout_us, const GrpcHeaders& extra,
                           bool secure = false) {
  h2::HeaderList h = {
      {":method", "POST"},
      // gRPC-over-HTTP/2 mapping: :scheme reflects the transport; strict
      // intermediaries (Envoy, grpc-go) validate it.
      {":scheme", secure ? "https" : "http"},
      {":path", method_path},
      {":authority", authority},
      {"te", "trailers"},
      {"content-type", "application/grpc"},
      {"user-agent", "tpuclient-grpc/1.0"},
      // Always advertised: PopMessage inflates compressed responses
      // (gzip and zlib framings auto-detected).
      {"grpc-accept-encoding", "identity, deflate, gzip"},
  };
  if (timeout_us > 0) {
    // gRPC-over-HTTP/2 caps TimeoutValue at 8 ASCII digits; scale to
    // coarser units (m/S/M) when microseconds would overflow that, the
    // same way grpc-core's timeout encoder does.
    uint64_t v = timeout_us;
    const char* unit = "u";
    if (v > 99999999ULL) {
      v = (v + 999) / 1000;  // milliseconds, round up
      unit = "m";
    }
    if (v > 99999999ULL) {
      v = (v + 999) / 1000;  // seconds
      unit = "S";
    }
    if (v > 99999999ULL) {
      v = (v + 59) / 60;  // minutes
      unit = "M";
    }
    if (v > 99999999ULL) {
      v = (v + 59) / 60;  // hours
      unit = "H";
    }
    // Coarsest unit exhausted: clamp like grpc-core ("infinite" deadline).
    if (v > 99999999ULL) v = 99999999ULL;
    h.emplace_back("grpc-timeout", std::to_string(v) + unit);
  }
  for (const auto& kv : extra) h.emplace_back(kv.first, kv.second);
  return h;
}

uint64_t DeadlineNs(uint64_t timeout_us) {
  return timeout_us == 0 ? 0
                         : RequestTimers::Now() + timeout_us * 1000;
}

void SetParam(google::protobuf::Map<std::string, inference::InferParameter>*
                  params,
              const std::string& key, int64_t value) {
  (*params)[key].set_int64_param(value);
}
void SetParamBool(google::protobuf::Map<std::string,
                                        inference::InferParameter>* params,
                  const std::string& key, bool value) {
  (*params)[key].set_bool_param(value);
}
void SetParamU64(google::protobuf::Map<std::string,
                                       inference::InferParameter>* params,
                 const std::string& key, uint64_t value) {
  (*params)[key].set_uint64_param(value);
}
void SetParamStr(google::protobuf::Map<std::string,
                                       inference::InferParameter>* params,
                 const std::string& key, const std::string& value) {
  (*params)[key].set_string_param(value);
}

}  // namespace

// ------------------------------------------------------- InferResultGrpc ----

Error InferResultGrpc::Create(
    InferResult** result,
    std::shared_ptr<inference::ModelInferResponse> response, Error status) {
  *result = new InferResultGrpc(std::move(response), std::move(status));
  return Error::Success();
}

InferResultGrpc::InferResultGrpc(
    std::shared_ptr<inference::ModelInferResponse> response, Error status)
    : response_(std::move(response)), status_(std::move(status)) {
  if (response_ != nullptr) {
    // raw_output_contents has no entry for shared-memory outputs (the server
    // skips them, grpc_server.py _response_to_proto), so the raw index must
    // be counted over non-shm outputs only.
    int raw_idx = 0;
    for (int i = 0; i < response_->outputs_size(); ++i) {
      const auto& out = response_->outputs(i);
      index_[out.name()] = i;
      bool in_shm = out.parameters().count("shared_memory_region") > 0;
      raw_index_[out.name()] = in_shm ? -1 : raw_idx++;
    }
  }
}

Error InferResultGrpc::ModelName(std::string* name) const {
  if (!status_.IsOk()) return status_;
  *name = response_->model_name();
  return Error::Success();
}

Error InferResultGrpc::ModelVersion(std::string* version) const {
  if (!status_.IsOk()) return status_;
  *version = response_->model_version();
  return Error::Success();
}

Error InferResultGrpc::Id(std::string* id) const {
  // Usable on error results too (per-request stream errors carry the id
  // so the caller can attribute the failure); only a missing proto makes
  // the id unavailable.
  if (response_ == nullptr) {
    if (!status_.IsOk()) return status_;
    return Error("no response");
  }
  *id = response_->id();
  return Error::Success();
}

Error InferResultGrpc::Shape(const std::string& output_name,
                             std::vector<int64_t>* shape) const {
  if (!status_.IsOk()) return status_;
  auto it = index_.find(output_name);
  if (it == index_.end()) {
    return Error("output '" + output_name + "' not found");
  }
  shape->assign(response_->outputs(it->second).shape().begin(),
                response_->outputs(it->second).shape().end());
  return Error::Success();
}

Error InferResultGrpc::Datatype(const std::string& output_name,
                                std::string* datatype) const {
  if (!status_.IsOk()) return status_;
  auto it = index_.find(output_name);
  if (it == index_.end()) {
    return Error("output '" + output_name + "' not found");
  }
  *datatype = response_->outputs(it->second).datatype();
  return Error::Success();
}

Error InferResultGrpc::RawData(const std::string& output_name,
                               const uint8_t** buf, size_t* byte_size) const {
  if (!status_.IsOk()) return status_;
  auto it = raw_index_.find(output_name);
  if (it == raw_index_.end()) {
    return Error("output '" + output_name + "' not found");
  }
  if (it->second < 0 || it->second >= response_->raw_output_contents_size()) {
    // Output lives in shared memory — no inline bytes on the wire.
    *buf = nullptr;
    *byte_size = 0;
    return Error::Success();
  }
  const std::string& raw = response_->raw_output_contents(it->second);
  *buf = reinterpret_cast<const uint8_t*>(raw.data());
  *byte_size = raw.size();
  return Error::Success();
}

Error InferResultGrpc::RequestStatus() const { return status_; }

std::string InferResultGrpc::DebugString() const {
  if (!status_.IsOk()) return "error: " + status_.Message();
  return response_->ShortDebugString();
}

// -------------------------------------------- InferenceServerGrpcClient ----

InferenceServerGrpcClient::InferenceServerGrpcClient(bool verbose)
    : InferenceServerClient(verbose) {}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client, const std::string& url,
    bool verbose, bool use_cached_channel, bool use_ssl,
    const SslOptions& ssl_options, const KeepAliveOptions& keepalive_options) {
  client->reset(new InferenceServerGrpcClient(verbose));
  Error err = (*client)->Connect(url, use_cached_channel, use_ssl,
                                 ssl_options, keepalive_options);
  if (!err.IsOk()) client->reset();
  return err;
}

InferenceServerGrpcClient::~InferenceServerGrpcClient() {
  StopStream();
  async_exit_ = true;
  async_cv_.notify_all();
  if (async_worker_.joinable()) async_worker_.join();
}

Error InferenceServerGrpcClient::Connect(
    const std::string& url, bool use_cached_channel, bool use_ssl,
    const SslOptions& ssl_options,
    const KeepAliveOptions& keepalive_options) {
  std::string host;
  int port = 8001;
  std::string proto = SplitUrl(url, 8001, &host, &port);
  if (proto == "https" || proto == "grpcs") use_ssl = true;
  authority_ = host.find(':') != std::string::npos
                   ? "[" + host + "]:" + std::to_string(port)
                   : host + ":" + std::to_string(port);

  TlsOptions tls;
  tls.use_ssl = use_ssl;
  tls.root_certificates = ssl_options.root_certificates;
  tls.private_key = ssl_options.private_key;
  tls.certificate_chain = ssl_options.certificate_chain;
  tls.alpn = "h2";
  const TlsOptions* tls_ptr = use_ssl ? &tls : nullptr;
  auto start_keepalive = [&keepalive_options](h2::Connection* c) {
    c->StartKeepalive(keepalive_options.keepalive_time_ms,
                      keepalive_options.keepalive_timeout_ms,
                      keepalive_options.keepalive_permit_without_calls,
                      keepalive_options.http2_max_pings_without_data);
  };

  if (use_cached_channel) {
    // TLS and cleartext channels to the same authority are distinct.
    const std::string cache_key =
        (use_ssl ? "grpcs://" : "grpc://") + authority_;
    {
      std::lock_guard<std::mutex> lk(CacheMutex());
      auto it = ChannelCache().find(cache_key);
      if (it != ChannelCache().end() && it->second->Alive()) {
        conn_ = it->second;
        // Adopting a cached channel must still honor this client's
        // keepalive request (first requester wins; StartKeepalive is
        // idempotent).
        start_keepalive(conn_.get());
        return Error::Success();
      }
    }
    // Connect OUTSIDE the cache lock: a slow/unreachable host must not
    // stall unrelated clients' Create calls. Losing the insert race just
    // means adopting the winner's connection.
    auto conn = std::make_shared<h2::Connection>();
    Error err = conn->Connect(host, port, tls_ptr);
    if (!err.IsOk()) return err;
    start_keepalive(conn.get());
    std::lock_guard<std::mutex> lk(CacheMutex());
    auto it = ChannelCache().find(cache_key);
    if (it != ChannelCache().end() && it->second->Alive()) {
      conn_ = it->second;  // another thread won; drop ours
      start_keepalive(conn_.get());
      return Error::Success();
    }
    ChannelCache()[cache_key] = conn;
    conn_ = conn;
    return Error::Success();
  }
  conn_ = std::make_shared<h2::Connection>();
  Error err = conn_->Connect(host, port, tls_ptr);
  if (!err.IsOk()) return err;
  start_keepalive(conn_.get());
  return Error::Success();
}

Error GrpcUnaryCall(h2::Connection* conn, const std::string& authority,
                    const std::string& method_path,
                    const google::protobuf::Message& request,
                    google::protobuf::Message* response, uint64_t timeout_us,
                    const GrpcHeaders& headers) {
  std::string payload;
  if (!request.SerializeToString(&payload)) {
    return Error("failed to serialize " + method_path + " request");
  }
  std::string body;
  FrameMessage(payload, &body);

  uint64_t deadline = DeadlineNs(timeout_us);
  int32_t sid = 0;
  Error err = conn->StartStream(
      CallHeaders(authority, method_path, timeout_us, headers, conn->Tls()),
      false, &sid);
  if (!err.IsOk()) return err;
  err = conn->SendData(sid, reinterpret_cast<const uint8_t*>(body.data()),
                       body.size(), true, deadline);
  if (!err.IsOk()) {
    conn->CloseStream(sid);
    return err;
  }
  // Unary: wait for the peer half-close (SIZE_MAX min_bytes can never be
  // satisfied by data alone, so this unblocks on end_stream/reset/deadline).
  if (!conn->WaitStream(sid, SIZE_MAX, deadline)) {
    conn->CloseStream(sid);
    return Error("Deadline Exceeded", 499);
  }
  std::string msg;
  Error status("stream vanished");
  // ConnectionError() locks the connection state mutex, which WithStream's
  // callback already holds — read it before entering the callback.
  std::string conn_error = conn->ConnectionError();
  conn->WithStream(sid, [&](h2::Connection::Stream& s) {
    status = ExtractUnaryResult(s, conn_error, &msg);
  });
  conn->CloseStream(sid);
  if (!status.IsOk()) return status;
  if (!response->ParseFromString(msg)) {
    return Error("failed to parse " + method_path + " response");
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::Rpc(const std::string& method,
                                     const google::protobuf::Message& request,
                                     google::protobuf::Message* response,
                                     uint64_t timeout_us,
                                     const GrpcHeaders& headers) {
  return GrpcUnaryCall(conn_.get(), authority_,
                       std::string(kServicePrefix) + method, request,
                       response, timeout_us, headers);
}

// -- control plane -----------------------------------------------------------

Error InferenceServerGrpcClient::IsServerLive(bool* live) {
  inference::ServerLiveRequest req;
  inference::ServerLiveResponse resp;
  Error err = Rpc("ServerLive", req, &resp);
  if (err.IsOk()) *live = resp.live();
  return err;
}

Error InferenceServerGrpcClient::IsServerReady(bool* ready) {
  inference::ServerReadyRequest req;
  inference::ServerReadyResponse resp;
  Error err = Rpc("ServerReady", req, &resp);
  if (err.IsOk()) *ready = resp.ready();
  return err;
}

Error InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version) {
  inference::ModelReadyRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  inference::ModelReadyResponse resp;
  Error err = Rpc("ModelReady", req, &resp);
  if (err.IsOk()) *ready = resp.ready();
  return err;
}

Error InferenceServerGrpcClient::ServerMetadata(
    inference::ServerMetadataResponse* response) {
  inference::ServerMetadataRequest req;
  return Rpc("ServerMetadata", req, response);
}

Error InferenceServerGrpcClient::ModelMetadata(
    inference::ModelMetadataResponse* response, const std::string& model_name,
    const std::string& model_version) {
  inference::ModelMetadataRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc("ModelMetadata", req, response);
}

Error InferenceServerGrpcClient::ModelConfig(
    inference::ModelConfigResponse* response, const std::string& model_name,
    const std::string& model_version) {
  inference::ModelConfigRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc("ModelConfig", req, response);
}

Error InferenceServerGrpcClient::ModelRepositoryIndex(
    inference::RepositoryIndexResponse* response) {
  inference::RepositoryIndexRequest req;
  return Rpc("RepositoryIndex", req, response);
}

Error InferenceServerGrpcClient::LoadModel(const std::string& model_name) {
  inference::RepositoryModelLoadRequest req;
  req.set_model_name(model_name);
  inference::RepositoryModelLoadResponse resp;
  return Rpc("RepositoryModelLoad", req, &resp);
}

Error InferenceServerGrpcClient::UnloadModel(const std::string& model_name) {
  inference::RepositoryModelUnloadRequest req;
  req.set_model_name(model_name);
  inference::RepositoryModelUnloadResponse resp;
  return Rpc("RepositoryModelUnload", req, &resp);
}

Error InferenceServerGrpcClient::ModelInferenceStatistics(
    inference::ModelStatisticsResponse* response,
    const std::string& model_name, const std::string& model_version) {
  inference::ModelStatisticsRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc("ModelStatistics", req, response);
}

Error InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset) {
  inference::SystemSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_key(key);
  req.set_offset(offset);
  req.set_byte_size(byte_size);
  inference::SystemSharedMemoryRegisterResponse resp;
  return Rpc("SystemSharedMemoryRegister", req, &resp);
}

Error InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name) {
  inference::SystemSharedMemoryUnregisterRequest req;
  req.set_name(name);
  inference::SystemSharedMemoryUnregisterResponse resp;
  return Rpc("SystemSharedMemoryUnregister", req, &resp);
}

Error InferenceServerGrpcClient::SystemSharedMemoryStatus(
    inference::SystemSharedMemoryStatusResponse* response) {
  inference::SystemSharedMemoryStatusRequest req;
  return Rpc("SystemSharedMemoryStatus", req, response);
}

Error InferenceServerGrpcClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, int64_t device_id,
    size_t byte_size) {
  inference::TpuSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_raw_handle(raw_handle);
  req.set_device_id(device_id);
  req.set_byte_size(byte_size);
  inference::TpuSharedMemoryRegisterResponse resp;
  return Rpc("TpuSharedMemoryRegister", req, &resp);
}

Error InferenceServerGrpcClient::UnregisterTpuSharedMemory(
    const std::string& name) {
  inference::TpuSharedMemoryUnregisterRequest req;
  req.set_name(name);
  inference::TpuSharedMemoryUnregisterResponse resp;
  return Rpc("TpuSharedMemoryUnregister", req, &resp);
}

Error InferenceServerGrpcClient::TpuSharedMemoryStatus(
    inference::TpuSharedMemoryStatusResponse* response) {
  inference::TpuSharedMemoryStatusRequest req;
  return Rpc("TpuSharedMemoryStatus", req, response);
}

// -- infer request build (reference PreRunProcessing, grpc_client.cc:1084) --

void InferenceServerGrpcClient::BuildRequest(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    inference::ModelInferRequest* request) {
  // Clear() keeps protobuf arena/heap blocks around, giving the same
  // allocation-reuse benefit as the reference's submessage recycling.
  request->Clear();
  request->set_model_name(options.model_name);
  request->set_model_version(options.model_version);
  request->set_id(options.request_id);
  auto* params = request->mutable_parameters();
  if (options.sequence_id != 0) {
    SetParamU64(params, "sequence_id", options.sequence_id);
    SetParamBool(params, "sequence_start", options.sequence_start);
    SetParamBool(params, "sequence_end", options.sequence_end);
  }
  if (options.priority != 0) SetParamU64(params, "priority", options.priority);
  if (options.server_timeout_us != 0) {
    SetParamU64(params, "timeout", options.server_timeout_us);
  }
  for (const auto& kv : options.int_parameters) {
    (*params)[kv.first].set_int64_param(kv.second);
  }
  for (const auto& kv : options.string_parameters) {
    (*params)[kv.first].set_string_param(kv.second);
  }
  for (const auto& kv : options.bool_parameters) {
    (*params)[kv.first].set_bool_param(kv.second);
  }
  for (const InferInput* input : inputs) {
    auto* tensor = request->add_inputs();
    tensor->set_name(input->Name());
    tensor->set_datatype(input->Datatype());
    for (int64_t d : input->Shape()) tensor->add_shape(d);
    if (input->IsSharedMemory()) {
      auto* tparams = tensor->mutable_parameters();
      SetParamStr(tparams, "shared_memory_region", input->SharedMemoryName());
      SetParamU64(tparams, "shared_memory_byte_size",
                  input->SharedMemoryByteSize());
      if (input->SharedMemoryOffset() != 0) {
        SetParamU64(tparams, "shared_memory_offset",
                    input->SharedMemoryOffset());
      }
    } else {
      // Scatter-gather buffers concatenate into one raw content entry (the
      // hot memcpy path, reference grpc_client.cc raw_input_contents loop).
      std::string* raw = request->add_raw_input_contents();
      raw->reserve(input->TotalByteSize());
      for (const auto& buf : input->Buffers()) {
        raw->append(reinterpret_cast<const char*>(buf.first), buf.second);
      }
    }
  }
  for (const InferRequestedOutput* output : outputs) {
    auto* tensor = request->add_outputs();
    tensor->set_name(output->Name());
    auto* oparams = tensor->mutable_parameters();
    if (output->ClassCount() > 0) {
      SetParam(oparams, "classification", int64_t(output->ClassCount()));
    }
    if (output->IsSharedMemory()) {
      SetParamStr(oparams, "shared_memory_region", output->SharedMemoryName());
      SetParamU64(oparams, "shared_memory_byte_size",
                  output->SharedMemoryByteSize());
      if (output->SharedMemoryOffset() != 0) {
        SetParamU64(oparams, "shared_memory_offset",
                    output->SharedMemoryOffset());
      }
    }
  }
}

// -- sync infer --------------------------------------------------------------

Error InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const GrpcHeaders& headers) {
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);

  std::string payload;
  {
    std::lock_guard<std::mutex> lk(sync_mutex_);
    BuildRequest(options, inputs, outputs, &sync_request_);
    if (!sync_request_.SerializeToString(&payload)) {
      return Error("failed to serialize infer request");
    }
  }
  std::string body;
  const char* encoding = nullptr;
  Error cerr = BuildInferBody(payload, options.compression_algorithm, &body,
                              &encoding);
  if (!cerr.IsOk()) return cerr;

  uint64_t deadline = DeadlineNs(options.client_timeout_us);
  int32_t sid = 0;
  timers.Capture(RequestTimers::Kind::SEND_START);
  h2::HeaderList call_headers =
      CallHeaders(authority_, std::string(kServicePrefix) + "ModelInfer",
                  options.client_timeout_us, headers, conn_->Tls());
  if (encoding != nullptr) {
    call_headers.push_back({"grpc-encoding", encoding});
  }
  Error err = conn_->StartStream(call_headers, false, &sid);
  if (!err.IsOk()) return err;
  err = conn_->SendData(sid, reinterpret_cast<const uint8_t*>(body.data()),
                        body.size(), true, deadline);
  timers.Capture(RequestTimers::Kind::SEND_END);
  if (!err.IsOk()) {
    conn_->CloseStream(sid);
    return err;
  }
  if (!conn_->WaitStream(sid, SIZE_MAX, deadline)) {
    conn_->CloseStream(sid);
    return Error("Deadline Exceeded", 499);
  }
  timers.Capture(RequestTimers::Kind::RECV_START);
  auto response = std::make_shared<inference::ModelInferResponse>();
  Error status("stream vanished");
  std::string conn_error = conn_->ConnectionError();
  conn_->WithStream(sid, [&](h2::Connection::Stream& s) {
    std::string msg;
    status = ExtractUnaryResult(s, conn_error, &msg);
    if (status.IsOk() && !response->ParseFromString(msg)) {
      status = Error("failed to parse infer response");
    }
  });
  conn_->CloseStream(sid);
  timers.Capture(RequestTimers::Kind::RECV_END);
  timers.Capture(RequestTimers::Kind::REQUEST_END);
  if (!status.IsOk()) return status;
  UpdateInferStat(timers);
  return InferResultGrpc::Create(result, std::move(response));
}

// -- async infer -------------------------------------------------------------

Error InferenceServerGrpcClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const GrpcHeaders& headers) {
  if (callback == nullptr) {
    return Error("callback is required for AsyncInfer");
  }
  {
    // Lazy worker spawn (reference grpc_client.cc:934-936).
    std::lock_guard<std::mutex> lk(async_mutex_);
    if (!async_worker_.joinable()) {
      async_worker_ = std::thread([this] { AsyncWorker(); });
    }
  }

  auto job = std::make_shared<AsyncJob>();
  job->callback = std::move(callback);
  job->timers.Capture(RequestTimers::Kind::REQUEST_START);

  inference::ModelInferRequest request;
  BuildRequest(options, inputs, outputs, &request);
  std::string payload;
  if (!request.SerializeToString(&payload)) {
    return Error("failed to serialize infer request");
  }
  std::string body;
  const char* encoding = nullptr;
  Error cerr = BuildInferBody(payload, options.compression_algorithm, &body,
                              &encoding);
  if (!cerr.IsOk()) return cerr;

  uint64_t deadline = DeadlineNs(options.client_timeout_us);
  job->timers.Capture(RequestTimers::Kind::SEND_START);
  h2::HeaderList call_headers =
      CallHeaders(authority_, std::string(kServicePrefix) + "ModelInfer",
                  options.client_timeout_us, headers, conn_->Tls());
  if (encoding != nullptr) {
    call_headers.push_back({"grpc-encoding", encoding});
  }
  Error err = conn_->StartStream(call_headers, false, &job->sid);
  if (!err.IsOk()) return err;
  // Completion signal: the h2 reader calls on_event with its stream lock
  // held, so the handler must stay lock-free — it only pokes the worker cv.
  conn_->WithStream(job->sid, [this](h2::Connection::Stream& s) {
    s.on_event = [this] {
      async_events_.fetch_add(1);
      async_cv_.notify_all();
    };
  });
  err = conn_->SendData(job->sid,
                        reinterpret_cast<const uint8_t*>(body.data()),
                        body.size(), true, deadline);
  job->timers.Capture(RequestTimers::Kind::SEND_END);
  if (!err.IsOk()) {
    conn_->CloseStream(job->sid);
    return err;
  }
  {
    std::lock_guard<std::mutex> lk(async_mutex_);
    async_jobs_.push_back(job);
  }
  async_events_.fetch_add(1);
  async_cv_.notify_all();
  return Error::Success();
}

void InferenceServerGrpcClient::AsyncWorker() {
  // Drains completions, mirroring the reference's AsyncTransfer CQ loop
  // (grpc_client.cc:1225-1268). The timed wait is a backstop against the
  // (benign) lost-wakeup window of the lock-free on_event notify.
  uint64_t seen = 0;
  while (true) {
    std::vector<std::shared_ptr<AsyncJob>> jobs;
    {
      std::unique_lock<std::mutex> lk(async_mutex_);
      async_cv_.wait_for(lk, std::chrono::milliseconds(50), [&] {
        return async_exit_.load() || async_events_.load() != seen;
      });
      seen = async_events_.load();
      if (async_exit_.load()) {
        // Fail whatever is still in flight so callbacks always fire.
        jobs.assign(async_jobs_.begin(), async_jobs_.end());
        async_jobs_.clear();
        lk.unlock();
        for (auto& job : jobs) {
          conn_->CloseStream(job->sid);
          InferResult* result = nullptr;
          InferResultGrpc::Create(&result, nullptr,
                                  Error("client shutting down"));
          job->callback(result);
        }
        return;
      }
      jobs.assign(async_jobs_.begin(), async_jobs_.end());
    }
    for (auto& job : jobs) {
      bool done = false;
      Error status("stream vanished");
      auto response = std::make_shared<inference::ModelInferResponse>();
      std::string conn_error = conn_->ConnectionError();
      bool present = conn_->WithStream(
          job->sid, [&](h2::Connection::Stream& s) {
            if (!s.end_stream && !s.reset) return;
            done = true;
            std::string msg;
            status = ExtractUnaryResult(s, conn_error, &msg);
            if (status.IsOk() && !response->ParseFromString(msg)) {
              status = Error("failed to parse infer response");
            }
          });
      if (!present) {
        done = true;
        status = Error("stream closed before completion");
      }
      if (!done) continue;
      conn_->CloseStream(job->sid);
      {
        std::lock_guard<std::mutex> lk(async_mutex_);
        auto it = std::find(async_jobs_.begin(), async_jobs_.end(), job);
        if (it != async_jobs_.end()) async_jobs_.erase(it);
      }
      job->timers.Capture(RequestTimers::Kind::RECV_START);
      job->timers.Capture(RequestTimers::Kind::RECV_END);
      job->timers.Capture(RequestTimers::Kind::REQUEST_END);
      if (status.IsOk()) UpdateInferStat(job->timers);
      InferResult* result = nullptr;
      InferResultGrpc::Create(&result, std::move(response),
                              std::move(status));
      job->callback(result);
    }
  }
}

// -- streaming ---------------------------------------------------------------

Error InferenceServerGrpcClient::StartStream(OnCompleteFn callback,
                                             const GrpcHeaders& headers,
                                             GrpcCompression compression) {
  if (callback == nullptr) return Error("callback is required");
  std::lock_guard<std::mutex> lk(stream_mutex_);
  if (stream_active_) return Error("stream already active");
  int32_t sid = 0;
  h2::HeaderList call_headers = CallHeaders(
      authority_, std::string(kServicePrefix) + "ModelStreamInfer", 0,
      headers, conn_->Tls());
  if (compression != GrpcCompression::NONE) {
    // HTTP/2 declares the stream's message coding once, up front; each
    // message's flag byte then says whether THAT message used it.
    call_headers.push_back(
        {"grpc-encoding",
         compression == GrpcCompression::GZIP ? "gzip" : "deflate"});
  }
  Error err = conn_->StartStream(call_headers, false, &sid);
  if (!err.IsOk()) return err;
  stream_compression_ = compression;
  stream_sid_ = sid;
  stream_callback_ = std::move(callback);
  stream_active_ = true;
  stream_exit_ = false;
  stream_worker_ = std::thread([this] { StreamWorker(); });
  return Error::Success();
}

Error InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  int32_t sid;
  {
    std::lock_guard<std::mutex> lk(stream_mutex_);
    if (!stream_active_) return Error("no active stream; call StartStream");
    sid = stream_sid_;
  }
  inference::ModelInferRequest request;
  BuildRequest(options, inputs, outputs, &request);
  std::string payload;
  if (!request.SerializeToString(&payload)) {
    return Error("failed to serialize stream infer request");
  }
  GrpcCompression algo = options.compression_algorithm;
  if (algo != GrpcCompression::NONE && algo != stream_compression_) {
    return Error(
        "stream compression mismatch: pass the algorithm to StartStream "
        "(the stream's grpc-encoding is declared at stream start)");
  }
  std::string body;
  const char* encoding = nullptr;
  Error cerr = BuildInferBody(payload, algo, &body, &encoding);
  if (!cerr.IsOk()) return cerr;
  std::lock_guard<std::mutex> lk(stream_send_mutex_);
  return conn_->SendData(sid, reinterpret_cast<const uint8_t*>(body.data()),
                         body.size(), false,
                         DeadlineNs(options.client_timeout_us));
}

void InferenceServerGrpcClient::StreamWorker() {
  // Reads stream responses in order and fires the user callback per message
  // (reference AsyncStreamTransfer read loop, grpc_client.cc:1271-1315).
  size_t want = 5;  // unconsumed bytes needed before the next scan is useful
  while (true) {
    bool closed = false;
    std::vector<std::string> messages;
    Error terminal = Error::Success();
    // Bounded wait so StopStream's stream_exit_ flag is honored even when
    // the peer never closes; normal wakeups come from the reader's
    // state_cv_ notifications inside WaitStream. `want` grows to the full
    // frame size once a message header is visible, so a partially-received
    // large message blocks here instead of spinning.
    conn_->WaitStream(stream_sid_, want,
                      RequestTimers::Now() + uint64_t(250e6));
    if (stream_exit_.load()) return;
    bool present = conn_->WithStream(
        stream_sid_, [&](h2::Connection::Stream& s) {
          Error perr = Error::Success();
          std::string msg;
          size_t pos = s.consumed;
          while (PopMessage(s.data, &pos, &msg, &perr)) {
            messages.push_back(std::move(msg));
          }
          s.consumed = pos;
          size_t avail = s.data.size() - s.consumed;
          want = 5;
          if (avail >= 5) {
            const uint8_t* p =
                reinterpret_cast<const uint8_t*>(s.data.data()) + s.consumed;
            uint32_t len = (uint32_t(p[1]) << 24) | (uint32_t(p[2]) << 16) |
                           (uint32_t(p[3]) << 8) | uint32_t(p[4]);
            want = 5 + size_t(len);
          }
          // Trim consumed prefix so long-lived streams don't grow without
          // bound.
          if (s.consumed > (1u << 20)) {
            s.data.erase(0, s.consumed);
            s.consumed = 0;
          }
          if (!perr.IsOk()) {
            closed = true;
            terminal = perr;
            return;
          }
          if (s.reset && !s.end_stream) {
            closed = true;
            terminal = Error("gRPC: stream reset (code " +
                             std::to_string(s.reset_code) + ")");
          } else if (s.end_stream) {
            // All complete messages were popped above; anything left is a
            // truncated tail that can never complete.
            closed = true;
            bool have = false;
            terminal = GrpcStatusFromStream(s, &have);
          }
        });
    if (!present) return;
    for (auto& msg : messages) {
      inference::ModelStreamInferResponse stream_response;
      InferResult* result = nullptr;
      if (!stream_response.ParseFromString(msg)) {
        InferResultGrpc::Create(&result, nullptr,
                                Error("failed to parse stream response"));
      } else if (!stream_response.error_message().empty()) {
        // Keep the response proto: the server sets infer_response.id on
        // per-request errors (grpc_server.py), and callers need the id to
        // route the failure to ITS request instead of treating it as a
        // terminal stream loss.
        auto response = std::make_shared<inference::ModelInferResponse>(
            std::move(*stream_response.mutable_infer_response()));
        InferResultGrpc::Create(&result, std::move(response),
                                Error(stream_response.error_message()));
      } else {
        auto response = std::make_shared<inference::ModelInferResponse>(
            std::move(*stream_response.mutable_infer_response()));
        InferResultGrpc::Create(&result, std::move(response));
      }
      stream_callback_(result);
    }
    if (closed) {
      if (!terminal.IsOk() && !stream_exit_.load()) {
        InferResult* result = nullptr;
        InferResultGrpc::Create(&result, nullptr, terminal);
        stream_callback_(result);
      }
      return;
    }
    if (stream_exit_.load()) return;
  }
}

Error InferenceServerGrpcClient::StopStream() {
  int32_t sid;
  {
    std::lock_guard<std::mutex> lk(stream_mutex_);
    if (!stream_active_) return Error::Success();
    sid = stream_sid_;
  }
  // Half-close; the server answers with trailers, the worker drains and
  // exits, then the stream can be dropped.
  {
    std::lock_guard<std::mutex> send_lk(stream_send_mutex_);
    conn_->SendData(sid, nullptr, 0, true);
  }
  uint64_t deadline = RequestTimers::Now() + uint64_t(5e9);
  conn_->WaitStream(sid, SIZE_MAX, deadline);
  stream_exit_ = true;
  if (stream_worker_.joinable()) stream_worker_.join();
  conn_->CloseStream(sid);
  std::lock_guard<std::mutex> lk(stream_mutex_);
  stream_active_ = false;
  stream_callback_ = nullptr;
  stream_sid_ = 0;
  return Error::Success();
}

}  // namespace tpuclient
