#include "tpuclient/json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace tpuclient {

JsonPtr Json::MakeBool(bool v) {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kBool;
  j->bool_ = v;
  return j;
}
JsonPtr Json::MakeInt(int64_t v) {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kInt;
  j->int_ = v;
  return j;
}
JsonPtr Json::MakeUint(uint64_t v) {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kUint;
  j->uint_ = v;
  return j;
}
JsonPtr Json::MakeDouble(double v) {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kDouble;
  j->dbl_ = v;
  return j;
}
JsonPtr Json::MakeString(std::string v) {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kString;
  j->str_ = std::move(v);
  return j;
}
JsonPtr Json::MakeArray() {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kArray;
  return j;
}
JsonPtr Json::MakeObject() {
  auto j = std::make_shared<Json>();
  j->type_ = Type::kObject;
  return j;
}

int64_t Json::AsInt() const {
  switch (type_) {
    case Type::kInt:
      return int_;
    case Type::kUint:
      return static_cast<int64_t>(uint_);
    case Type::kDouble:
      return static_cast<int64_t>(dbl_);
    case Type::kBool:
      return bool_ ? 1 : 0;
    default:
      return 0;
  }
}
uint64_t Json::AsUint() const {
  switch (type_) {
    case Type::kInt:
      return static_cast<uint64_t>(int_);
    case Type::kUint:
      return uint_;
    case Type::kDouble:
      return static_cast<uint64_t>(dbl_);
    case Type::kBool:
      return bool_ ? 1 : 0;
    default:
      return 0;
  }
}
double Json::AsDouble() const {
  switch (type_) {
    case Type::kInt:
      return static_cast<double>(int_);
    case Type::kUint:
      return static_cast<double>(uint_);
    case Type::kDouble:
      return dbl_;
    default:
      return 0.0;
  }
}

JsonPtr Json::Get(const std::string& key) const {
  for (const auto& kv : obj_) {
    if (kv.first == key) return kv.second;
  }
  return nullptr;
}
bool Json::Has(const std::string& key) const { return Get(key) != nullptr; }
void Json::Set(const std::string& key, JsonPtr v) {
  for (auto& kv : obj_) {
    if (kv.first == key) {
      kv.second = std::move(v);
      return;
    }
  }
  obj_.emplace_back(key, std::move(v));
}

// ---------------------------------------------------------------------------
// Serialization
// ---------------------------------------------------------------------------

static void EscapeString(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"':
        out->append("\\\"");
        break;
      case '\\':
        out->append("\\\\");
        break;
      case '\b':
        out->append("\\b");
        break;
      case '\f':
        out->append("\\f");
        break;
      case '\n':
        out->append("\\n");
        break;
      case '\r':
        out->append("\\r");
        break;
      case '\t':
        out->append("\\t");
        break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out->append(buf);
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

void Json::SerializeTo(std::string* out) const {
  char buf[32];
  switch (type_) {
    case Type::kNull:
      out->append("null");
      break;
    case Type::kBool:
      out->append(bool_ ? "true" : "false");
      break;
    case Type::kInt:
      snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(int_));
      out->append(buf);
      break;
    case Type::kUint:
      snprintf(buf, sizeof(buf), "%llu", static_cast<unsigned long long>(uint_));
      out->append(buf);
      break;
    case Type::kDouble: {
      if (std::isfinite(dbl_)) {
        char dbuf[40];
        snprintf(dbuf, sizeof(dbuf), "%.17g", dbl_);
        out->append(dbuf);
      } else {
        out->append("null");  // JSON has no Inf/NaN
      }
      break;
    }
    case Type::kString:
      EscapeString(str_, out);
      break;
    case Type::kArray: {
      out->push_back('[');
      for (size_t i = 0; i < arr_.size(); ++i) {
        if (i) out->push_back(',');
        arr_[i]->SerializeTo(out);
      }
      out->push_back(']');
      break;
    }
    case Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& kv : obj_) {
        if (!first) out->push_back(',');
        first = false;
        EscapeString(kv.first, out);
        out->push_back(':');
        kv.second->SerializeTo(out);
      }
      out->push_back('}');
      break;
    }
  }
}

std::string Json::Serialize() const {
  std::string out;
  out.reserve(256);
  SerializeTo(&out);
  return out;
}

// ---------------------------------------------------------------------------
// Parsing
// ---------------------------------------------------------------------------

namespace {

struct Parser {
  const char* p;
  const char* end;
  std::string err;

  void SkipWs() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool Fail(const std::string& msg) {
    err = msg + " at offset " + std::to_string(p - start);
    return false;
  }

  const char* start;

  bool ParseValue(JsonPtr* out) {
    SkipWs();
    if (p >= end) return Fail("unexpected end of input");
    switch (*p) {
      case '{':
        return ParseObject(out);
      case '[':
        return ParseArray(out);
      case '"': {
        std::string s;
        if (!ParseString(&s)) return false;
        *out = Json::MakeString(std::move(s));
        return true;
      }
      case 't':
        if (end - p >= 4 && memcmp(p, "true", 4) == 0) {
          p += 4;
          *out = Json::MakeBool(true);
          return true;
        }
        return Fail("invalid literal");
      case 'f':
        if (end - p >= 5 && memcmp(p, "false", 5) == 0) {
          p += 5;
          *out = Json::MakeBool(false);
          return true;
        }
        return Fail("invalid literal");
      case 'n':
        if (end - p >= 4 && memcmp(p, "null", 4) == 0) {
          p += 4;
          *out = Json::MakeNull();
          return true;
        }
        return Fail("invalid literal");
      default:
        return ParseNumber(out);
    }
  }

  bool ParseString(std::string* out) {
    ++p;  // opening quote
    out->clear();
    while (p < end) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        ++p;
        return true;
      }
      if (c == '\\') {
        ++p;
        if (p >= end) return Fail("bad escape");
        switch (*p) {
          case '"':
            out->push_back('"');
            break;
          case '\\':
            out->push_back('\\');
            break;
          case '/':
            out->push_back('/');
            break;
          case 'b':
            out->push_back('\b');
            break;
          case 'f':
            out->push_back('\f');
            break;
          case 'n':
            out->push_back('\n');
            break;
          case 'r':
            out->push_back('\r');
            break;
          case 't':
            out->push_back('\t');
            break;
          case 'u': {
            if (end - p < 5) return Fail("bad \\u escape");
            unsigned code = 0;
            for (int i = 1; i <= 4; ++i) {
              char h = p[i];
              code <<= 4;
              if (h >= '0' && h <= '9')
                code |= h - '0';
              else if (h >= 'a' && h <= 'f')
                code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F')
                code |= h - 'A' + 10;
              else
                return Fail("bad \\u escape");
            }
            p += 4;
            // UTF-8 encode (surrogate pairs for completeness)
            if (code >= 0xD800 && code <= 0xDBFF && end - p >= 7 &&
                p[1] == '\\' && p[2] == 'u') {
              unsigned lo = 0;
              bool ok = true;
              for (int i = 3; i <= 6; ++i) {
                char h = p[i];
                lo <<= 4;
                if (h >= '0' && h <= '9')
                  lo |= h - '0';
                else if (h >= 'a' && h <= 'f')
                  lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F')
                  lo |= h - 'A' + 10;
                else {
                  ok = false;
                  break;
                }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                code = 0x10000 + ((code - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            if (code < 0x80) {
              out->push_back(static_cast<char>(code));
            } else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else if (code < 0x10000) {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xF0 | (code >> 18)));
              out->push_back(static_cast<char>(0x80 | ((code >> 12) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default:
            return Fail("bad escape");
        }
        ++p;
      } else {
        out->push_back(static_cast<char>(c));
        ++p;
      }
    }
    return Fail("unterminated string");
  }

  bool ParseNumber(JsonPtr* out) {
    const char* num_start = p;
    bool neg = false;
    bool is_double = false;
    if (p < end && *p == '-') {
      neg = true;
      ++p;
    }
    while (p < end && isdigit(static_cast<unsigned char>(*p))) ++p;
    if (p < end && *p == '.') {
      is_double = true;
      ++p;
      while (p < end && isdigit(static_cast<unsigned char>(*p))) ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      is_double = true;
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      while (p < end && isdigit(static_cast<unsigned char>(*p))) ++p;
    }
    if (p == num_start || (neg && p == num_start + 1))
      return Fail("invalid number");
    std::string text(num_start, p - num_start);
    if (is_double) {
      *out = Json::MakeDouble(strtod(text.c_str(), nullptr));
    } else if (neg) {
      *out = Json::MakeInt(strtoll(text.c_str(), nullptr, 10));
    } else {
      uint64_t v = strtoull(text.c_str(), nullptr, 10);
      if (v <= static_cast<uint64_t>(INT64_MAX)) {
        *out = Json::MakeInt(static_cast<int64_t>(v));
      } else {
        *out = Json::MakeUint(v);
      }
    }
    return true;
  }

  bool ParseArray(JsonPtr* out) {
    ++p;  // '['
    auto arr = Json::MakeArray();
    SkipWs();
    if (p < end && *p == ']') {
      ++p;
      *out = arr;
      return true;
    }
    while (true) {
      JsonPtr v;
      if (!ParseValue(&v)) return false;
      arr->Append(std::move(v));
      SkipWs();
      if (p >= end) return Fail("unterminated array");
      if (*p == ',') {
        ++p;
        continue;
      }
      if (*p == ']') {
        ++p;
        *out = arr;
        return true;
      }
      return Fail("expected ',' or ']'");
    }
  }

  bool ParseObject(JsonPtr* out) {
    ++p;  // '{'
    auto obj = Json::MakeObject();
    SkipWs();
    if (p < end && *p == '}') {
      ++p;
      *out = obj;
      return true;
    }
    while (true) {
      SkipWs();
      if (p >= end || *p != '"') return Fail("expected object key");
      std::string key;
      if (!ParseString(&key)) return false;
      SkipWs();
      if (p >= end || *p != ':') return Fail("expected ':'");
      ++p;
      JsonPtr v;
      if (!ParseValue(&v)) return false;
      obj->Set(key, std::move(v));
      SkipWs();
      if (p >= end) return Fail("unterminated object");
      if (*p == ',') {
        ++p;
        continue;
      }
      if (*p == '}') {
        ++p;
        *out = obj;
        return true;
      }
      return Fail("expected ',' or '}'");
    }
  }
};

}  // namespace

Error Json::Parse(const char* text, size_t len, JsonPtr* out) {
  Parser parser{text, text + len, "", text};
  JsonPtr v;
  if (!parser.ParseValue(&v)) {
    return Error("JSON parse error: " + parser.err, 400);
  }
  parser.SkipWs();
  if (parser.p != parser.end) {
    return Error("JSON parse error: trailing data", 400);
  }
  *out = std::move(v);
  return Error::Success();
}

}  // namespace tpuclient
