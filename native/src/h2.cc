// HTTP/2 + HPACK client transport implementation. See h2.h for design notes.
//
// Protocol references: RFC 7540 (framing, flow control), RFC 7541 (HPACK).
// The Huffman table is generated and cross-verified against libnghttp2 by
// tools/gen_hpack_table.py.

#include "h2.h"

#include "tpuclient/common.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <climits>
#include <cstring>
#include <unordered_map>

namespace tpuclient {
namespace h2 {

namespace {

#include "hpack_huffman.inc"

// Frame types (RFC 7540 §6).
constexpr uint8_t kData = 0x0;
constexpr uint8_t kHeaders = 0x1;
constexpr uint8_t kRstStream = 0x3;
constexpr uint8_t kSettings = 0x4;
constexpr uint8_t kPing = 0x6;
constexpr uint8_t kGoaway = 0x7;
constexpr uint8_t kWindowUpdate = 0x8;
constexpr uint8_t kContinuation = 0x9;

// Flags.
constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

// Settings ids.
constexpr uint16_t kSettingsEnablePush = 0x2;
constexpr uint16_t kSettingsInitialWindowSize = 0x4;
constexpr uint16_t kSettingsMaxFrameSize = 0x5;

constexpr int64_t kOurStreamWindow = 4 << 20;   // INITIAL_WINDOW_SIZE we set
constexpr int64_t kOurConnWindow = 16 << 20;    // connection recv window

// HPACK static table (RFC 7541 Appendix A).
const struct { const char* name; const char* value; } kStaticTable[61] = {
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
};

// (nbits<<32 | code) -> symbol, built lazily once.
const std::unordered_map<uint64_t, uint8_t>& HuffmanReverse() {
  static const std::unordered_map<uint64_t, uint8_t>* map = [] {
    auto* m = new std::unordered_map<uint64_t, uint8_t>();
    m->reserve(256);
    for (int s = 0; s < 256; ++s) {
      (*m)[(uint64_t(kHuffmanTable[s].nbits) << 32) | kHuffmanTable[s].code] =
          uint8_t(s);
    }
    return m;
  }();
  return *map;
}

void PutU32(std::string* out, uint32_t v) {
  out->push_back(char(v >> 24));
  out->push_back(char(v >> 16));
  out->push_back(char(v >> 8));
  out->push_back(char(v));
}

uint32_t GetU32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

void HpackEncodeInt(uint64_t value, int prefix_bits, uint8_t first_flags,
                    std::string* out) {
  uint64_t mask = (1u << prefix_bits) - 1;
  if (value < mask) {
    out->push_back(char(first_flags | value));
    return;
  }
  out->push_back(char(first_flags | mask));
  value -= mask;
  while (value >= 0x80) {
    out->push_back(char(0x80 | (value & 0x7F)));
    value >>= 7;
  }
  out->push_back(char(value));
}

}  // namespace

// ---------------------------------------------------------------- HPACK ----

void HuffmanEncode(const std::string& in, std::string* out) {
  uint64_t acc = 0;
  int nacc = 0;
  for (unsigned char c : in) {
    acc = (acc << kHuffmanTable[c].nbits) | kHuffmanTable[c].code;
    nacc += kHuffmanTable[c].nbits;
    while (nacc >= 8) {
      nacc -= 8;
      out->push_back(char((acc >> nacc) & 0xFF));
    }
  }
  if (nacc > 0) {
    int pad = 8 - nacc;
    out->push_back(char(((acc << pad) | ((1u << pad) - 1)) & 0xFF));
  }
}

Error HuffmanDecode(const uint8_t* data, size_t len, std::string* out) {
  const auto& rev = HuffmanReverse();
  uint64_t acc = 0;
  int nacc = 0;
  for (size_t i = 0; i < len; ++i) {
    acc = (acc << 8) | data[i];
    nacc += 8;
    bool matched = true;
    while (matched && nacc >= 5) {
      matched = false;
      int maxb = nacc < 30 ? nacc : 30;
      for (int nb = 5; nb <= maxb; ++nb) {
        uint64_t code = (acc >> (nacc - nb)) & ((1u << nb) - 1);
        auto it = rev.find((uint64_t(nb) << 32) | code);
        if (it != rev.end()) {
          out->push_back(char(it->second));
          nacc -= nb;
          acc &= (uint64_t(1) << nacc) - 1;
          matched = true;
          break;
        }
      }
    }
    if (nacc > 30) return Error("HPACK: invalid Huffman sequence");
  }
  // Remaining bits must be the EOS-prefix padding: < 8 bits, all ones.
  if (nacc >= 8 || acc != (uint64_t(1) << nacc) - 1) {
    return Error("HPACK: invalid Huffman padding");
  }
  return Error::Success();
}

void HpackEncode(const HeaderList& headers, std::string* out) {
  for (const auto& h : headers) {
    // Literal Header Field without Indexing — New Name (RFC 7541 §6.2.2).
    out->push_back(0x00);
    HpackEncodeInt(h.first.size(), 7, 0x00, out);
    out->append(h.first);
    HpackEncodeInt(h.second.size(), 7, 0x00, out);
    out->append(h.second);
  }
}

Error HpackDecoder::ReadInt(const uint8_t* data, size_t len, size_t* pos,
                            int prefix_bits, uint64_t* value) {
  if (*pos >= len) return Error("HPACK: truncated integer");
  uint64_t mask = (1u << prefix_bits) - 1;
  *value = data[(*pos)++] & mask;
  if (*value < mask) return Error::Success();
  int shift = 0;
  while (true) {
    if (*pos >= len) return Error("HPACK: truncated varint");
    if (shift > 56) return Error("HPACK: integer overflow");
    uint8_t b = data[(*pos)++];
    *value += uint64_t(b & 0x7F) << shift;
    shift += 7;
    if (!(b & 0x80)) return Error::Success();
  }
}

Error HpackDecoder::ReadString(const uint8_t* data, size_t len, size_t* pos,
                               std::string* out) {
  if (*pos >= len) return Error("HPACK: truncated string");
  bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t slen;
  Error err = ReadInt(data, len, pos, 7, &slen);
  if (!err.IsOk()) return err;
  if (*pos + slen > len) return Error("HPACK: string exceeds block");
  if (huffman) {
    err = HuffmanDecode(data + *pos, slen, out);
    if (!err.IsOk()) return err;
  } else {
    out->assign(reinterpret_cast<const char*>(data + *pos), slen);
  }
  *pos += slen;
  return Error::Success();
}

Error HpackDecoder::LookupIndex(uint64_t index, std::string* name,
                                std::string* value) {
  if (index == 0) return Error("HPACK: index 0");
  if (index <= 61) {
    *name = kStaticTable[index - 1].name;
    *value = kStaticTable[index - 1].value;
    return Error::Success();
  }
  size_t di = index - 62;
  if (di >= dynamic_.size()) return Error("HPACK: index out of range");
  *name = dynamic_[di].first;
  *value = dynamic_[di].second;
  return Error::Success();
}

void HpackDecoder::DynamicInsert(const std::string& name,
                                 const std::string& value) {
  dynamic_.emplace_front(name, value);
  dynamic_size_ += name.size() + value.size() + 32;
  EvictToFit();
}

void HpackDecoder::EvictToFit() {
  while (dynamic_size_ > max_dynamic_size_ && !dynamic_.empty()) {
    dynamic_size_ -=
        dynamic_.back().first.size() + dynamic_.back().second.size() + 32;
    dynamic_.pop_back();
  }
}

Error HpackDecoder::Decode(const uint8_t* data, size_t len, HeaderList* out) {
  size_t pos = 0;
  while (pos < len) {
    uint8_t b = data[pos];
    std::string name, value;
    Error err;
    uint64_t index;
    if (b & 0x80) {  // Indexed Header Field (§6.1)
      err = ReadInt(data, len, &pos, 7, &index);
      if (!err.IsOk()) return err;
      err = LookupIndex(index, &name, &value);
      if (!err.IsOk()) return err;
      out->emplace_back(std::move(name), std::move(value));
    } else if (b & 0x40) {  // Literal with Incremental Indexing (§6.2.1)
      err = ReadInt(data, len, &pos, 6, &index);
      if (!err.IsOk()) return err;
      if (index > 0) {
        std::string ignored;
        err = LookupIndex(index, &name, &ignored);
        if (!err.IsOk()) return err;
      } else {
        err = ReadString(data, len, &pos, &name);
        if (!err.IsOk()) return err;
      }
      err = ReadString(data, len, &pos, &value);
      if (!err.IsOk()) return err;
      DynamicInsert(name, value);
      out->emplace_back(std::move(name), std::move(value));
    } else if ((b & 0xE0) == 0x20) {  // Dynamic Table Size Update (§6.3)
      err = ReadInt(data, len, &pos, 5, &index);
      if (!err.IsOk()) return err;
      if (index > configured_max_) {
        return Error("HPACK dynamic table size update " +
                     std::to_string(index) + " exceeds configured limit " +
                     std::to_string(configured_max_));
      }
      max_dynamic_size_ = index;
      EvictToFit();
    } else {  // Literal without Indexing / Never Indexed (§6.2.2/§6.2.3)
      err = ReadInt(data, len, &pos, 4, &index);
      if (!err.IsOk()) return err;
      if (index > 0) {
        std::string ignored;
        err = LookupIndex(index, &name, &ignored);
        if (!err.IsOk()) return err;
      } else {
        err = ReadString(data, len, &pos, &name);
        if (!err.IsOk()) return err;
      }
      err = ReadString(data, len, &pos, &value);
      if (!err.IsOk()) return err;
      out->emplace_back(std::move(name), std::move(value));
    }
  }
  return Error::Success();
}

// ----------------------------------------------------------- connection ----

Connection::~Connection() {
  FailConnection("connection destroyed");
  {
    std::lock_guard<std::mutex> sl(state_mutex_);
    ka_stop_ = true;
  }
  state_cv_.notify_all();
  if (ka_thread_.joinable()) ka_thread_.join();
  if (reader_.joinable()) reader_.join();
  if (tls_) tls_->Close();
  if (fd_ >= 0) ::close(fd_);
}

Error Connection::Connect(const std::string& host, int port,
                          const TlsOptions* tls) {
  struct addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rv = ::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                         &res);
  if (rv != 0) {
    return Error("getaddrinfo(" + host + "): " + gai_strerror(rv));
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0) return Error("failed to connect to " + host);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  fd_ = fd;

  if (tls != nullptr && tls->use_ssl) {
    tls_ = std::make_unique<TlsSession>();
    Error terr = tls_->Handshake(fd_, host, *tls);
    if (!terr.IsOk()) {
      tls_.reset();
      ::close(fd_);
      fd_ = -1;
      return terr;
    }
    // Handshake ran blocking; switch to non-blocking so the reader thread's
    // SSL_read can't camp inside the TLS layer while holding tls_mutex_
    // (reader and writers share one SSL object — see tls.h).
    int fl = ::fcntl(fd_, F_GETFL, 0);
    ::fcntl(fd_, F_SETFL, fl | O_NONBLOCK);
  }

  // Client preface + SETTINGS + connection window bump (RFC 7540 §3.5).
  static const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  std::string settings;
  auto put_setting = [&settings](uint16_t id, uint32_t v) {
    settings.push_back(char(id >> 8));
    settings.push_back(char(id));
    PutU32(&settings, v);
  };
  put_setting(kSettingsEnablePush, 0);
  put_setting(kSettingsInitialWindowSize, uint32_t(kOurStreamWindow));
  std::lock_guard<std::mutex> wl(write_mutex_);
  Error err = SendRaw(reinterpret_cast<const uint8_t*>(kPreface),
                      sizeof(kPreface) - 1);
  if (!err.IsOk()) return err;
  err = SendFrame(kSettings, 0, 0,
                  reinterpret_cast<const uint8_t*>(settings.data()),
                  settings.size());
  if (!err.IsOk()) return err;
  std::string wu;
  PutU32(&wu, uint32_t(kOurConnWindow - 65535));
  err = SendFrame(kWindowUpdate, 0, 0,
                  reinterpret_cast<const uint8_t*>(wu.data()), wu.size());
  if (!err.IsOk()) return err;

  reader_ = std::thread([this] { ReaderLoop(); });
  return Error::Success();
}

Error Connection::SendRaw(const uint8_t* data, size_t len) {
  size_t off = 0;
  while (off < len) {
    ssize_t n;
    if (tls_) {
      Error terr;
      {
        std::lock_guard<std::mutex> tl(tls_mutex_);
        n = tls_->Write(data + off, len - off, &terr);
      }
      if (n == TlsSession::kWantWrite || n == TlsSession::kWantRead) {
        // Non-blocking fd: wait for socket readiness outside the TLS lock.
        struct pollfd pfd{fd_,
                          short(n == TlsSession::kWantWrite ? POLLOUT
                                                            : POLLIN),
                          0};
        ::poll(&pfd, 1, 1000);
        continue;
      }
      if (n <= 0) return terr.IsOk() ? Error("h2 TLS send closed") : terr;
    } else {
      n = ::send(fd_, data + off, len - off, MSG_NOSIGNAL);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        return Error("h2 send failed: " +
                     std::string(n < 0 ? strerror(errno) : "closed"));
      }
    }
    off += size_t(n);
  }
  return Error::Success();
}

Error Connection::SendFrame(uint8_t type, uint8_t flags, int32_t sid,
                            const uint8_t* payload, size_t len) {
  uint8_t hdr[9];
  hdr[0] = uint8_t(len >> 16);
  hdr[1] = uint8_t(len >> 8);
  hdr[2] = uint8_t(len);
  hdr[3] = type;
  hdr[4] = flags;
  hdr[5] = uint8_t(uint32_t(sid) >> 24) & 0x7F;
  hdr[6] = uint8_t(uint32_t(sid) >> 16);
  hdr[7] = uint8_t(uint32_t(sid) >> 8);
  hdr[8] = uint8_t(uint32_t(sid));
  Error err = SendRaw(hdr, 9);
  if (!err.IsOk()) return err;
  if (len > 0) return SendRaw(payload, len);
  return Error::Success();
}

Error Connection::StartStream(const HeaderList& headers, bool end_stream,
                              int32_t* sid) {
  std::string block;
  HpackEncode(headers, &block);
  {
    std::lock_guard<std::mutex> sl(state_mutex_);
    ka_data_since_ping_ = true;  // HEADERS counts against the ping cap
  }

  // Hold the write lock across id allocation + HEADERS so stream ids appear
  // on the wire in increasing order (RFC 7540 §5.1.1).
  std::lock_guard<std::mutex> wl(write_mutex_);
  size_t max_frame;
  {
    std::lock_guard<std::mutex> sl(state_mutex_);
    if (dead_) return Error("h2 connection dead: " + error_);
    auto stream = std::make_shared<Stream>();
    stream->id = next_stream_id_;
    next_stream_id_ += 2;
    stream->send_window = peer_initial_window_;
    streams_[stream->id] = stream;
    *sid = stream->id;
    max_frame = peer_max_frame_;
  }
  uint8_t flags = kFlagEndHeaders | (end_stream ? kFlagEndStream : 0);
  if (block.size() <= max_frame) {
    return SendFrame(kHeaders, flags, *sid,
                     reinterpret_cast<const uint8_t*>(block.data()),
                     block.size());
  }
  // Oversized header block: HEADERS + CONTINUATION chain (must be contiguous
  // on the wire — we are still under the write lock).
  size_t off = 0;
  Error err = SendFrame(kHeaders, flags & ~kFlagEndHeaders, *sid,
                        reinterpret_cast<const uint8_t*>(block.data()),
                        max_frame);
  if (!err.IsOk()) return err;
  off = max_frame;
  while (off < block.size()) {
    size_t n = std::min(max_frame, block.size() - off);
    bool last = off + n == block.size();
    err = SendFrame(kContinuation, last ? kFlagEndHeaders : 0, *sid,
                    reinterpret_cast<const uint8_t*>(block.data()) + off, n);
    if (!err.IsOk()) return err;
    off += n;
  }
  return Error::Success();
}

Error Connection::SendData(int32_t sid, const uint8_t* data, size_t len,
                           bool end_stream, uint64_t deadline_ns) {
  {
    std::unique_lock<std::mutex> sl(state_mutex_);
    ka_data_since_ping_ = true;
    // Wait (bounded) for the server's initial SETTINGS before the first
    // DATA bytes: RFC 7540 doesn't require it, but sending a large body
    // chunked at the 16384 default while the server's max_frame/window
    // SETTINGS race down the pipe wastes frames — and the server's first
    // frame after the preface MUST be SETTINGS (§3.5), so this costs at
    // most one in-flight latency, once per connection.
    if (!peer_settings_received_ && !dead_) {
      // Capped by the caller's deadline: a short client timeout must not
      // stretch to the 5s settings-wait ceiling.
      uint64_t now = uint64_t(std::chrono::duration_cast<
                                  std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch()).count());
      uint64_t cap = now + uint64_t(5e9);
      if (deadline_ns != 0 && deadline_ns < cap) cap = deadline_ns;
      if (cap > now) {
        state_cv_.wait_for(sl, std::chrono::nanoseconds(cap - now), [&] {
          return peer_settings_received_ || dead_;
        });
      }
    }
  }
  size_t off = 0;
  while (off < len || (end_stream && off == 0 && len == 0)) {
    size_t chunk = 0;
    size_t max_frame;
    {
      std::unique_lock<std::mutex> sl(state_mutex_);
      auto pred = [&] {
        if (dead_) return true;
        auto it = streams_.find(sid);
        if (it == streams_.end() || it->second->reset) return true;
        return len == off ||
               (conn_send_window_ > 0 && it->second->send_window > 0);
      };
      if (deadline_ns > 0) {
        auto dl = std::chrono::steady_clock::time_point(
            std::chrono::nanoseconds(deadline_ns));
        if (!state_cv_.wait_until(sl, dl, pred)) {
          return Error("h2 send: flow-control deadline exceeded", 499);
        }
      } else {
        state_cv_.wait(sl, pred);
      }
      if (dead_) return Error("h2 connection dead: " + error_);
      auto it = streams_.find(sid);
      if (it == streams_.end()) return Error("h2 send on closed stream");
      if (it->second->reset) {
        return Error("h2 stream reset by peer (code " +
                     std::to_string(it->second->reset_code) + ")");
      }
      if (len > off) {
        chunk = std::min({len - off, size_t(conn_send_window_),
                          size_t(it->second->send_window), peer_max_frame_});
        conn_send_window_ -= int64_t(chunk);
        it->second->send_window -= int64_t(chunk);
      }
      max_frame = peer_max_frame_;
      (void)max_frame;
    }
    bool last = end_stream && off + chunk == len;
    std::lock_guard<std::mutex> wl(write_mutex_);
    Error err = SendFrame(kData, last ? kFlagEndStream : 0, sid, data + off,
                          chunk);
    if (!err.IsOk()) return err;
    off += chunk;
    if (last) break;
  }
  return Error::Success();
}

bool Connection::WaitStream(int32_t sid, size_t min_bytes,
                            uint64_t deadline_ns) {
  std::unique_lock<std::mutex> sl(state_mutex_);
  auto pred = [&] {
    if (dead_) return true;
    auto it = streams_.find(sid);
    if (it == streams_.end()) return true;
    const Stream& s = *it->second;
    return s.reset || s.end_stream ||
           s.data.size() - s.consumed >= min_bytes;
  };
  if (deadline_ns > 0) {
    auto dl = std::chrono::steady_clock::time_point(
        std::chrono::nanoseconds(deadline_ns));
    return state_cv_.wait_until(sl, dl, pred);
  }
  state_cv_.wait(sl, pred);
  return true;
}

bool Connection::WithStream(int32_t sid,
                            const std::function<void(Stream&)>& fn) {
  std::lock_guard<std::mutex> sl(state_mutex_);
  auto it = streams_.find(sid);
  if (it == streams_.end()) return false;
  fn(*it->second);
  return true;
}

void Connection::CloseStream(int32_t sid) {
  bool need_rst = false;
  {
    std::lock_guard<std::mutex> sl(state_mutex_);
    auto it = streams_.find(sid);
    if (it == streams_.end()) return;
    need_rst = !it->second->end_stream && !it->second->reset && !dead_;
    streams_.erase(it);
  }
  if (need_rst) {
    std::string payload;
    PutU32(&payload, 0x8);  // CANCEL
    std::lock_guard<std::mutex> wl(write_mutex_);
    SendFrame(kRstStream, 0, sid,
              reinterpret_cast<const uint8_t*>(payload.data()),
              payload.size());
  }
  state_cv_.notify_all();
}

bool Connection::Alive() {
  std::lock_guard<std::mutex> sl(state_mutex_);
  return !dead_;
}

void Connection::StartKeepalive(int time_ms, int timeout_ms,
                                bool permit_without_calls,
                                int max_pings_without_data) {
  if (time_ms <= 0 || time_ms == INT_MAX) return;
  {
    // Idempotent + thread-safe: cached channels may be adopted by several
    // clients; the first keepalive-requesting one starts the ping thread.
    std::lock_guard<std::mutex> sl(state_mutex_);
    if (ka_started_ || dead_) return;
    ka_started_ = true;
  }
  ka_thread_ = std::thread([this, time_ms, timeout_ms, permit_without_calls,
                            max_pings_without_data] {
    std::unique_lock<std::mutex> sl(state_mutex_);
    while (true) {
      state_cv_.wait_for(sl, std::chrono::milliseconds(time_ms),
                         [this] { return ka_stop_ || dead_; });
      if (ka_stop_ || dead_) return;
      if (!permit_without_calls && streams_.empty()) continue;
      if (ka_data_since_ping_) {
        ka_pings_without_data_ = 0;
      } else if (max_pings_without_data > 0 &&
                 ka_pings_without_data_ >= max_pings_without_data) {
        continue;  // gRPC-core: stop pinging an idle transport at the cap
      }
      ka_data_since_ping_ = false;
      ka_pings_without_data_++;
      ka_ack_pending_ = true;
      sl.unlock();
      {
        std::lock_guard<std::mutex> wl(write_mutex_);
        static const uint8_t kKaPayload[8] = {'k', 'e', 'e', 'p',
                                              'a', 'l', 'v', '1'};
        SendFrame(kPing, 0, 0, kKaPayload, sizeof(kKaPayload));
      }
      sl.lock();
      bool acked = state_cv_.wait_for(
          sl, std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 20000),
          [this] { return !ka_ack_pending_ || ka_stop_ || dead_; });
      if (ka_stop_ || dead_) return;
      if (!acked) {
        sl.unlock();
        FailConnection("keepalive ping timeout");
        return;
      }
    }
  });
}

const std::string& Connection::ConnectionError() {
  std::lock_guard<std::mutex> sl(state_mutex_);
  return error_;
}

bool Connection::ReadN(uint8_t* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r;
    if (tls_) {
      {
        std::lock_guard<std::mutex> tl(tls_mutex_);
        r = tls_->Read(buf + off, n - off, nullptr);
      }
      if (r == TlsSession::kWantRead || r == TlsSession::kWantWrite) {
        struct pollfd pfd{fd_,
                          short(r == TlsSession::kWantRead ? POLLIN
                                                           : POLLOUT),
                          0};
        ::poll(&pfd, 1, 1000);
        {
          std::lock_guard<std::mutex> sl(state_mutex_);
          if (dead_) return false;
        }
        continue;
      }
    } else {
      r = ::recv(fd_, buf + off, n - off, 0);
      if (r < 0 && errno == EINTR) continue;
    }
    if (r <= 0) return false;
    off += size_t(r);
  }
  return true;
}

void Connection::ReaderLoop() {
  std::vector<uint8_t> payload;
  while (true) {
    uint8_t hdr[9];
    if (!ReadN(hdr, 9)) {
      FailConnection("connection closed by peer");
      return;
    }
    size_t len = (size_t(hdr[0]) << 16) | (size_t(hdr[1]) << 8) | hdr[2];
    uint8_t type = hdr[3];
    uint8_t flags = hdr[4];
    int32_t sid = int32_t(GetU32(hdr + 5) & 0x7FFFFFFF);
    if (len > (32u << 20)) {
      FailConnection("oversized frame from peer");
      return;
    }
    payload.resize(len);
    if (len > 0 && !ReadN(payload.data(), len)) {
      FailConnection("connection closed mid-frame");
      return;
    }
    HandleFrame(type, flags, sid, payload.data(), len);
    {
      std::lock_guard<std::mutex> sl(state_mutex_);
      if (dead_) return;
    }
  }
}

void Connection::HandleFrame(uint8_t type, uint8_t flags, int32_t sid,
                             const uint8_t* payload, size_t len) {
  // RFC 7540 §4.3: a header block (HEADERS .. CONTINUATIONs) is a single
  // unit; any other frame interleaved before END_HEADERS is a connection
  // error. Silently accepting one would desync the shared HPACK decoder.
  if (continuation_sid_ != 0 &&
      (type != kContinuation || sid != continuation_sid_)) {
    return FailConnection("frame interleaved inside a header block");
  }
  switch (type) {
    case kData: {
      size_t off = 0, dlen = len;
      if (flags & kFlagPadded) {
        if (len < 1) return FailConnection("bad padded DATA");
        size_t pad = payload[0];
        if (pad + 1 > len) return FailConnection("bad DATA padding");
        off = 1;
        dlen = len - 1 - pad;
      }
      {
        std::lock_guard<std::mutex> sl(state_mutex_);
        auto it = streams_.find(sid);
        if (it != streams_.end()) {
          it->second->data.append(reinterpret_cast<const char*>(payload + off),
                                  dlen);
          if (flags & kFlagEndStream) it->second->end_stream = true;
          if (it->second->on_event) it->second->on_event();
        }
      }
      state_cv_.notify_all();
      // Replenish flow-control windows by the full frame length (padding
      // counts, RFC 7540 §6.9.1).
      if (len > 0) {
        std::string wu;
        PutU32(&wu, uint32_t(len));
        std::lock_guard<std::mutex> wl(write_mutex_);
        SendFrame(kWindowUpdate, 0, 0,
                  reinterpret_cast<const uint8_t*>(wu.data()), wu.size());
        if (!(flags & kFlagEndStream)) {
          SendFrame(kWindowUpdate, 0, sid,
                    reinterpret_cast<const uint8_t*>(wu.data()), wu.size());
        }
      }
      break;
    }
    case kHeaders: {
      size_t off = 0, blen = len;
      if (flags & kFlagPadded) {
        if (len < 1) return FailConnection("bad padded HEADERS");
        size_t pad = payload[0];
        off = 1;
        if (1 + pad > len) return FailConnection("bad HEADERS padding");
        blen = len - 1 - pad;
      }
      if (flags & kFlagPriority) {
        if (blen < 5) return FailConnection("bad HEADERS priority");
        off += 5;
        blen -= 5;
      }
      continuation_sid_ = sid;
      continuation_buf_.assign(reinterpret_cast<const char*>(payload + off),
                               blen);
      continuation_end_stream_ = (flags & kFlagEndStream) != 0;
      if (flags & kFlagEndHeaders) {
        HeaderList fields;
        Error err = hpack_.Decode(
            reinterpret_cast<const uint8_t*>(continuation_buf_.data()),
            continuation_buf_.size(), &fields);
        if (!err.IsOk()) return FailConnection("HPACK error: " + err.Message());
        std::lock_guard<std::mutex> sl(state_mutex_);
        auto it = streams_.find(sid);
        if (it != streams_.end()) {
          Stream& s = *it->second;
          if (!s.headers_done) {
            s.headers = std::move(fields);
            s.headers_done = true;
          } else {
            s.trailers = std::move(fields);
          }
          if (continuation_end_stream_) s.end_stream = true;
          if (s.on_event) s.on_event();
        }
        continuation_sid_ = 0;
        state_cv_.notify_all();
      }
      break;
    }
    case kContinuation: {
      if (sid != continuation_sid_) {
        return FailConnection("CONTINUATION for wrong stream");
      }
      continuation_buf_.append(reinterpret_cast<const char*>(payload), len);
      if (flags & kFlagEndHeaders) {
        HeaderList fields;
        Error err = hpack_.Decode(
            reinterpret_cast<const uint8_t*>(continuation_buf_.data()),
            continuation_buf_.size(), &fields);
        if (!err.IsOk()) return FailConnection("HPACK error: " + err.Message());
        std::lock_guard<std::mutex> sl(state_mutex_);
        auto it = streams_.find(sid);
        if (it != streams_.end()) {
          Stream& s = *it->second;
          if (!s.headers_done) {
            s.headers = std::move(fields);
            s.headers_done = true;
          } else {
            s.trailers = std::move(fields);
          }
          if (continuation_end_stream_) s.end_stream = true;
          if (s.on_event) s.on_event();
        }
        continuation_sid_ = 0;
        state_cv_.notify_all();
      }
      break;
    }
    case kRstStream: {
      if (len < 4) return FailConnection("bad RST_STREAM");
      std::lock_guard<std::mutex> sl(state_mutex_);
      auto it = streams_.find(sid);
      if (it != streams_.end()) {
        it->second->reset = true;
        it->second->reset_code = GetU32(payload);
        if (it->second->on_event) it->second->on_event();
      }
      state_cv_.notify_all();
      break;
    }
    case kSettings: {
      if (flags & kFlagAck) break;
      {
        std::lock_guard<std::mutex> sl(state_mutex_);
        peer_settings_received_ = true;
      }
      {
        // The peer may keep enforcing its PREVIOUS limits until it
        // receives our ACK (RFC 7540 §6.5.3) — grpc-core does exactly
        // that for max_frame_size. So the ACK must hit the wire before
        // any frame sized under the new values: hold the write lock
        // across the state update + ACK, so a sender that observed the
        // updated settings cannot acquire the write lock (and thus reach
        // the wire) until the ACK is out. Lock order (write -> state)
        // matches StartStream.
        std::lock_guard<std::mutex> wl(write_mutex_);
        {
          std::lock_guard<std::mutex> sl(state_mutex_);
          for (size_t p = 0; p + 6 <= len; p += 6) {
            uint16_t id = (uint16_t(payload[p]) << 8) | payload[p + 1];
            uint32_t value = GetU32(payload + p + 2);
            if (id == kSettingsInitialWindowSize) {
              int64_t delta = int64_t(value) - peer_initial_window_;
              peer_initial_window_ = value;
              for (auto& kv : streams_) kv.second->send_window += delta;
            } else if (id == kSettingsMaxFrameSize) {
              peer_max_frame_ = value;
            }
          }
        }
        SendFrame(kSettings, kFlagAck, 0, nullptr, 0);
      }
      state_cv_.notify_all();
      break;
    }
    case kPing: {
      if (flags & kFlagAck) {
        {
          std::lock_guard<std::mutex> sl(state_mutex_);
          ka_ack_pending_ = false;
        }
        state_cv_.notify_all();
      } else if (len == 8) {
        std::lock_guard<std::mutex> wl(write_mutex_);
        SendFrame(kPing, kFlagAck, 0, payload, len);
      }
      break;
    }
    case kWindowUpdate: {
      if (len < 4) return FailConnection("bad WINDOW_UPDATE");
      uint32_t inc = GetU32(payload) & 0x7FFFFFFF;
      std::lock_guard<std::mutex> sl(state_mutex_);
      if (sid == 0) {
        conn_send_window_ += inc;
      } else {
        auto it = streams_.find(sid);
        if (it != streams_.end()) it->second->send_window += inc;
      }
      state_cv_.notify_all();
      break;
    }
    case kGoaway: {
      std::string debug;
      if (len > 8) {
        debug.assign(reinterpret_cast<const char*>(payload + 8), len - 8);
      }
      FailConnection("GOAWAY from peer" +
                     (debug.empty() ? std::string()
                                    : ": " + SanitizeForLog(debug)));
      break;
    }
    default:
      break;  // PRIORITY / PUSH_PROMISE / unknown: ignore
  }
}

void Connection::FailConnection(const std::string& reason) {
  {
    std::lock_guard<std::mutex> sl(state_mutex_);
    if (dead_) return;
    dead_ = true;
    error_ = reason;
    for (auto& kv : streams_) {
      kv.second->reset = true;
      kv.second->reset_code = 0xFFFFFFFF;
      if (kv.second->on_event) kv.second->on_event();
    }
  }
  state_cv_.notify_all();
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

}  // namespace h2
}  // namespace tpuclient
